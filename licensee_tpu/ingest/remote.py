"""Remote HTTP(S) blob sources: scan release artifacts where they live.

Manifest entries may address containers by URL through the same ``::``
grammar as local paths (``https://host/release.tar.gz::*``,
``https://host/src.zip::member``) — members stream straight off the
forge into the featurize lane, the container never lands on disk:

* **zip** — central directory via zipfile over a ranged-window file
  view (a tail read plus whatever blocks the directory spans), then
  per-member ranged GETs of the local record span, decompressed and
  CRC-checked on the host.
* **uncompressed tar** — one ranged metadata scan (tarfile walks the
  512-byte headers through the same block view, seeking past data),
  then per-member ranged GETs by ``offset_data``.
* **compressed tar** (``.tar.gz`` and friends) — no random access
  exists inside the stream, so metadata and reads ride forward-only
  streaming GETs through the PR 15 sequential-window reader (one
  stream per stripe span, wanted members cached as the walk passes).

The perf core is a **pipelined prefetch window**: the expansion's
``want()`` registrations give each container its span's read schedule
up front, adjacent small members **coalesce** into one ranged read
(split on the host — a thousand tiny LICENSE files must not pay a
thousand round trips), and a bounded window of coalesced requests is
kept in flight over keep-alive connection pools so per-request RTT
hides behind featurize instead of serializing with it
(``details.ingest.remote`` in bench.py prices this with injected
latency).  Knobs (env): ``LICENSEE_TPU_REMOTE_READAHEAD`` (in-flight
requests, default 8; 1 = no overlap), ``LICENSEE_TPU_REMOTE_COALESCE_KB``
(max coalesced span, default 1024), ``LICENSEE_TPU_REMOTE_GAP_KB``
(max dead bytes fetched between coalesced members, default 16).

The failure model is part of the contract:

* **retry/backoff budget** — timeouts, connection drops, torn bodies
  (fewer bytes than Content-Length), and 5xx answers retry with
  capped exponential backoff on a monotonic clock, bounded per read
  (``LICENSEE_TPU_REMOTE_RETRIES``, default 4) and cumulatively per
  container (``LICENSEE_TPU_REMOTE_RETRY_CAP``, default 64); budget
  exhaustion raises :class:`RemoteRetryBudgetError` — the container
  fails CLOSED like a torn gzip, never a silent partial scan.
* **mid-job rewrite fencing** — ETag/Last-Modified/Content-Length are
  captured at expansion, folded into the expansion fingerprint (so a
  republished artifact refuses to RESUME via the existing sidecar
  check), and re-validated on every read: ranged GETs carry
  ``If-Range`` (a changed artifact answers 200-full-body, detected and
  refused), streaming GETs carry ``If-Match`` (412 on change) — a
  republish mid-job raises :class:`RemoteChangedError` instead of
  mixing old rows with new bytes.
* **submit-time probing** — :func:`probe_remote` is the cheap
  HEAD + 1-byte ranged GET the jobs tier runs at ``validate_spec``
  time, so an unreachable URL or a server without Range support is a
  400 at submit, not a mid-job stripe crash.

Expansion stays deterministic and metadata-only, so everything
downstream is unchanged: expanded-count striping splits a remote
million-member tarball across ``--stripes`` × hosts ×
``--featurize-procs`` exactly like a local one, and the picklable
descriptor re-opens remote readers (fresh probes, fresh pools) in
every worker process, fingerprint-gated against a mid-job republish.

git-over-HTTP is refused at expansion (publish a tar/zip artifact);
object-store schemes can join behind the same seam later.
"""

from __future__ import annotations

import os
import threading
import time
import zlib

from licensee_tpu.ingest import OVERSIZED, SkippedBlob
from licensee_tpu.ingest.sources import (
    _COMPRESSED_TAR_SUFFIXES,
    _SeqTarContainer,
    IngestError,
)
from licensee_tpu.projects.git_project import MAX_LICENSE_SIZE


class RemoteError(IngestError):
    """A remote container that cannot be fetched safely."""


class RemoteProbeError(RemoteError):
    """The submit-time probe failed: unreachable, non-2xx, or the
    server cannot answer byte-range requests for a ranged kind."""


class RemoteChangedError(RemoteError):
    """The artifact changed under a running job (ETag/Last-Modified/
    Content-Length no longer match what expansion captured) — the scan
    refuses to mix bytes from two publishes."""


class RemoteRetryBudgetError(RemoteError):
    """The per-read or per-container retry budget is exhausted — the
    container fails closed like a torn local archive."""


class _Transient(Exception):
    """Internal: a retryable fetch failure (timeout, dropped
    connection, torn body, 5xx)."""


# -- knobs (read once per container, overridable per instance) --------

def _env_int(name: str, default: int, lo: int = 0) -> int:
    try:
        return max(lo, int(os.environ.get(name, "") or default))
    except ValueError:
        return default


def _knobs() -> dict:
    return {
        "readahead": _env_int("LICENSEE_TPU_REMOTE_READAHEAD", 8, lo=1),
        "coalesce_bytes": _env_int(
            "LICENSEE_TPU_REMOTE_COALESCE_KB", 1024, lo=1
        ) * 1024,
        "coalesce_gap": _env_int(
            "LICENSEE_TPU_REMOTE_GAP_KB", 16, lo=0
        ) * 1024,
        "retries": _env_int("LICENSEE_TPU_REMOTE_RETRIES", 4),
        "retry_cap": _env_int("LICENSEE_TPU_REMOTE_RETRY_CAP", 64),
        "backoff_ms": _env_int("LICENSEE_TPU_REMOTE_BACKOFF_MS", 100),
        "timeout_s": _env_int("LICENSEE_TPU_REMOTE_TIMEOUT_S", 20, lo=1),
    }


# -- metrics (lazy: the registry import stays off the manifest-scan
# path until a remote container actually opens) -----------------------

_METRICS = None
_METRICS_LOCK = threading.Lock()


def _metrics() -> dict:
    global _METRICS
    if _METRICS is None:
        with _METRICS_LOCK:
            if _METRICS is None:
                from licensee_tpu.obs import get_registry

                reg = get_registry()
                _METRICS = {
                    "requests": reg.counter(
                        "ingest_remote_requests_total",
                        "Remote-source HTTP requests by kind "
                        "(ranged/stream/probe)",
                        labels=("kind",),
                    ),
                    "retries": reg.counter(
                        "ingest_remote_retries_total",
                        "Remote fetches retried after a transient "
                        "failure (timeout, drop, torn body, 5xx)",
                    ),
                    "bytes": reg.counter(
                        "ingest_remote_bytes_total",
                        "Response body bytes fetched from remote "
                        "sources",
                    ),
                    "readahead": reg.gauge(
                        "ingest_remote_readahead",
                        "Prefetch-window occupancy: coalesced ranged "
                        "reads currently in flight",
                    ),
                }
    return _METRICS


def _split_url(url: str):
    from urllib.parse import urlsplit

    parts = urlsplit(url)
    scheme = parts.scheme.lower()
    if scheme not in ("http", "https") or not parts.hostname:
        raise RemoteError(f"unsupported remote url {url!r}")
    port = parts.port or (443 if scheme == "https" else 80)
    target = parts.path or "/"
    if parts.query:
        target = f"{target}?{parts.query}"
    return scheme, parts.hostname, port, target


def remote_entry_kind(container: str) -> str | None:
    """The remote container kind for a manifest container path, or
    None when it is not an HTTP(S) URL: ``rtar`` (ranged uncompressed
    tar), ``rctar`` (streaming compressed tar), ``rzip`` (ranged zip),
    ``rgit`` (recognized but refused)."""
    low = container.lower()
    if not (low.startswith("http://") or low.startswith("https://")):
        return None
    base = low.split("?", 1)[0].split("#", 1)[0]
    if base.endswith(_COMPRESSED_TAR_SUFFIXES):
        return "rctar"
    if base.endswith(".tar"):
        return "rtar"
    if base.endswith(".zip"):
        return "rzip"
    if base.endswith(".git"):
        return "rgit"
    return None


# -- connection pool ---------------------------------------------------


class _HostPool:
    """A small bounded pool of keep-alive connections to one origin.
    ``acquire`` hands out a parked connection (or dials a fresh one);
    ``release`` parks it for reuse; ``discard`` closes it.  Every
    caller must do exactly one of release/discard on every path."""

    def __init__(self, scheme: str, host: str, port: int,
                 timeout_s: float, size: int = 8):
        self._scheme = scheme
        self._host = host
        self._port = port
        self._timeout_s = timeout_s
        self._size = size
        self._idle: list = []
        self._lock = threading.Lock()
        self._closed = False

    def _dial(self):
        import http.client

        if self._scheme == "https":
            return http.client.HTTPSConnection(
                self._host, self._port, timeout=self._timeout_s
            )
        return http.client.HTTPConnection(
            self._host, self._port, timeout=self._timeout_s
        )

    def acquire(self) -> tuple:
        """``(conn, parked)`` — parked connections may be stale (the
        server closed an idle keep-alive); a request failure on a
        parked connection earns one free fresh-dial retry."""
        with self._lock:
            if self._idle:
                return self._idle.pop(), True
        return self._dial(), False

    def release(self, conn) -> None:
        with self._lock:
            if not self._closed and len(self._idle) < self._size:
                self._idle.append(conn)
                return
        conn.close()

    def discard(self, conn) -> None:
        conn.close()

    def close(self) -> None:
        with self._lock:
            idle, self._idle = self._idle, []
            self._closed = True
        for conn in idle:
            conn.close()


# -- the one remote artifact -------------------------------------------


class _RemoteSource:
    """One remote artifact: validators captured at open, a keep-alive
    pool, the retry/backoff budget, and the fetch primitives the
    container readers share."""

    def __init__(self, url: str, *, require_range: bool, knobs=None):
        self.url = url
        k = knobs or _knobs()
        self.retries = k["retries"]
        self.retry_cap = k["retry_cap"]
        self.backoff_s = k["backoff_ms"] / 1000.0
        self.backoff_cap_s = min(30.0, max(self.backoff_s, 1.0) * 16)
        self.timeout_s = float(k["timeout_s"])
        self.readahead = k["readahead"]
        self.coalesce_bytes = k["coalesce_bytes"]
        self.coalesce_gap = k["coalesce_gap"]
        scheme, host, port, target = _split_url(url)
        self._target = target
        self.pool = _HostPool(
            scheme, host, port, self.timeout_s,
            size=max(2, self.readahead),
        )
        self._retries_used = 0
        self._lock = threading.Lock()
        info = self._probe(require_range=require_range)
        self.size = info["size"]
        self.etag = info["etag"]
        self.last_modified = info["last_modified"]

    # -- plumbing ------------------------------------------------------

    def _request_once(self, method: str, headers: dict, kind: str):
        """One request/response over the pool; answers
        ``(status, header_dict, body)`` with the body fully read and
        the connection parked for reuse.  A stale parked keep-alive
        (dies before the status line) earns one free fresh dial."""
        import http.client
        import socket

        for attempt in (0, 1):
            conn, parked = self.pool.acquire()
            try:
                conn.request(method, self._target, headers=headers)
                resp = conn.getresponse()
            except (http.client.HTTPException, OSError) as exc:
                self.pool.discard(conn)
                if parked and attempt == 0:
                    continue  # free retry: the park was stale
                raise _Transient(f"{method} {self.url}: {exc}") from exc
            try:
                try:
                    body = resp.read()
                except (
                    http.client.HTTPException, socket.timeout, OSError,
                ) as exc:
                    self.pool.discard(conn)
                    conn = None
                    raise _Transient(
                        f"{method} {self.url}: body: {exc}"
                    ) from exc
                hdrs = {k.lower(): v for k, v in resp.getheaders()}
                clen = hdrs.get("content-length")
                if (
                    method != "HEAD" and clen is not None
                    and clen.isdigit() and len(body) != int(clen)
                ):
                    # a torn body the transport did not catch
                    self.pool.discard(conn)
                    conn = None
                    raise _Transient(
                        f"{method} {self.url}: torn body "
                        f"({len(body)} of {clen} bytes)"
                    )
            finally:
                if conn is not None:
                    if resp.will_close:
                        self.pool.discard(conn)
                    else:
                        self.pool.release(conn)
            m = _metrics()
            m["requests"].labels(kind=kind).inc()
            m["bytes"].inc(len(body))
            return resp.status, hdrs, body
        raise AssertionError("unreachable")

    def _with_retries(self, fn, what: str):
        """Capped exponential backoff on a monotonic clock, bounded
        per read AND cumulatively per container; exhaustion fails the
        container closed."""
        attempt = 0
        delay = self.backoff_s
        deadline = time.monotonic() + self.timeout_s * (self.retries + 2)
        while True:
            try:
                return fn()
            except _Transient as exc:
                attempt += 1
                with self._lock:
                    self._retries_used += 1
                    used = self._retries_used
                _metrics()["retries"].inc()
                if (
                    attempt > self.retries
                    or used > self.retry_cap
                    or time.monotonic() > deadline
                ):
                    raise RemoteRetryBudgetError(
                        f"{what}: retry budget exhausted after "
                        f"{attempt - 1} retries "
                        f"({used}/{self.retry_cap} container-wide): "
                        f"{exc}"
                    ) from exc
                time.sleep(delay)
                delay = min(delay * 2, self.backoff_cap_s)

    def _probe(self, require_range: bool) -> dict:
        """HEAD for reachability + validators, then a 1-byte ranged
        GET when the kind needs random access — a server that ignores
        Range (200) is refused HERE, not mid-job."""

        def head():
            status, hdrs, _ = self._request_once("HEAD", {}, "probe")
            if status in (500, 502, 503, 504):
                raise _Transient(f"HEAD {self.url}: {status}")
            return status, hdrs

        status, hdrs = self._with_retries(head, f"probe {self.url}")
        if status != 200:
            raise RemoteProbeError(
                f"remote source {self.url!r} answered {status} to HEAD"
            )
        clen = hdrs.get("content-length")
        size = int(clen) if clen is not None and clen.isdigit() else None
        info = {
            "size": size,
            "etag": hdrs.get("etag"),
            "last_modified": hdrs.get("last-modified"),
            "accept_ranges": "bytes" in hdrs.get("accept-ranges", ""),
        }
        if require_range:
            if size is None:
                raise RemoteProbeError(
                    f"remote source {self.url!r} sends no "
                    "Content-Length; ranged reads need the size"
                )

            def probe_range():
                s, h, _ = self._request_once(
                    "GET", {"Range": "bytes=0-0"}, "probe"
                )
                if s in (500, 502, 503, 504):
                    raise _Transient(f"GET {self.url}: {s}")
                return s, h

            s, _h = self._with_retries(
                probe_range, f"range-probe {self.url}"
            )
            if s != 206:
                raise RemoteProbeError(
                    f"remote source {self.url!r} does not honor byte "
                    f"ranges (answered {s} to a 1-byte Range GET)"
                )
        return info

    def validators_evidence(self) -> str:
        """The fencing facts the expansion fingerprint folds in: a
        republished artifact (new ETag / Last-Modified / size) changes
        the fingerprint, so a resumed run REFUSES via the existing
        sidecar check before any row is written."""
        return (
            f"{self.url}:{self.size}:{self.etag or '-'}"
            f":{self.last_modified or '-'}"
        )

    def _fence_headers(self) -> dict:
        """``If-Range`` for ranged GETs: unchanged answers 206 as
        asked; a republished artifact answers 200-full-body, which
        :meth:`fetch_range` refuses as a change."""
        validator = self.etag or self.last_modified
        return {"If-Range": validator} if validator else {}

    def fetch_range(self, offset: int, length: int,
                    kind: str = "ranged") -> bytes:
        """One ranged read with the full contract: retry budget,
        If-Range fencing, exact-length and validator re-checks."""
        if length <= 0:
            return b""
        end = offset + length - 1

        def attempt() -> bytes:
            headers = {"Range": f"bytes={offset}-{end}"}
            headers.update(self._fence_headers())
            status, hdrs, body = self._request_once(
                "GET", headers, kind
            )
            if status in (500, 502, 503, 504):
                raise _Transient(f"GET {self.url}: {status}")
            if status == 200:
                # If-Range mismatch: the server fell back to the full
                # (new) representation — the artifact was republished
                raise RemoteChangedError(
                    f"remote source {self.url!r} changed under a "
                    "running job (If-Range fence answered 200)"
                )
            if status != 206:
                raise RemoteError(
                    f"remote source {self.url!r} answered {status} to "
                    f"a ranged GET"
                )
            etag = hdrs.get("etag")
            if self.etag and etag and etag != self.etag:
                raise RemoteChangedError(
                    f"remote source {self.url!r} changed under a "
                    f"running job (ETag {self.etag} -> {etag})"
                )
            if len(body) != length:
                raise _Transient(
                    f"GET {self.url}: ranged body {len(body)} bytes, "
                    f"want {length}"
                )
            return body

        return self._with_retries(
            attempt, f"ranged read {self.url}@{offset}+{length}"
        )

    def open_stream(self):
        """A forward-only full-body GET on a DEDICATED connection
        (never pooled: an abandoned stream cannot be reused), fenced
        with ``If-Match`` so a mid-job republish answers 412 instead
        of new bytes.  Answers a file-like whose ``read`` raises
        ``OSError`` on transport failure (the sequential-window
        reader's row-contained contract) and whose ``close`` closes
        the connection on every path."""
        import http.client
        import socket

        def attempt():
            conn = self.pool._dial()
            try:
                headers = {}
                if self.etag:
                    headers["If-Match"] = self.etag
                conn.request("GET", self._target, headers=headers)
                resp = conn.getresponse()
                status = resp.status
                hdrs = {k.lower(): v for k, v in resp.getheaders()}
            except (http.client.HTTPException, OSError) as exc:
                conn.close()
                raise _Transient(f"GET {self.url}: {exc}") from exc
            try:
                if status in (500, 502, 503, 504):
                    raise _Transient(f"GET {self.url}: {status}")
                if status == 412:
                    raise RemoteChangedError(
                        f"remote source {self.url!r} changed under a "
                        "running job (If-Match fence answered 412)"
                    )
                if status != 200:
                    raise RemoteError(
                        f"remote source {self.url!r} answered "
                        f"{status} to a streaming GET"
                    )
                for got, want, what in (
                    (hdrs.get("etag"), self.etag, "ETag"),
                    (
                        hdrs.get("last-modified"), self.last_modified,
                        "Last-Modified",
                    ),
                ):
                    if want and got and got != want:
                        raise RemoteChangedError(
                            f"remote source {self.url!r} changed "
                            f"under a running job ({what} {want} -> "
                            f"{got})"
                        )
                clen = hdrs.get("content-length")
                if (
                    self.size is not None and clen is not None
                    and clen.isdigit() and int(clen) != self.size
                ):
                    raise RemoteChangedError(
                        f"remote source {self.url!r} changed under a "
                        f"running job (size {self.size} -> {clen})"
                    )
            except BaseException:
                conn.close()
                raise
            m = _metrics()
            m["requests"].labels(kind="stream").inc()
            return _StreamBody(conn, resp, m["bytes"], socket.timeout)

        return self._with_retries(attempt, f"stream {self.url}")

    def note_transient(self, what: str) -> None:
        """Budget accounting for retries driven OUTSIDE
        :meth:`_with_retries` (the sequential-window reader's
        row-contained torn-stream retries)."""
        with self._lock:
            self._retries_used += 1
            used = self._retries_used
        _metrics()["retries"].inc()
        if used > self.retry_cap:
            raise RemoteRetryBudgetError(
                f"{what}: container retry budget exhausted "
                f"({used}/{self.retry_cap})"
            )

    def close(self) -> None:
        self.pool.close()


class _StreamBody:
    """The streaming GET's body: reads count into the bytes counter,
    transport failures surface as OSError (what the sequential-window
    reader treats as a torn stream), close closes the connection."""

    def __init__(self, conn, resp, bytes_counter, timeout_exc):
        self._conn = conn
        self._resp = resp
        self._bytes = bytes_counter
        self._timeout_exc = timeout_exc

    def read(self, n: int = -1) -> bytes:
        import http.client

        try:
            data = self._resp.read() if n is None or n < 0 else (
                self._resp.read(n)
            )
        except (http.client.HTTPException, self._timeout_exc) as exc:
            raise OSError(f"remote stream failed: {exc}") from exc
        self._bytes.inc(len(data))
        return data

    def close(self) -> None:
        try:
            self._conn.close()
        except OSError:
            pass


class _RangedFile:
    """A seekable read-only file view over ranged GETs, for the
    stdlib parsers that do the metadata work (tarfile header walk,
    zipfile central directory): block-aligned fetches with a tiny LRU
    so a forward header scan — or zipfile's tail-first directory read
    — costs one request per 256 KiB touched, not one per ``read``."""

    block = 256 << 10
    cached_blocks = 4

    def __init__(self, source: _RemoteSource):
        if source.size is None:
            raise RemoteError(
                f"remote source {source.url!r} sends no Content-Length"
            )
        self._source = source
        self._size = source.size
        self._pos = 0
        self._blocks: dict[int, bytes] = {}

    def _block(self, idx: int) -> bytes:
        data = self._blocks.pop(idx, None)
        if data is None:
            offset = idx * self.block
            length = min(self.block, self._size - offset)
            data = self._source.fetch_range(offset, length)
        self._blocks[idx] = data  # re-insert: LRU order
        while len(self._blocks) > self.cached_blocks:
            self._blocks.pop(next(iter(self._blocks)))
        return data

    def read(self, n: int = -1) -> bytes:
        if n is None or n < 0:
            n = self._size - self._pos
        n = min(n, self._size - self._pos)
        out = []
        while n > 0:
            idx, off = divmod(self._pos, self.block)
            chunk = self._block(idx)[off:off + n]
            if not chunk:
                break
            out.append(chunk)
            self._pos += len(chunk)
            n -= len(chunk)
        return b"".join(out)

    def seek(self, offset: int, whence: int = 0) -> int:
        if whence == 1:
            offset += self._pos
        elif whence == 2:
            offset += self._size
        self._pos = max(0, min(offset, self._size))
        return self._pos

    def tell(self) -> int:
        return self._pos

    def seekable(self) -> bool:
        return True

    def readable(self) -> bool:
        return True

    def close(self) -> None:
        self._blocks.clear()


# -- the pipelined prefetch window ------------------------------------


class _Group:
    """One coalesced ranged read: ``[offset, offset+length)`` covering
    ``members`` = [(name, rel_offset)] slices."""

    __slots__ = ("offset", "length", "members", "pending", "state")

    def __init__(self, offset: int, length: int):
        self.offset = offset
        self.length = length
        self.members: list[tuple[str, int]] = []
        self.pending = 0
        self.state = "new"  # new | inflight | ready | failed


class _RangedPrefetcher:
    """The readahead window shared by the ranged containers (tar +
    zip).  The expansion's ``want()`` calls build the read plan; reads
    pump a bounded window of coalesced ranged requests through a small
    thread pool so the next blobs are already in flight while the
    featurize lane consumes the current ones.  ``readahead=1``
    degrades to strictly serial requests (the bench's baseline rung).

    Window discipline: a group occupies a slot from schedule until its
    LAST member is consumed, so buffered-but-unread bytes stay bounded
    by ``readahead × coalesce_bytes`` no matter how far the reader
    falls behind.  Reads outside the plan (duplicate explicit entries,
    out-of-contract orders) fetch directly — correct, just not
    prefetched."""

    def __init__(self, source: _RemoteSource, span_of, extract):
        # span_of(name) -> (offset, length) byte span to fetch;
        # extract(group, raw) -> {name: bytes | None}
        self._source = source
        self._span_of = span_of
        self._extract = extract
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._plan: list[str] = []
        self._planned: set[str] = set()
        self._groups: list[_Group] | None = None
        self._group_of: dict[str, int] = {}
        self._ready: dict[str, object] = {}
        self._errors: dict[int, BaseException] = {}
        self._next = 0
        self._occupied = 0
        self._inflight = 0
        self._pool = None
        self._closed = False

    def want(self, name: str) -> None:
        with self._lock:
            if name not in self._planned:
                self._planned.add(name)
                self._plan.append(name)
            self._groups = None  # rebuild lazily at next read

    def reset(self) -> None:
        with self._lock:
            self._plan = []
            self._planned = set()
            self._groups = None
            self._ready.clear()
            self._errors.clear()

    def _build_groups_locked(self) -> None:
        src = self._source
        groups: list[_Group] = []
        self._group_of = {}
        cur: _Group | None = None
        for name in self._plan:
            span = self._span_of(name)
            if span is None:
                continue
            offset, length = span
            end = offset + length
            if (
                cur is not None
                and offset >= cur.offset + cur.length
                and offset - (cur.offset + cur.length) <= src.coalesce_gap
                and end - cur.offset <= src.coalesce_bytes
            ):
                cur.length = end - cur.offset
            else:
                cur = _Group(offset, length)
                groups.append(cur)
            cur.members.append((name, offset - cur.offset))
            cur.pending += 1
            self._group_of[name] = len(groups) - 1
        self._groups = groups
        self._next = 0
        self._occupied = 0
        self._inflight = 0

    def _ensure_pool(self):
        if self._pool is None:
            from concurrent.futures import ThreadPoolExecutor

            self._pool = ThreadPoolExecutor(
                max_workers=min(8, max(1, self._source.readahead)),
                thread_name_prefix="remote-prefetch",
            )
        return self._pool

    def _schedule_locked(self, gid: int) -> None:
        group = self._groups[gid]
        if group.state != "new":
            return
        group.state = "inflight"
        self._occupied += 1
        self._inflight += 1
        _metrics()["readahead"].set(self._inflight)
        self._ensure_pool().submit(self._fetch_group, gid, group)

    def _pump_locked(self) -> None:
        while (
            self._next < len(self._groups)
            and self._occupied < self._source.readahead
        ):
            gid = self._next
            self._next += 1
            self._schedule_locked(gid)

    def _fetch_group(self, gid: int, group: _Group) -> None:
        # the group rides in as an argument (captured under the lock
        # at schedule time) so this worker thread never indexes the
        # rebuildable _groups list off-lock
        try:
            raw = self._source.fetch_range(group.offset, group.length)
            blobs = self._extract(group, raw)
        except BaseException as exc:  # noqa: BLE001 — relayed to readers
            with self._cond:
                self._errors[gid] = exc
                group.state = "failed"
                self._inflight -= 1
                _metrics()["readahead"].set(self._inflight)
                self._cond.notify_all()
            return
        with self._cond:
            self._ready.update(blobs)
            group.state = "ready"
            self._inflight -= 1
            _metrics()["readahead"].set(self._inflight)
            self._cond.notify_all()

    def _consume_locked(self, name: str):
        blob = self._ready.pop(name)
        gid = self._group_of.get(name)
        if gid is not None:
            group = self._groups[gid]
            group.pending -= 1
            if group.pending <= 0:
                self._occupied -= 1
        self._pump_locked()
        return blob

    def read(self, name: str):
        """The planned-read path: pop the prefetched blob, keeping the
        window full; block on the group when the fetch is still in
        flight; re-raise the group's failure (fail closed)."""
        with self._cond:
            if self._groups is None:
                self._build_groups_locked()
            if name in self._ready:
                return self._consume_locked(name)
            gid = self._group_of.get(name)
            if gid is None:
                # outside the plan: direct fetch, no window
                span = self._span_of(name)
            else:
                self._pump_locked()
                self._schedule_locked(gid)  # out-of-order: jump ahead
                while True:
                    if name in self._ready:
                        return self._consume_locked(name)
                    exc = self._errors.get(gid)
                    if exc is not None:
                        raise exc
                    if self._groups[gid].state == "ready":
                        # group landed but this name was consumed
                        # already (duplicate manifest entry): fall
                        # through to a direct fetch
                        span = self._span_of(name)
                        break
                    self._cond.wait()
        if span is None:
            return None
        group = _Group(span[0], span[1])
        group.members.append((name, 0))
        raw = self._source.fetch_range(group.offset, group.length)
        return self._extract(group, raw).get(name)

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            pool = self._pool
            self._pool = None
        if pool is not None:
            pool.shutdown(wait=True, cancel_futures=True)


# -- containers --------------------------------------------------------


class _RemoteTarContainer:
    """Uncompressed tar over HTTP: tarfile walks the member headers
    through the ranged block view (metadata only — it seeks past
    data), then per-member reads are ranged GETs by ``offset_data``
    through the prefetch window."""

    def __init__(self, url: str):
        import tarfile

        self.url = url
        self._source = _RemoteSource(url, require_range=True)
        try:
            self._members: dict[str, tuple[int, int]] = {}
            self._order: list[str] = []
            self._evidence = [f"rtar:{self._source.validators_evidence()}"]
            view = _RangedFile(self._source)
            size = self._source.size
            try:
                with tarfile.open(fileobj=view, mode="r:") as tf:
                    for info in tf:
                        if not info.isreg():
                            continue
                        if info.offset_data + info.size > size:
                            raise IngestError(
                                f"torn remote archive {url!r}: member "
                                f"{info.name!r} claims {info.size} "
                                "bytes past end of artifact"
                            )
                        if info.name not in self._members:
                            self._order.append(info.name)
                        self._members[info.name] = (
                            info.offset_data, info.size,
                        )
                        self._evidence.append(
                            f"{info.name}@{info.offset_data}"
                            f"+{info.size}:{info.mtime}:{info.chksum}"
                        )
            finally:
                view.close()
        except tarfile.TarError as exc:
            self._source.close()
            raise IngestError(
                f"cannot read remote tar {url!r}: {exc}"
            ) from exc
        except BaseException:
            self._source.close()
            raise
        self._prefetch = _RangedPrefetcher(
            self._source, self._span_of, self._extract
        )

    def _span_of(self, name: str):
        got = self._members.get(name)
        if got is None or got[1] > MAX_LICENSE_SIZE:
            return None
        return got

    def _extract(self, group: _Group, raw: bytes) -> dict:
        out = {}
        for name, rel in group.members:
            size = self._members[name][1]
            blob = raw[rel:rel + size]
            out[name] = blob if len(blob) == size else None
        return out

    def members(self) -> list[str]:
        return list(self._order)

    def evidence(self) -> list[str]:
        """URL validators (ETag/Last-Modified/size — the republish
        fence) plus the same member table evidence as the local tar
        reader (offset, size, mtime, header checksum)."""
        return list(self._evidence)

    def want(self, member: str) -> None:
        if self._span_of(member) is not None:
            self._prefetch.want(member)

    def reset_wants(self) -> None:
        self._prefetch.reset()

    def read(self, member: str):
        got = self._members.get(member)
        if got is None:
            return None  # a read_error row, like the local readers
        if got[1] > MAX_LICENSE_SIZE:
            return SkippedBlob(OVERSIZED)
        return self._prefetch.read(member)

    def close(self) -> None:
        self._prefetch.close()
        self._source.close()


class _RemoteZipContainer:
    """Zip over HTTP: zipfile parses the central directory through the
    ranged block view (its tail-first reads hit the cached end
    blocks), then per-member reads fetch the LOCAL RECORD span
    ``[header_offset, next_header_offset)`` in one ranged GET —
    coalesced with its neighbors — and inflate + CRC-check on the
    host."""

    def __init__(self, url: str):
        import zipfile

        self.url = url
        self._source = _RemoteSource(url, require_range=True)
        try:
            view = _RangedFile(self._source)
            try:
                try:
                    zf = zipfile.ZipFile(view)
                except (zipfile.BadZipFile, OSError) as exc:
                    raise IngestError(
                        f"cannot read remote zip {url!r}: {exc}"
                    ) from exc
                with zf:
                    infos = [i for i in zf.infolist() if not i.is_dir()]
                    cd_start = zf.start_dir
            finally:
                view.close()
        except BaseException:
            self._source.close()
            raise
        # duplicate member names collapse to the archive's effective
        # LAST copy, same semantics as the local reader
        self._infos = {i.filename: i for i in infos}
        self._order = list(
            dict.fromkeys(i.filename for i in infos)
        )
        # each member's local record ends where the next local header
        # (or the central directory) starts — the exact fetch bound,
        # data descriptor included
        starts = sorted(i.header_offset for i in infos)
        next_start = {}
        for a, b in zip(starts, starts[1:] + [cd_start]):
            next_start[a] = b
        self._spans = {
            i.filename: (
                i.header_offset,
                max(0, next_start[i.header_offset] - i.header_offset),
            )
            for i in infos
        }
        self._prefetch = _RangedPrefetcher(
            self._source, self._span_of, self._extract
        )

    def _span_of(self, name: str):
        info = self._infos.get(name)
        if info is None or info.file_size > MAX_LICENSE_SIZE:
            return None
        return self._spans[name]

    def _extract(self, group: _Group, raw: bytes) -> dict:
        out = {}
        for name, rel in group.members:
            info = self._infos[name]
            span_len = self._spans[name][1]
            out[name] = self._inflate(info, raw[rel:rel + span_len])
        return out

    def _inflate(self, info, record: bytes):
        """Local header -> compressed slice -> plain bytes, CRC-gated;
        malformed records are row-contained read errors, exactly like
        a local zip member whose inflate fails."""
        if len(record) < 30 or record[:4] != b"PK\x03\x04":
            return None
        fnlen = int.from_bytes(record[26:28], "little")
        exlen = int.from_bytes(record[28:30], "little")
        data = record[30 + fnlen + exlen:30 + fnlen + exlen
                      + info.compress_size]
        if len(data) != info.compress_size:
            return None
        if info.compress_type == 0:
            blob = bytes(data)
        elif info.compress_type == 8:
            try:
                d = zlib.decompressobj(-15)
                blob = d.decompress(data) + d.flush()
            except zlib.error:
                return None
        else:
            return None  # an unsupported method is a read_error row
        if len(blob) != info.file_size:
            return None
        if zlib.crc32(blob) & 0xFFFFFFFF != info.CRC:
            return None
        return blob

    def members(self) -> list[str]:
        return list(self._order)

    def evidence(self) -> list[str]:
        """URL validators plus the exact content evidence (member
        CRC + size), same strength as the local zip reader."""
        head = [f"rzip:{self._source.validators_evidence()}"]
        return head + [
            f"{n}:{self._infos[n].CRC}:{self._infos[n].file_size}"
            for n in self._order
        ]

    def want(self, member: str) -> None:
        if self._span_of(member) is not None:
            self._prefetch.want(member)

    def reset_wants(self) -> None:
        self._prefetch.reset()

    def read(self, member: str):
        info = self._infos.get(member)
        if info is None:
            return None
        if info.file_size > MAX_LICENSE_SIZE:
            return SkippedBlob(OVERSIZED)
        return self._prefetch.read(member)

    def close(self) -> None:
        self._prefetch.close()
        self._source.close()


class _RemoteSeqTarContainer(_SeqTarContainer):
    """Compressed tar over HTTP: the PR 15 sequential-window reader
    with its forward passes riding streaming GETs — one full-body
    stream for the metadata scan, one per stripe span for reads, each
    fenced with ``If-Match``.  A torn stream is retried with the
    container's budget (reopen = the window reader's counted rescan);
    budget exhaustion fails the container closed."""

    def __init__(self, url: str):
        self._source = _RemoteSource(url, require_range=False)
        self._raw = None
        self._meta_raw = None
        try:
            super().__init__(url)
        except BaseException:
            self._close_meta()
            self._source.close()
            raise
        # the metadata pass is done; its dedicated connection dies now,
        # not at container close
        self._close_meta()

    def _head_evidence(self) -> str:
        return f"rctar:{self._source.validators_evidence()}"

    def _open_meta_tar(self):
        import tarfile

        raw = self._source.open_stream()
        try:
            tf = tarfile.open(fileobj=raw, mode="r|*")
        except BaseException:
            raw.close()
            raise
        # tarfile's `with` close does not close the fileobj; the
        # caller (our __init__) closes it via _close_meta
        self._meta_raw = raw
        return tf

    def _close_meta(self) -> None:
        if self._meta_raw is not None:
            self._meta_raw.close()
            self._meta_raw = None

    def _open_stream_tar(self):
        import tarfile

        raw = self._source.open_stream()
        try:
            tf = tarfile.open(fileobj=raw, mode="r|*")
        except BaseException:
            raw.close()
            raise
        self._raw = raw
        return tf

    def _close_stream(self) -> None:
        super()._close_stream()
        if self._raw is not None:
            self._raw.close()
            self._raw = None

    def read(self, member: str):
        got = self._members.get(member)
        if got is None:
            return None
        if got[1] > MAX_LICENSE_SIZE:
            return SkippedBlob(OVERSIZED)
        delay = self._source.backoff_s
        attempt = 0
        while True:
            out = super().read(member)
            if out is not None:
                return out
            # None from the window reader = torn/dropped stream (the
            # transport surfaces as OSError inside the walk).  Retry
            # against the budget: the next read reopens a fresh
            # fenced stream; a PERSISTENT tear (or a member whose
            # bytes genuinely come up short) exhausts the budget and
            # fails the container closed.
            attempt += 1
            self._source.note_transient(
                f"stream read {self.url}::{member}"
            )
            if attempt > self._source.retries:
                raise RemoteRetryBudgetError(
                    f"stream read {self.url!r}::{member!r}: retry "
                    f"budget exhausted after {attempt - 1} retries"
                )
            time.sleep(delay)
            delay = min(delay * 2, self._source.backoff_cap_s)

    def close(self) -> None:
        super().close()
        self._close_meta()
        self._source.close()


def open_remote_container(kind: str, url: str):
    """The sources.py routing hook for ``http(s)://`` containers."""
    if kind == "rtar":
        return _RemoteTarContainer(url)
    if kind == "rctar":
        return _RemoteSeqTarContainer(url)
    if kind == "rzip":
        return _RemoteZipContainer(url)
    if kind == "rgit":
        raise IngestError(
            f"git-over-HTTP container {url!r} is not supported; "
            "publish a tar/zip artifact (release tarballs address as "
            "https://...tar.gz::*)"
        )
    raise IngestError(f"unrecognized remote container kind {kind!r}")


def probe_remote(url: str, *, timeout_s: float = 5.0) -> dict:
    """The cheap submit-time probe (``POST /jobs`` validate_spec): a
    HEAD for reachability + validators, plus a 1-byte ranged GET for
    the kinds that need random access.  Answers
    ``{kind, size, etag, last_modified}``; raises
    :class:`RemoteProbeError` (unreachable, non-200, no Range support)
    or :class:`RemoteError` (unsupported scheme/shape) so the edge can
    400 at submit instead of crashing a stripe mid-job."""
    kind = remote_entry_kind(url)
    if kind is None:
        raise RemoteError(
            f"{url!r} is not a recognized remote container "
            "(want http(s)://...{.tar,.tar.gz,.tgz,.zip})"
        )
    if kind == "rgit":
        raise RemoteError(
            f"git-over-HTTP container {url!r} is not supported; "
            "publish a tar/zip artifact"
        )
    knobs = dict(_knobs())
    knobs["timeout_s"] = timeout_s
    knobs["retries"] = min(knobs["retries"], 1)
    source = _RemoteSource(
        url, require_range=kind in ("rtar", "rzip"), knobs=knobs
    )
    try:
        return {
            "kind": kind,
            "size": source.size,
            "etag": source.etag,
            "last_modified": source.last_modified,
        }
    finally:
        source.close()
