"""Container-aware blob sources: stream blobs out of tarballs, zip
archives, and bare git repositories straight into the batch featurize
lane — no extraction to disk, bounded memory (only member METADATA is
held; blob bytes are read per batch by the produce workers and
dropped with the batch).

Manifest addressing grammar (the ``::`` forms)
----------------------------------------------
``path``                     a loose file (the existing manifest entry)
``archive.tar::member``      one member blob inside a tar archive
``archive.tar::*``           every regular-file member, archive order
``archive.zip::member``      one member blob inside a zip archive
``archive.zip::*``           every regular-file member, archive order
``repo.git::HEAD``           every root-tree blob of that revision
                             (any rev: branch, tag, sha — the same
                             root-level-only view as GitProject,
                             git_project.rb:64-76)

Whole-container forms (``*`` / a git revision) expand to one per-blob
work item per member, DISPLAYED by the member's own stored name — the
per-blob output rows of a container read like the project listing the
reference sees, and the container-level verdict row (verdict.py) is
the join handle that names the container.  Explicit single-member
entries echo back exactly as written.

Every reader enforces the reference's ``MAX_LICENSE_SIZE`` 64 KiB blob
cap (git_project.rb:53) by SKIPPING oversized blobs (a
:class:`SkippedBlob` marker -> an ``"error": "oversized"`` output row),
never by truncating and scoring the head.

Striping denomination (``--stripes`` / multi-host ranks) is EXPANDED
blob counts, not raw manifest entries: every rank runs the same
metadata-only expansion of the full manifest (member tables, central
directories, git root trees — no blob bytes), then
:meth:`ManifestExpansion.restrict`\\ s itself to its span of the
expanded rows, closing the handles of containers its span never
touches.  A single million-member tarball therefore splits across
stripes, each stripe ``read_at()``-ing only its own span.
:func:`expanded_layout` is the supervisor-side twin: one counting pass
that returns the total, the container groups, and the expansion
fingerprint, with every handle closed before it returns.

Torn containers fail closed: a truncated tar member table, a zip with
a corrupt central directory, or a git repo whose pack cannot resolve
the revision's root tree all raise :class:`IngestError` at expansion
time — before any row is written — instead of producing a partial
container that would poison the resume invariant.

Thread-safety: tar members are read with ``os.pread`` (no shared file
offset, so produce worker threads need no lock); zip and git readers
serialize on a per-container lock (zipfile shares one seekable handle;
the native git ODB handle makes no concurrency promise).
"""

from __future__ import annotations

import hashlib
import os
import threading

from licensee_tpu.ingest import OVERSIZED, SkippedBlob

# the one blob cap, shared with the git backend (projects/git_project.py
# imports stay light: Project + subprocess only)
from licensee_tpu.projects.git_project import MAX_LICENSE_SIZE

SEP = "::"

# compressed tar forms: random access into a compressed stream is
# O(archive) per member, so these route to the sequential-WINDOW reader
# (_SeqTarContainer) — one forward decompression pass per stripe span,
# never a from-zero rescan per blob
_COMPRESSED_TAR_SUFFIXES = (".tar.gz", ".tgz", ".tar.bz2", ".tar.xz", ".tbz2", ".txz")


class IngestError(ValueError):
    """A container that cannot be opened or safely enumerated (torn
    archive, corrupt central directory, unresolvable git revision)."""


def split_entry(entry: str):
    """``(container, selector)`` for a ``::`` manifest entry, else None.

    Splits on the FIRST ``::`` so member names may themselves contain
    colons; a path with ``::`` whose prefix is not a recognized
    container shape is treated as a plain loose path (the read then
    fails row-contained, like any other unreadable manifest entry)."""
    if SEP not in entry:
        return None
    container, selector = entry.split(SEP, 1)
    if not container or _container_kind(container) is None:
        return None
    return container, selector


def is_container_entry(entry: str) -> bool:
    return split_entry(entry) is not None


def _container_kind(container: str) -> str | None:
    low = container.lower()
    if low.startswith(("http://", "https://")):
        # remote containers route by URL suffix (query/fragment
        # stripped): rtar/rctar/rzip stream over HTTP(S) (remote.py),
        # rgit is recognized-but-refused with a clear message.  An
        # unrecognized URL shape degrades to a loose path whose failed
        # read is row-contained, same as any local non-container.
        # Lazy import: remote.py imports this module at its top.
        from licensee_tpu.ingest import remote as _remote

        return _remote.remote_entry_kind(container)
    if low.endswith(_COMPRESSED_TAR_SUFFIXES):
        return "ctar"
    if low.endswith(".tar"):
        return "tar"
    if low.endswith(".zip"):
        return "zip"
    if low.endswith(".git"):
        return "git"
    # a bare directory is a git container only when it LOOKS like a
    # repository (a .git entry, or the bare HEAD+objects layout) — an
    # ordinary directory path that happens to contain '::' stays a
    # plain loose path whose failed read is row-contained, exactly as
    # before containers existed
    if os.path.isdir(container) and (
        os.path.exists(os.path.join(container, ".git"))
        or (
            os.path.isfile(os.path.join(container, "HEAD"))
            and os.path.isdir(os.path.join(container, "objects"))
        )
    ):
        return "git"
    return None


class _TarContainer:
    """Random access into an UNCOMPRESSED tar: one metadata scan up
    front (name -> (offset, size)), then lock-free ``os.pread`` per
    member read."""

    def __init__(self, path: str):
        import tarfile

        if path.lower().endswith(_COMPRESSED_TAR_SUFFIXES):
            # defensive: open_container routes these to _SeqTarContainer
            raise IngestError(
                f"compressed tar {path!r} needs the sequential-window "
                "reader (_SeqTarContainer), not random-access pread"
            )
        self.path = path
        self._members: dict[str, tuple[int, int]] = {}
        self._order: list[str] = []
        self._evidence: list[str] = []
        try:
            size = os.path.getsize(path)
            self._evidence.append(f"tar:{size}")
            with tarfile.open(path, mode="r:") as tf:
                for info in tf:
                    if not info.isreg():
                        continue  # dirs, symlinks, devices carry no blob
                    if info.offset_data + info.size > size:
                        raise IngestError(
                            f"torn archive {path!r}: member {info.name!r} "
                            f"claims {info.size} bytes past end of file"
                        )
                    if info.name not in self._members:
                        self._order.append(info.name)
                    self._members[info.name] = (info.offset_data, info.size)
                    self._evidence.append(
                        f"{info.name}@{info.offset_data}+{info.size}"
                        f":{info.mtime}:{info.chksum}"
                    )
        except tarfile.TarError as exc:
            raise IngestError(f"cannot read tar {path!r}: {exc}") from exc
        except OSError as exc:
            raise IngestError(f"cannot open tar {path!r}: {exc}") from exc
        self._fd = os.open(path, os.O_RDONLY)

    def members(self) -> list[str]:
        return list(self._order)

    def evidence(self) -> list[str]:
        """Resume-fingerprint evidence: archive size plus every
        member's (offset, size, mtime, header checksum) — a repack
        with the same member names still changes the layout/mtimes,
        so the resumed run refuses instead of mixing contents (zip
        and git evidence is exact: CRCs / object ids)."""
        return list(self._evidence)

    def read(self, member: str):
        span = self._members.get(member)
        if span is None:
            return None  # a read_error row, like an unreadable loose path
        offset, size = span
        if size > MAX_LICENSE_SIZE:
            return SkippedBlob(OVERSIZED)
        try:
            data = os.pread(self._fd, size, offset)
        except OSError:
            return None
        return data if len(data) == size else None

    def close(self) -> None:
        if self._fd is not None:
            os.close(self._fd)
            self._fd = None


class _SeqTarContainer:
    """Compressed tar (``.tar.gz`` and friends): a sequential-WINDOW
    reader.  Random access into a compressed stream is O(archive) per
    member, so reads ride ONE forward decompression pass instead.

    The metadata scan (one full pass up front — a torn gzip fails
    closed HERE, before any row is written) assigns every regular
    member a stream ordinal.  ``read()`` advances a forward-only
    ``r|*`` tarfile stream to the requested ordinal, caching any
    WANTED member it passes over (``want()`` — the expansion registers
    exactly the members its span will read, narrowed to the unread
    suffix on resume via ``ManifestExpansion.mark_done_prefix``), so
    the batch pipeline's bounded read reordering (``inflight`` produce
    batches) pops the cache instead of rescanning.  Cache entries are
    popped on read, and the window is additionally HARD-BOUNDED at
    ``cache_bytes_max`` (FIFO eviction): a caller whose read order
    strands entries — a --featurize-procs pool hands each worker only
    some of the span's chunks — degrades to the counted rescan
    fallback instead of holding an archive's worth of blobs.  A read
    behind the window that was never cached (or was evicted) reopens
    the stream once (``rescans`` counts them; the pipeline's in-order
    pattern never takes this path — it exists so out-of-contract
    orderings stay correct, not fast)."""

    # the reorder window the pipeline needs is inflight batches x
    # batch_size blobs of <= 64 KiB each; 32 MiB covers that many
    # times over while keeping the stranded-entry worst case harmless
    cache_bytes_max = 32 << 20

    def __init__(self, path: str):
        import tarfile
        import zlib

        self.path = path
        self._lock = threading.Lock()
        self._members: dict[str, tuple[int, int]] = {}
        self._order: list[str] = []
        self._evidence: list[str] = []
        self._wanted: set[int] = set()
        self._cache: dict[int, bytes] = {}
        self._cache_bytes = 0
        self._tf = None
        self._iter = None
        self._pos = 0
        self.rescans = 0
        try:
            self._evidence.append(self._head_evidence())
            ordinal = 0
            with self._open_meta_tar() as tf:
                for info in tf:
                    if not info.isreg():
                        continue
                    if info.name not in self._order:
                        self._order.append(info.name)
                    # duplicates collapse to the LAST occurrence (tar
                    # extraction semantics, like _TarContainer)
                    self._members[info.name] = (ordinal, info.size)
                    self._evidence.append(
                        f"{info.name}@{ordinal}+{info.size}"
                        f":{info.mtime}:{info.chksum}"
                    )
                    ordinal += 1
        except (tarfile.TarError, EOFError, OSError, zlib.error) as exc:
            raise IngestError(
                f"cannot read compressed tar {path!r}: {exc}"
            ) from exc
        self._closed = False

    # the three seams the remote subclass overrides (remote.py): head
    # evidence carries the republish-fence validators instead of the
    # local size, and both tar passes ride streaming GETs instead of
    # local file opens
    def _head_evidence(self) -> str:
        return f"ctar:{os.path.getsize(self.path)}"

    def _open_meta_tar(self):
        import tarfile

        return tarfile.open(self.path, mode="r:*")

    def _open_stream_tar(self):
        import tarfile

        # r|* = strictly forward streaming decompression; members must
        # be consumed in stream order, which is exactly the window
        # discipline this reader enforces
        return tarfile.open(self.path, mode="r|*")

    def members(self) -> list[str]:
        return list(self._order)

    def evidence(self) -> list[str]:
        """Archive size plus every member's (stream ordinal, size,
        mtime, header checksum) — same repack-refusal strength as the
        plain-tar evidence."""
        return list(self._evidence)

    def want(self, member: str) -> None:
        """Mark a member this expansion WILL read: only wanted members
        are cached when the forward walk passes them (a stripe must
        never buffer another stripe's span)."""
        got = self._members.get(member)
        if got is not None and got[1] <= MAX_LICENSE_SIZE:
            self._wanted.add(got[0])

    def reset_wants(self) -> None:
        self._wanted.clear()
        self._cache.clear()
        self._cache_bytes = 0

    def _close_stream(self) -> None:
        if self._tf is not None:
            try:
                self._tf.close()
            except OSError:
                pass
            self._tf = None
            self._iter = None
        self._pos = 0

    def _next_reg(self):
        if self._tf is None:
            self._tf = self._open_stream_tar()
            self._iter = iter(self._tf)
            self._pos = 0
        while True:
            info = next(self._iter)
            if info.isreg():
                return info

    def read(self, member: str):
        import tarfile
        import zlib

        got = self._members.get(member)
        if got is None:
            return None  # a read_error row, like the other readers
        ordinal, size = got
        if size > MAX_LICENSE_SIZE:
            return SkippedBlob(OVERSIZED)
        with self._lock:
            data = self._cache.pop(ordinal, None)
            if data is not None:
                self._cache_bytes -= len(data)
                return data
            try:
                if ordinal < self._pos:
                    # behind the window and never cached: the one
                    # correctness rescan (counted; in-contract callers
                    # never reach here)
                    self._close_stream()
                    self.rescans += 1
                while True:
                    info = self._next_reg()
                    o = self._pos
                    self._pos += 1
                    if o == ordinal:
                        f = self._tf.extractfile(info)
                        data = f.read() if f is not None else None
                        if data is None or len(data) != size:
                            return None
                        return data
                    if o in self._wanted:
                        f = self._tf.extractfile(info)
                        blob = f.read() if f is not None else None
                        if blob is not None:
                            self._cache[o] = blob
                            self._cache_bytes += len(blob)
                            while (
                                self._cache_bytes > self.cache_bytes_max
                                and self._cache
                            ):
                                # FIFO eviction: a stranded entry's
                                # eventual read pays one rescan instead
                                # of this cache paying the archive
                                first = next(iter(self._cache))
                                self._cache_bytes -= len(
                                    self._cache.pop(first)
                                )
            except (
                tarfile.TarError, EOFError, OSError, StopIteration,
                zlib.error,
            ):
                # row-contained: the next read reopens a fresh stream
                self._close_stream()
                return None

    def close(self) -> None:
        if not self._closed:
            self._close_stream()
            self._cache.clear()
            self._cache_bytes = 0
            self._closed = True


class _ZipContainer:
    """zipfile-backed reads off the central directory; one shared
    seekable handle guarded by a lock."""

    def __init__(self, path: str):
        import zipfile

        self.path = path
        self._lock = threading.Lock()
        try:
            # a truncated/garbage zip fails HERE, on the central
            # directory, before any row is written; per-member CRC
            # failures later are row-contained read errors
            self._zf = zipfile.ZipFile(path)
        except (zipfile.BadZipFile, OSError) as exc:
            raise IngestError(f"cannot read zip {path!r}: {exc}") from exc
        self._infos = {
            i.filename: i for i in self._zf.infolist() if not i.is_dir()
        }
        # duplicate member names (an appended archive) collapse to ONE
        # row of the archive's effective copy — ZipFile's name table
        # resolves to the LAST occurrence, the same last-wins semantics
        # extraction (and the tar reader above) would give; emitting a
        # row per occurrence would silently score the wrong bytes for
        # all but the last
        self._order = list(dict.fromkeys(
            i.filename for i in self._zf.infolist() if not i.is_dir()
        ))

    def members(self) -> list[str]:
        return list(self._order)

    def evidence(self) -> list[str]:
        """Exact content evidence: every member's CRC + size."""
        return [
            f"{n}:{self._infos[n].CRC}:{self._infos[n].file_size}"
            for n in self._order
        ]

    def read(self, member: str):
        info = self._infos.get(member)
        if info is None:
            return None
        if info.file_size > MAX_LICENSE_SIZE:
            return SkippedBlob(OVERSIZED)
        try:
            with self._lock:
                return self._zf.read(member)
        except Exception:  # noqa: BLE001 — CRC/zlib errors are row-contained
            return None

    def close(self) -> None:
        if self._zf is not None:
            self._zf.close()
            self._zf = None


class _GitContainer:
    """A revision's root tree straight out of the object database —
    the native packfile/ODB reader when it builds, git plumbing
    subprocesses otherwise (the same backend pair as GitProject, so the
    64 KiB skip semantics cannot drift between the two)."""

    def __init__(self, path: str, revision: str):
        from licensee_tpu.projects.git_project import (
            GitProject,
            InvalidRepository,
        )

        self.path = path
        self._lock = threading.Lock()
        try:
            self._backend = GitProject._open_backend(path, revision)
            files = self._backend.files()
        except InvalidRepository as exc:
            raise IngestError(
                f"cannot open git container {path!r} at {revision!r}: {exc}"
            ) from exc
        self._files = {f["name"]: f for f in files}
        self._order = [f["name"] for f in files]

    def members(self) -> list[str]:
        return list(self._order)

    def evidence(self) -> list[str]:
        """Exact content evidence: every root entry's object id."""
        return [f"{n}:{self._files[n]['oid']}" for n in self._order]

    def read(self, member: str):
        from licensee_tpu.projects.git_project import InvalidRepository

        file = self._files.get(member)
        if file is None:
            return None
        try:
            with self._lock:
                data = self._backend.load_file(file)
        except InvalidRepository:
            return None
        # the backends answer None for exactly one reason: the blob is
        # past the MAX_LICENSE_SIZE cap (read errors raise)
        return SkippedBlob(OVERSIZED) if data is None else data

    def close(self) -> None:
        if self._backend is not None:
            self._backend.close()
            self._backend = None


def open_container(container: str, selector: str):
    """Open one container path; the selector picks git revisions
    (a git container is opened per distinct revision)."""
    kind = _container_kind(container)
    if kind in ("rtar", "rctar", "rzip", "rgit"):
        from licensee_tpu.ingest import remote as _remote

        return _remote.open_remote_container(kind, container)
    if kind == "tar":
        return _TarContainer(container)
    if kind == "ctar":
        return _SeqTarContainer(container)
    if kind == "zip":
        return _ZipContainer(container)
    if kind == "git":
        return _GitContainer(container, selector or "HEAD")
    raise IngestError(f"unrecognized container {container!r}")


# the loose-file read policy, bound lazily ONCE (serve/featurize.py
# imports this package's __init__, so a module-level import here would
# be circular; a per-read import statement costs a sys.modules probe
# on the hot produce path)
_READ_CAPPED = None


def _loose_read(path: str):
    global _READ_CAPPED
    if _READ_CAPPED is None:
        from licensee_tpu.serve.featurize import read_capped

        _READ_CAPPED = read_capped
    return _READ_CAPPED(path)


class ManifestExpansion:
    """The expanded manifest: per-blob display paths, the container
    groups behind them, and the positional reader the produce stage
    pulls blobs through.

    ``paths[i]`` is what the output row prints; ``read_at(i)`` loads
    the bytes (``None`` -> read_error row, :class:`SkippedBlob` ->
    skip row).  Reads are addressed BY INDEX, not by display path, so
    two containers holding the same member name can never cross wires.

    ``total`` is the FULL expanded blob count — the striping
    denominator — even after :meth:`restrict` narrows this instance to
    one stripe's span; :meth:`fingerprint` is likewise computed over
    the full expansion, so every stripe's resume sidecar (and the
    merged output's) carries the same value as a single-process run.
    """

    def __init__(self, entries: list[str]):
        # the raw manifest entries this expansion came from — with
        # ``span``, everything a worker process needs to re-open the
        # containers itself (see descriptor()/from_descriptor)
        self.entries = list(entries)
        self.span: tuple[int, int] | None = None
        self.total = 0
        self.paths: list[str] = []
        # parallel to paths: the filename the routing/dispatch tables
        # see (the MEMBER's basename for container blobs — an explicit
        # `a.tar::LICENSE` entry must route exactly like the loose
        # LICENSE it addresses, not like its display string)
        self.filenames: list[str] = []
        # parallel to paths: None for loose files, (container, member)
        self._refs: list = []
        # whole-container groups: (entry, start, count) in manifest order
        self.spans: list[tuple[str, int, int]] = []
        # explicitly-listed member groups: (container path,
        # [(index, member), ...]) — `a.tar::LICENSE` + `a.tar::COPYING`
        # in one manifest yield ONE container row over exactly the
        # listed members (verdict.py), instead of silently skipping
        # the container sidecar
        self.subsets: list[tuple[str, list[tuple[int, str]]]] = []
        self._containers: list = []
        self._fingerprint: str | None = None
        self._any_containers = False
        # resume support: rows [0, _done_prefix) of this view are
        # already written and will never be read (mark_done_prefix)
        self._done_prefix = 0

    @property
    def has_containers(self) -> bool:
        return self._any_containers

    def read_at(self, index: int):
        ref = self._refs[index]
        if ref is None:
            return _loose_read(self.paths[index])
        container, member = ref
        return container.read(member)

    def fingerprint(self) -> str | None:
        """sha1 over the FULL expanded path list PLUS per-container
        content evidence (tar member offsets/sizes/mtimes/header
        checksums, zip CRCs, git object ids) — the resume sidecar's
        proof that a resumed run expands to the SAME rows of the SAME
        bytes.  An archive rewritten between runs — even one keeping
        every member name — must refuse, not silently append rows
        scored from different content after a completed prefix of the
        old.  Span-independent by construction (computed during the
        full enumeration, before any restrict), so a stripe shard's
        sidecar, the merged output's, and a single-process run's all
        agree."""
        return self._fingerprint if self._any_containers else None

    def restrict(self, lo: int, hi: int) -> "ManifestExpansion":
        """Narrow to the expanded-index span ``[lo, hi)`` — one
        stripe's view.  Rows outside the span drop, container groups
        clip to span-local indices, and containers whose members all
        fall outside the span are CLOSED (a stripe never holds fds for
        blobs another stripe owns).  ``total``/``fingerprint()`` keep
        their full-expansion values."""
        if not 0 <= lo <= hi <= self.total:
            raise ValueError(
                f"span [{lo}, {hi}) out of range for {self.total} "
                "expanded entries"
            )
        self.paths = self.paths[lo:hi]
        self.filenames = self.filenames[lo:hi]
        self._refs = self._refs[lo:hi]
        clipped = []
        for entry, start, count in self.spans:
            s, e = max(start, lo), min(start + count, hi)
            if e > s:
                clipped.append((entry, s - lo, e - s))
        self.spans = clipped
        self.subsets = [
            (label, kept)
            for label, members in self.subsets
            if (kept := [(i - lo, m) for i, m in members if lo <= i < hi])
        ]
        live = {id(c) for ref in self._refs if ref is not None
                for c in (ref[0],)}
        keep = []
        for c in self._containers:
            if id(c) in live:
                keep.append(c)
            else:
                try:
                    c.close()
                except OSError:
                    pass
        self._containers = keep
        self.span = (lo, hi)
        self._register_wants()
        return self

    def _register_wants(self) -> None:
        """Tell sequential-window containers exactly which members
        this expansion will read (its span minus any completed resume
        prefix), so their forward walk caches nothing another stripe
        owns and nothing a resumed run already wrote."""
        for c in self._containers:
            if hasattr(c, "reset_wants"):
                c.reset_wants()
        for ref in self._refs[self._done_prefix:]:
            if ref is not None and hasattr(ref[0], "want"):
                ref[0].want(ref[1])

    def mark_done_prefix(self, done: int) -> None:
        """Resume support: rows [0, done) of THIS view are already on
        disk and will never be read — narrow the sequential-window
        wants to the unread suffix, so the resumed forward walk skips
        the completed prefix (decompress-and-discard) without caching
        it."""
        done = max(0, min(int(done), len(self._refs)))
        if done > self._done_prefix:
            self._done_prefix = done
            self._register_wants()

    def layout(self) -> dict:
        """The supervisor-facing summary (see :func:`expanded_layout`):
        total / container groups / fingerprint.  Call on an
        UNRESTRICTED expansion — after :meth:`restrict` the groups are
        span-local, not full-manifest."""
        return {
            "total": self.total,
            "spans": list(self.spans),
            "subsets": [(label, list(m)) for label, m in self.subsets],
            "fingerprint": self.fingerprint(),
        }

    def descriptor(self) -> dict:
        """A picklable re-open recipe for worker PROCESSES
        (``--featurize-procs``): the raw entries, the span, and the
        expansion fingerprint.  Workers rebuild their own expansion
        from it (:meth:`from_descriptor`) — fresh container handles in
        the worker, never inherited fds — and the fingerprint check
        refuses if the containers changed between the parent's
        expansion and the worker's."""
        return {
            "entries": list(self.entries),
            "span": list(self.span) if self.span is not None else None,
            "fingerprint": self.fingerprint(),
            "done_prefix": self._done_prefix,
        }

    @classmethod
    def from_descriptor(cls, desc: dict) -> "ManifestExpansion":
        out = expand_manifest(desc["entries"])
        try:
            if desc.get("fingerprint") and (
                out.fingerprint() != desc["fingerprint"]
            ):
                raise IngestError(
                    "container contents changed under a running job: "
                    "the worker's expansion fingerprint does not match "
                    "the parent's"
                )
            span = desc.get("span")
            if span is not None:
                out.restrict(span[0], span[1])
            if desc.get("done_prefix"):
                out.mark_done_prefix(desc["done_prefix"])
        except BaseException:
            out.close()
            raise
        return out

    def __getstate__(self):
        # fds and ODB handles must never cross a process boundary — a
        # pickled fd NUMBER would "work" in a fork child and silently
        # share file offsets; spawn children would read a stranger's
        # fd.  Workers ship descriptor() and re-open for themselves.
        raise TypeError(
            "ManifestExpansion holds live container handles and never "
            "pickles; ship descriptor() and re-open with "
            "from_descriptor() in the worker process"
        )

    def close(self) -> None:
        for c in self._containers:
            try:
                c.close()
            except OSError:
                pass
        self._containers = []


def expand_manifest(
    entries: list[str], span: tuple[int, int] | None = None
) -> ManifestExpansion:
    """Expand raw manifest entries into per-blob work items,
    optionally restricted to the expanded-index ``span`` (a stripe's
    view — see :meth:`ManifestExpansion.restrict`).

    Deterministic given the manifest and the container contents —
    the property the blob-level resume invariant (line count ==
    completed prefix) rides on."""
    out = ManifestExpansion(entries)
    try:
        _expand_into(out, entries)
        out.total = len(out.paths)
        out._fingerprint = _full_fingerprint(out)
        if span is not None:
            out.restrict(span[0], span[1])
        else:
            out._register_wants()
    except BaseException:
        # a torn container midway through the manifest must not leak
        # the handles already opened for the containers before it
        out.close()
        raise
    return out


def _full_fingerprint(out: ManifestExpansion) -> str:
    h = hashlib.sha1(usedforsecurity=False)
    for p in out.paths:
        h.update(p.encode("utf-8", "surrogatepass"))
        h.update(b"\0")
    for container in out._containers:
        for line in container.evidence():
            h.update(line.encode("utf-8", "surrogatepass"))
            h.update(b"\0")
    return h.hexdigest()


def expanded_layout(entries: list[str]) -> dict:
    """The supervisor-side counting/spanning pass: ``total`` (the
    expanded striping denominator), the whole-container ``spans`` and
    explicit-member ``subsets`` in FULL expanded coordinates (the
    merge-time container-verdict groups), and the expansion
    ``fingerprint`` — with every container handle closed before
    returning (the stripe runner supervises; its workers open their
    own handles).  Metadata only: no blob bytes are read."""
    ex = expand_manifest(entries)
    try:
        return ex.layout()
    finally:
        ex.close()


def _expand_into(out: ManifestExpansion, entries: list[str]) -> None:
    # one open handle per (container path, git revision) pair, shared
    # by every entry that names it
    opened: dict[tuple[str, str], object] = {}
    # explicit-member groups accumulate per container handle (manifest
    # entries naming the same container may interleave other entries)
    subset_of: dict[int, list[tuple[int, str]]] = {}

    def get_container(container: str, selector: str):
        kind = _container_kind(container)
        rev = selector if kind == "git" else ""
        key = (container, rev)
        handle = opened.get(key)
        if handle is None:
            handle = open_container(container, selector)
            opened[key] = handle
            out._containers.append(handle)
        return handle

    for entry in entries:
        parsed = split_entry(entry)
        if parsed is None:
            out.paths.append(entry)
            out.filenames.append(os.path.basename(entry))
            out._refs.append(None)
            continue
        out._any_containers = True
        container_path, selector = parsed
        if not selector:
            raise IngestError(
                f"manifest entry {entry!r}: empty selector after "
                f"'{SEP}' (want a member path, '*', or a git revision)"
            )
        kind = _container_kind(container_path)
        handle = get_container(container_path, selector)
        if kind == "git" or selector == "*":
            start = len(out.paths)
            for member in handle.members():
                out.paths.append(member)
                out.filenames.append(os.path.basename(member))
                out._refs.append((handle, member))
            out.spans.append((entry, start, len(out.paths) - start))
        else:
            # explicit single member: the DISPLAY echoes back exactly
            # as written; the routing filename is the member's own.
            # The listed members of one container form a subset group
            # — a container row over exactly what was listed.
            subset_of.setdefault(id(handle), []).append(
                (len(out.paths), selector)
            )
            if len(subset_of[id(handle)]) == 1:
                out.subsets.append(
                    (container_path, subset_of[id(handle)])
                )
            out.paths.append(entry)
            out.filenames.append(os.path.basename(selector))
            out._refs.append((handle, selector))
