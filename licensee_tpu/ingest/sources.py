"""Container-aware blob sources: stream blobs out of tarballs, zip
archives, and bare git repositories straight into the batch featurize
lane — no extraction to disk, bounded memory (only member METADATA is
held; blob bytes are read per batch by the produce workers and
dropped with the batch).

Manifest addressing grammar (the ``::`` forms)
----------------------------------------------
``path``                     a loose file (the existing manifest entry)
``archive.tar::member``      one member blob inside a tar archive
``archive.tar::*``           every regular-file member, archive order
``archive.zip::member``      one member blob inside a zip archive
``archive.zip::*``           every regular-file member, archive order
``repo.git::HEAD``           every root-tree blob of that revision
                             (any rev: branch, tag, sha — the same
                             root-level-only view as GitProject,
                             git_project.rb:64-76)

Whole-container forms (``*`` / a git revision) expand to one per-blob
work item per member, DISPLAYED by the member's own stored name — the
per-blob output rows of a container read like the project listing the
reference sees, and the container-level verdict row (verdict.py) is
the join handle that names the container.  Explicit single-member
entries echo back exactly as written.

Every reader enforces the reference's ``MAX_LICENSE_SIZE`` 64 KiB blob
cap (git_project.rb:53) by SKIPPING oversized blobs (a
:class:`SkippedBlob` marker -> an ``"error": "oversized"`` output row),
never by truncating and scoring the head.

Torn containers fail closed: a truncated tar member table, a zip with
a corrupt central directory, or a git repo whose pack cannot resolve
the revision's root tree all raise :class:`IngestError` at expansion
time — before any row is written — instead of producing a partial
container that would poison the resume invariant.

Thread-safety: tar members are read with ``os.pread`` (no shared file
offset, so produce worker threads need no lock); zip and git readers
serialize on a per-container lock (zipfile shares one seekable handle;
the native git ODB handle makes no concurrency promise).
"""

from __future__ import annotations

import hashlib
import os
import threading

from licensee_tpu.ingest import OVERSIZED, SkippedBlob

# the one blob cap, shared with the git backend (projects/git_project.py
# imports stay light: Project + subprocess only)
from licensee_tpu.projects.git_project import MAX_LICENSE_SIZE

SEP = "::"

# recognized-but-unsupported compressed tar forms: random access into a
# compressed stream is O(archive) per member, so the reader refuses
# loudly instead of quietly rescanning gigabytes per blob
_COMPRESSED_TAR_SUFFIXES = (".tar.gz", ".tgz", ".tar.bz2", ".tar.xz", ".tbz2", ".txz")


class IngestError(ValueError):
    """A container that cannot be opened or safely enumerated (torn
    archive, corrupt central directory, unresolvable git revision)."""


def split_entry(entry: str):
    """``(container, selector)`` for a ``::`` manifest entry, else None.

    Splits on the FIRST ``::`` so member names may themselves contain
    colons; a path with ``::`` whose prefix is not a recognized
    container shape is treated as a plain loose path (the read then
    fails row-contained, like any other unreadable manifest entry)."""
    if SEP not in entry:
        return None
    container, selector = entry.split(SEP, 1)
    if not container or _container_kind(container) is None:
        return None
    return container, selector


def is_container_entry(entry: str) -> bool:
    return split_entry(entry) is not None


def _container_kind(container: str) -> str | None:
    low = container.lower()
    if low.endswith(_COMPRESSED_TAR_SUFFIXES) or low.endswith(".tar"):
        return "tar"
    if low.endswith(".zip"):
        return "zip"
    if low.endswith(".git"):
        return "git"
    # a bare directory is a git container only when it LOOKS like a
    # repository (a .git entry, or the bare HEAD+objects layout) — an
    # ordinary directory path that happens to contain '::' stays a
    # plain loose path whose failed read is row-contained, exactly as
    # before containers existed
    if os.path.isdir(container) and (
        os.path.exists(os.path.join(container, ".git"))
        or (
            os.path.isfile(os.path.join(container, "HEAD"))
            and os.path.isdir(os.path.join(container, "objects"))
        )
    ):
        return "git"
    return None


class _TarContainer:
    """Random access into an UNCOMPRESSED tar: one metadata scan up
    front (name -> (offset, size)), then lock-free ``os.pread`` per
    member read."""

    def __init__(self, path: str):
        import tarfile

        if path.lower().endswith(_COMPRESSED_TAR_SUFFIXES):
            raise IngestError(
                f"compressed tar {path!r} is not supported for streaming "
                "ingestion (random access would rescan the whole stream "
                "per blob); repack as plain .tar or zip"
            )
        self.path = path
        self._members: dict[str, tuple[int, int]] = {}
        self._order: list[str] = []
        self._evidence: list[str] = []
        try:
            size = os.path.getsize(path)
            self._evidence.append(f"tar:{size}")
            with tarfile.open(path, mode="r:") as tf:
                for info in tf:
                    if not info.isreg():
                        continue  # dirs, symlinks, devices carry no blob
                    if info.offset_data + info.size > size:
                        raise IngestError(
                            f"torn archive {path!r}: member {info.name!r} "
                            f"claims {info.size} bytes past end of file"
                        )
                    if info.name not in self._members:
                        self._order.append(info.name)
                    self._members[info.name] = (info.offset_data, info.size)
                    self._evidence.append(
                        f"{info.name}@{info.offset_data}+{info.size}"
                        f":{info.mtime}:{info.chksum}"
                    )
        except tarfile.TarError as exc:
            raise IngestError(f"cannot read tar {path!r}: {exc}") from exc
        except OSError as exc:
            raise IngestError(f"cannot open tar {path!r}: {exc}") from exc
        self._fd = os.open(path, os.O_RDONLY)

    def members(self) -> list[str]:
        return list(self._order)

    def evidence(self) -> list[str]:
        """Resume-fingerprint evidence: archive size plus every
        member's (offset, size, mtime, header checksum) — a repack
        with the same member names still changes the layout/mtimes,
        so the resumed run refuses instead of mixing contents (zip
        and git evidence is exact: CRCs / object ids)."""
        return list(self._evidence)

    def read(self, member: str):
        span = self._members.get(member)
        if span is None:
            return None  # a read_error row, like an unreadable loose path
        offset, size = span
        if size > MAX_LICENSE_SIZE:
            return SkippedBlob(OVERSIZED)
        try:
            data = os.pread(self._fd, size, offset)
        except OSError:
            return None
        return data if len(data) == size else None

    def close(self) -> None:
        if self._fd is not None:
            os.close(self._fd)
            self._fd = None


class _ZipContainer:
    """zipfile-backed reads off the central directory; one shared
    seekable handle guarded by a lock."""

    def __init__(self, path: str):
        import zipfile

        self.path = path
        self._lock = threading.Lock()
        try:
            # a truncated/garbage zip fails HERE, on the central
            # directory, before any row is written; per-member CRC
            # failures later are row-contained read errors
            self._zf = zipfile.ZipFile(path)
        except (zipfile.BadZipFile, OSError) as exc:
            raise IngestError(f"cannot read zip {path!r}: {exc}") from exc
        self._infos = {
            i.filename: i for i in self._zf.infolist() if not i.is_dir()
        }
        # duplicate member names (an appended archive) collapse to ONE
        # row of the archive's effective copy — ZipFile's name table
        # resolves to the LAST occurrence, the same last-wins semantics
        # extraction (and the tar reader above) would give; emitting a
        # row per occurrence would silently score the wrong bytes for
        # all but the last
        self._order = list(dict.fromkeys(
            i.filename for i in self._zf.infolist() if not i.is_dir()
        ))

    def members(self) -> list[str]:
        return list(self._order)

    def evidence(self) -> list[str]:
        """Exact content evidence: every member's CRC + size."""
        return [
            f"{n}:{self._infos[n].CRC}:{self._infos[n].file_size}"
            for n in self._order
        ]

    def read(self, member: str):
        info = self._infos.get(member)
        if info is None:
            return None
        if info.file_size > MAX_LICENSE_SIZE:
            return SkippedBlob(OVERSIZED)
        try:
            with self._lock:
                return self._zf.read(member)
        except Exception:  # noqa: BLE001 — CRC/zlib errors are row-contained
            return None

    def close(self) -> None:
        if self._zf is not None:
            self._zf.close()
            self._zf = None


class _GitContainer:
    """A revision's root tree straight out of the object database —
    the native packfile/ODB reader when it builds, git plumbing
    subprocesses otherwise (the same backend pair as GitProject, so the
    64 KiB skip semantics cannot drift between the two)."""

    def __init__(self, path: str, revision: str):
        from licensee_tpu.projects.git_project import (
            GitProject,
            InvalidRepository,
        )

        self.path = path
        self._lock = threading.Lock()
        try:
            self._backend = GitProject._open_backend(path, revision)
            files = self._backend.files()
        except InvalidRepository as exc:
            raise IngestError(
                f"cannot open git container {path!r} at {revision!r}: {exc}"
            ) from exc
        self._files = {f["name"]: f for f in files}
        self._order = [f["name"] for f in files]

    def members(self) -> list[str]:
        return list(self._order)

    def evidence(self) -> list[str]:
        """Exact content evidence: every root entry's object id."""
        return [f"{n}:{self._files[n]['oid']}" for n in self._order]

    def read(self, member: str):
        from licensee_tpu.projects.git_project import InvalidRepository

        file = self._files.get(member)
        if file is None:
            return None
        try:
            with self._lock:
                data = self._backend.load_file(file)
        except InvalidRepository:
            return None
        # the backends answer None for exactly one reason: the blob is
        # past the MAX_LICENSE_SIZE cap (read errors raise)
        return SkippedBlob(OVERSIZED) if data is None else data

    def close(self) -> None:
        if self._backend is not None:
            self._backend.close()
            self._backend = None


def open_container(container: str, selector: str):
    """Open one container path; the selector picks git revisions
    (a git container is opened per distinct revision)."""
    kind = _container_kind(container)
    if kind == "tar":
        return _TarContainer(container)
    if kind == "zip":
        return _ZipContainer(container)
    if kind == "git":
        return _GitContainer(container, selector or "HEAD")
    raise IngestError(f"unrecognized container {container!r}")


# the loose-file read policy, bound lazily ONCE (serve/featurize.py
# imports this package's __init__, so a module-level import here would
# be circular; a per-read import statement costs a sys.modules probe
# on the hot produce path)
_READ_CAPPED = None


def _loose_read(path: str):
    global _READ_CAPPED
    if _READ_CAPPED is None:
        from licensee_tpu.serve.featurize import read_capped

        _READ_CAPPED = read_capped
    return _READ_CAPPED(path)


class ManifestExpansion:
    """The expanded manifest: per-blob display paths, the container
    spans behind them, and the positional reader the produce stage
    pulls blobs through.

    ``paths[i]`` is what the output row prints; ``read_at(i)`` loads
    the bytes (``None`` -> read_error row, :class:`SkippedBlob` ->
    skip row).  Reads are addressed BY INDEX, not by display path, so
    two containers holding the same member name can never cross wires.
    """

    def __init__(self):
        self.paths: list[str] = []
        # parallel to paths: the filename the routing/dispatch tables
        # see (the MEMBER's basename for container blobs — an explicit
        # `a.tar::LICENSE` entry must route exactly like the loose
        # LICENSE it addresses, not like its display string)
        self.filenames: list[str] = []
        # parallel to paths: None for loose files, (container, member)
        self._refs: list = []
        # whole-container groups: (entry, start, count) in manifest order
        self.spans: list[tuple[str, int, int]] = []
        self._containers: list = []

    @property
    def has_containers(self) -> bool:
        return bool(self._containers)

    def read_at(self, index: int):
        ref = self._refs[index]
        if ref is None:
            return _loose_read(self.paths[index])
        container, member = ref
        return container.read(member)

    def fingerprint(self) -> str | None:
        """sha1 over the expanded path list PLUS per-container content
        evidence (tar member offsets/sizes/mtimes/header checksums,
        zip CRCs, git object ids) — the resume sidecar's proof that a
        resumed run expands to the SAME rows of the SAME bytes.  An
        archive rewritten between runs — even one keeping every member
        name — must refuse, not silently append rows scored from
        different content after a completed prefix of the old."""
        if not self.has_containers:
            return None
        h = hashlib.sha1(usedforsecurity=False)
        for p in self.paths:
            h.update(p.encode("utf-8", "surrogatepass"))
            h.update(b"\0")
        for container in self._containers:
            for line in container.evidence():
                h.update(line.encode("utf-8", "surrogatepass"))
                h.update(b"\0")
        return h.hexdigest()

    def close(self) -> None:
        for c in self._containers:
            try:
                c.close()
            except OSError:
                pass
        self._containers = []


def expand_manifest(entries: list[str]) -> ManifestExpansion:
    """Expand raw manifest entries into per-blob work items.

    Deterministic given the manifest and the container contents —
    the property the blob-level resume invariant (line count ==
    completed prefix) rides on."""
    out = ManifestExpansion()
    try:
        _expand_into(out, entries)
    except BaseException:
        # a torn container midway through the manifest must not leak
        # the handles already opened for the containers before it
        out.close()
        raise
    return out


def _expand_into(out: ManifestExpansion, entries: list[str]) -> None:
    # one open handle per (container path, git revision) pair, shared
    # by every entry that names it
    opened: dict[tuple[str, str], object] = {}

    def get_container(container: str, selector: str):
        kind = _container_kind(container)
        rev = selector if kind == "git" else ""
        key = (container, rev)
        handle = opened.get(key)
        if handle is None:
            handle = open_container(container, selector)
            opened[key] = handle
            out._containers.append(handle)
        return handle

    for entry in entries:
        parsed = split_entry(entry)
        if parsed is None:
            out.paths.append(entry)
            out.filenames.append(os.path.basename(entry))
            out._refs.append(None)
            continue
        container_path, selector = parsed
        if not selector:
            raise IngestError(
                f"manifest entry {entry!r}: empty selector after "
                f"'{SEP}' (want a member path, '*', or a git revision)"
            )
        kind = _container_kind(container_path)
        handle = get_container(container_path, selector)
        if kind == "git" or selector == "*":
            start = len(out.paths)
            for member in handle.members():
                out.paths.append(member)
                out.filenames.append(os.path.basename(member))
                out._refs.append((handle, member))
            out.spans.append((entry, start, len(out.paths) - start))
        else:
            # explicit single member: the DISPLAY echoes back exactly
            # as written; the routing filename is the member's own
            out.paths.append(entry)
            out.filenames.append(os.path.basename(selector))
            out._refs.append((handle, selector))
