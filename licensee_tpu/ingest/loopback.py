"""A loopback HTTP blob host for benches, selftests, and tests.

Serves an in-memory name -> bytes map over real HTTP on
``127.0.0.1:0`` (stdlib ``http.server``, threaded, daemonized — the
same zero-dependency pattern as the PR 13 edge bench), speaking just
enough of the artifact-hosting dialect the remote ingest tier
(remote.py) depends on:

* ``Accept-Ranges: bytes`` + single-range ``206``/``416`` answers
* strong ``ETag`` (content sha1) and a fixed ``Last-Modified``
* ``If-Match`` -> 412 on mismatch; ``If-Range`` -> 200-full-body on
  mismatch (the two republish fences)

and the scripted FAULTS the failure-model tests need:

* ``fail_next(name, times, status=503)`` — the next N requests for
  that path answer ``status`` (the 503-then-recover rung)
* ``truncate_next(name, nbytes)`` — the next GET advertises the full
  Content-Length but tears the body after ``nbytes`` (a torn remote)
* ``latency_s`` — a per-request sleep, the injected-RTT knob the
  bench's prefetch-pipelining rung is priced with
* ``no_range = True`` — Range support vanishes (submit-probe tests)
* ``set_content(name, data)`` — republish: the ETag flips, fenced
  reads must refuse

Per-path request/range logs (``hits``, ``ranges``) let tests assert
request COUNTS — that coalescing collapsed a thousand tiny members
into few ranged reads, and that the prefetch window actually
overlapped them.
"""

from __future__ import annotations

import hashlib
import threading
import time


class LoopbackBlobHost:
    """``with LoopbackBlobHost({"a.tar": blob}) as host:`` ->
    ``host.url("a.tar")`` is a live ``http://127.0.0.1:<port>/a.tar``."""

    def __init__(self, content: dict[str, bytes] | None = None,
                 latency_s: float = 0.0):
        self._lock = threading.Lock()
        self._content: dict[str, bytes] = dict(content or {})
        self._etag: dict[str, str] = {}
        for name, data in self._content.items():
            self._etag[name] = self._make_etag(data)
        self.latency_s = latency_s
        self.no_range = False
        self.hits: dict[str, int] = {}
        self.ranges: dict[str, list[tuple[int, int]]] = {}
        self._fail: dict[str, list] = {}      # name -> [times, status]
        self._truncate: dict[str, int] = {}   # name -> body bytes kept
        self._server = None
        self._thread = None

    @staticmethod
    def _make_etag(data: bytes) -> str:
        return '"%s"' % hashlib.sha1(
            data, usedforsecurity=False
        ).hexdigest()

    # -- scripting -----------------------------------------------------

    def set_content(self, name: str, data: bytes) -> None:
        """(Re)publish a blob; the ETag flips with the bytes."""
        with self._lock:
            self._content[name] = data
            self._etag[name] = self._make_etag(data)

    def fail_next(self, name: str, times: int, status: int = 503) -> None:
        with self._lock:
            self._fail[name] = [times, status]

    def truncate_next(self, name: str, nbytes: int,
                      times: int = 1) -> None:
        with self._lock:
            self._truncate[name] = [times, nbytes]

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "LoopbackBlobHost":
        from http.server import (
            BaseHTTPRequestHandler,
            ThreadingHTTPServer,
        )

        host = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # noqa: D102 — silent
                pass

            def do_HEAD(self):  # noqa: N802 — http.server dispatch
                host._serve(self, body=False)

            def do_GET(self):  # noqa: N802 — http.server dispatch
                host._serve(self, body=True)

        self._server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self._server.daemon_threads = True
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            kwargs={"poll_interval": 0.05},
            name="loopback-blob-host",
            daemon=True,
        )
        self._thread.start()
        return self

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def url(self, name: str) -> str:
        return f"http://127.0.0.1:{self.port}/{name}"

    def close(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self) -> "LoopbackBlobHost":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # -- the request path ----------------------------------------------

    def _serve(self, handler, body: bool) -> None:
        if self.latency_s > 0:
            time.sleep(self.latency_s)
        name = handler.path.lstrip("/").split("?", 1)[0]
        with self._lock:
            self.hits[name] = self.hits.get(name, 0) + 1
            data = self._content.get(name)
            etag = self._etag.get(name)
            fail = self._fail.get(name)
            if fail is not None and fail[0] > 0:
                fail[0] -= 1
                status = fail[1]
            else:
                status = None
            truncate = None
            if body:
                tr = self._truncate.get(name)
                if tr is not None and tr[0] > 0:
                    tr[0] -= 1
                    truncate = tr[1]
        if status is not None:
            self._answer(handler, status, b"scripted fault")
            return
        if data is None:
            self._answer(handler, 404, b"no such blob")
            return
        if_match = handler.headers.get("If-Match")
        if if_match is not None and if_match != etag:
            self._answer(handler, 412, b"precondition failed")
            return
        rng = None
        if not self.no_range:
            rng = self._parse_range(
                handler.headers.get("Range"), len(data)
            )
            if rng == "bad":
                handler.send_response(416)
                handler.send_header(
                    "Content-Range", f"bytes */{len(data)}"
                )
                handler.send_header("Content-Length", "0")
                handler.end_headers()
                return
            if_range = handler.headers.get("If-Range")
            if rng is not None and if_range is not None and (
                if_range != etag
            ):
                rng = None  # fence tripped: full (new) body, 200
        status = 206 if rng is not None else 200
        lo, hi = rng if rng is not None else (0, len(data) - 1)
        payload = data[lo:hi + 1] if data else b""
        with self._lock:
            if rng is not None:
                self.ranges.setdefault(name, []).append((lo, hi))
        handler.send_response(status)
        handler.send_header("ETag", etag)
        handler.send_header("Last-Modified",
                            "Thu, 01 Jan 2026 00:00:00 GMT")
        if not self.no_range:
            handler.send_header("Accept-Ranges", "bytes")
        if rng is not None:
            handler.send_header(
                "Content-Range", f"bytes {lo}-{hi}/{len(data)}"
            )
        handler.send_header("Content-Length", str(len(payload)))
        handler.end_headers()
        if body:
            if truncate is not None and truncate < len(payload):
                # a torn body: full Content-Length promised, fewer
                # bytes delivered, connection dropped
                try:
                    handler.wfile.write(payload[:truncate])
                    handler.wfile.flush()
                finally:
                    handler.close_connection = True
                    try:
                        handler.connection.close()
                    except OSError:
                        pass
                return
            handler.wfile.write(payload)

    @staticmethod
    def _parse_range(header, size: int):
        """``bytes=a-b`` -> (a, b) clamped; None when absent/ignorable;
        ``"bad"`` for an unsatisfiable range (-> 416)."""
        if not header or not header.startswith("bytes=") or "," in header:
            return None
        spec = header[len("bytes="):]
        lo_s, _, hi_s = spec.partition("-")
        try:
            if lo_s == "":
                n = int(hi_s)  # suffix range: last n bytes
                if n <= 0:
                    return "bad"
                return max(0, size - n), size - 1
            lo = int(lo_s)
            hi = int(hi_s) if hi_s else size - 1
        except ValueError:
            return None
        if lo >= size or hi < lo:
            return "bad"
        return lo, min(hi, size - 1)

    @staticmethod
    def _answer(handler, status: int, msg: bytes) -> None:
        handler.send_response(status)
        handler.send_header("Content-Length", str(len(msg)))
        handler.end_headers()
        handler.wfile.write(msg)
