"""LICENSE-style files: filename scoring + Copyright/Exact/Dice chain.

Parity target: `lib/licensee/project_files/license_file.rb` — the 19-entry
ordered filename score table, the CC false-positive guard, attribution
extraction, and the unmatched-but-scored -> `other` fallback.
"""

from __future__ import annotations

import re

from licensee_tpu.normalize.pipeline import COPYRIGHT_REGEX, NormalizedContent
from licensee_tpu.project_files.project_file import ProjectFile
from licensee_tpu.rubytext import rb, ruby_strip

# license_file.rb:8-30 filename building blocks
PREFERRED_EXT = ("md", "markdown", "txt", "html")
PREFERRED_EXT_REGEX = r"\.(?:" + "|".join(PREFERRED_EXT) + r")\Z"
LICENSE_EXT_REGEX = r"\.(?!spdx|header)(?:[^./]|\.\d)+\Z"
OTHER_EXT_REGEX = r"\.(?!xml|go|gemspec)(?:[^./]|\.\d)+\Z"
ANY_EXT_REGEX = r"\.(?:[^./]|\.\d)+\Z"
LICENSE_REGEX = r"(?:un)?licen[sc]e"
COPYING_REGEX = r"copying"
COPYRIGHT_FILE_REGEX = r"copyright"
OFL_REGEX = r"ofl"
PATENTS_REGEX = r"patents"

# license_file.rb:38-59: ordered filename -> score table (first match wins)
FILENAME_SCORES = [
    (rb(r"\A" + LICENSE_REGEX + r"\Z", i=True), 1.00),                              # LICENSE
    (rb(r"\A" + LICENSE_REGEX + PREFERRED_EXT_REGEX, i=True), 0.95),                # LICENSE.md
    (rb(r"\A" + COPYING_REGEX + r"\Z", i=True), 0.90),                              # COPYING
    (rb(r"\A" + COPYING_REGEX + PREFERRED_EXT_REGEX, i=True), 0.85),                # COPYING.md
    (rb(r"\A" + LICENSE_REGEX + LICENSE_EXT_REGEX, i=True), 0.80),                  # LICENSE.textile
    (rb(r"\A" + COPYING_REGEX + ANY_EXT_REGEX, i=True), 0.75),                      # COPYING.textile
    (rb(r"\A" + LICENSE_REGEX + r"[-_][^.]*(?:" + OTHER_EXT_REGEX + r")?\Z", i=True), 0.70),  # LICENSE-MIT
    (rb(r"\A" + COPYING_REGEX + r"[-_][^.]*(?:" + OTHER_EXT_REGEX + r")?\Z", i=True), 0.65),  # COPYING-MIT
    (rb(r"\A\w+[-_]" + LICENSE_REGEX + r"[^.]*(?:" + OTHER_EXT_REGEX + r")?\Z", i=True), 0.60),  # MIT-LICENSE-MIT
    (rb(r"\A\w+[-_]" + COPYING_REGEX + r"[^.]*(?:" + OTHER_EXT_REGEX + r")?\Z", i=True), 0.55),  # MIT-COPYING
    (rb(r"\A" + OFL_REGEX + PREFERRED_EXT_REGEX, i=True), 0.50),                    # OFL.md
    (rb(r"\A" + OFL_REGEX + OTHER_EXT_REGEX, i=True), 0.45),                        # OFL.textile
    (rb(r"\A" + OFL_REGEX + r"\Z", i=True), 0.40),                                  # OFL
    (rb(r"\A" + COPYRIGHT_FILE_REGEX + r"\Z", i=True), 0.35),                       # COPYRIGHT
    (rb(r"\A" + COPYRIGHT_FILE_REGEX + PREFERRED_EXT_REGEX, i=True), 0.30),         # COPYRIGHT.txt
    (rb(r"\A" + COPYRIGHT_FILE_REGEX + OTHER_EXT_REGEX, i=True), 0.25),             # COPYRIGHT.textile
    (rb(r"\A" + COPYRIGHT_FILE_REGEX + r"[-_][^.]*(?:" + OTHER_EXT_REGEX + r")?\Z", i=True), 0.20),  # COPYRIGHT-MIT
    (rb(r"\A" + PATENTS_REGEX + r"\Z", i=True), 0.15),                              # PATENTS
    (rb(r"\A" + PATENTS_REGEX + OTHER_EXT_REGEX, i=True), 0.10),                    # PATENTS.txt
    (rb(r""), 0.00),                                                               # catch-all
]

# the copyright? filename test (project_file.rb:94): a COPYRIGHT(.ext)
# file — shared by ProjectFile.is_copyright and the batch attribution gate
COPYRIGHT_NAME_REGEX = rb(
    r"\Acopyright(?:" + OTHER_EXT_REGEX + r")?\Z", i=True
)

# license_file.rb:61-65: CC-NC / CC-ND must not be detected as CC-BY(-SA)
CC_FALSE_POSITIVE_REGEX = rb(
    r"^(creative\ commons\ )?Attribution-(NonCommercial|NoDerivatives)", i=True, x=True
)


class LicenseFile(NormalizedContent, ProjectFile):
    @property
    def possible_matchers(self) -> list:
        from licensee_tpu.matchers import Copyright, Dice, Exact

        return [Copyright, Exact, Dice]

    @property
    def attribution(self) -> str | None:
        """The copyright/attribution line, when the matched license carries a
        [fullname] field (license_file.rb:71-77)."""
        cached = self.__dict__.get("_attribution")
        if cached is None:
            cached = None
            license = self.license
            applicable = self.is_copyright or (
                license is not None
                and license.content is not None
                and "[fullname]" in license.content
            )
            if applicable:
                m = COPYRIGHT_REGEX.search(self.content_without_title_and_version)
                cached = m.group(0) if m else None
            self.__dict__["_attribution"] = cached
        return cached

    @property
    def potential_false_positive(self) -> bool:
        return bool(CC_FALSE_POSITIVE_REGEX.search(ruby_strip(self.content or "")))

    @property
    def is_lgpl(self) -> bool:
        return LicenseFile.lesser_gpl_score(self.filename) == 1 and bool(
            self.license and self.license.lgpl_q
        )

    @property
    def is_gpl(self) -> bool:
        return bool(self.license and self.license.gpl_q)

    @property
    def license(self):
        """A scored license file that fails all matchers is still 'other' —
        it looked like a license but we couldn't identify it
        (license_file.rb:92-98)."""
        from licensee_tpu.corpus.license import License

        if self.matcher and self.matcher.match:
            return self.matcher.match
        return License.find("other")

    def _serialized_content_normalized(self):
        return self.content_normalized()

    @staticmethod
    def name_score(filename: str) -> float:
        for regex, score in FILENAME_SCORES:
            if regex.search(filename):
                return score
        return 0.0

    @staticmethod
    def lesser_gpl_score(filename: str | None) -> int:
        """COPYING.lesser gets LGPL priority (license_file.rb:105-107)."""
        return 1 if filename is not None and filename.lower() == "copying.lesser" else 0
