"""Package-manager manifests (package.json, *.gemspec, Cargo.toml, ...).

Parity target: `lib/licensee/project_files/package_manager_file.rb`.
"""

from __future__ import annotations

import os

from licensee_tpu.project_files.project_file import ProjectFile


class PackageManagerFile(ProjectFile):
    @property
    def possible_matchers(self) -> list:
        from licensee_tpu import matchers

        ext_map = {
            ".gemspec": [matchers.Gemspec],
            ".json": [matchers.NpmBower],
            ".cabal": [matchers.Cabal],
            ".nuspec": [matchers.NuGet],
        }
        name_map = {
            "DESCRIPTION": [matchers.Cran],
            "dist.ini": [matchers.DistZilla],
            "LICENSE.spdx": [matchers.Spdx],
            "Cargo.toml": [matchers.Cargo],
        }
        ext = os.path.splitext(self.filename or "")[1]
        return ext_map.get(ext) or name_map.get(self.filename) or []

    FILENAMES_SCORES = {
        "package.json": 1.0,
        "LICENSE.spdx": 1.0,
        "Cargo.toml": 1.0,
        "DESCRIPTION": 0.9,
        "dist.ini": 0.8,
        "bower.json": 0.75,
        "elm-package.json": 0.7,
    }

    @staticmethod
    def name_score(filename: str) -> float:
        if os.path.splitext(filename)[1] in (".gemspec", ".cabal", ".nuspec"):
            return 1.0
        return PackageManagerFile.FILENAMES_SCORES.get(filename, 0.0)
