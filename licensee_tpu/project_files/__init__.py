from licensee_tpu.project_files.project_file import ProjectFile
from licensee_tpu.project_files.license_file import LicenseFile
from licensee_tpu.project_files.readme_file import ReadmeFile
from licensee_tpu.project_files.package_manager_file import PackageManagerFile

__all__ = ["ProjectFile", "LicenseFile", "ReadmeFile", "PackageManagerFile"]
