"""README files: extract the "## License" section and match it.

Parity target: `lib/licensee/project_files/readme_file.rb` — filename
scores, the header lookbehind/lookahead content regex (markdown `#`, rdoc
`=`, and underlined headers), and the Reference matcher appended to the
LicenseFile chain.
"""

from __future__ import annotations

from licensee_tpu.project_files.license_file import LicenseFile
from licensee_tpu.rubytext import rb, ruby_strip

EXTENSIONS = ("md", "markdown", "mdown", "txt", "rdoc", "rst")

_SCORES = [
    (rb(r"\AREADME\Z", i=True), 1.0),
    (rb(r"\AREADME\.(?:" + "|".join(EXTENSIONS) + r")\Z", i=True), 0.9),
]

_TITLE = r"licen[sc]e:?"
_UNDERLINE = r"\n[-=]+"

CONTENT_REGEX = rb(
    r"^"
    r"(?:"
    r"[\#=]+\s" + _TITLE + r"\s*[\#=]*"
    r"|" + _TITLE + _UNDERLINE +
    r")$"
    r"(.*?)"
    r"(?=^"
    r"(?:"
    r"[\#=]+"
    r"|"
    r"[^\n]+" + _UNDERLINE +
    r")"
    r"|"
    r"\Z"
    r")",
    i=True,
    m=True,
)


class ReadmeFile(LicenseFile):
    @property
    def possible_matchers(self) -> list:
        from licensee_tpu.matchers import Reference

        return super().possible_matchers + [Reference]

    @staticmethod
    def name_score(filename: str) -> float:
        for pattern, score in _SCORES:
            if pattern.search(filename):
                return score
        return 0.0

    @staticmethod
    def license_content(content: str | None) -> str | None:
        if content is None:
            return None
        m = CONTENT_REGEX.search(content)
        return ruby_strip(m.group(1)) if m else None
