"""README files: extract the "## License" section and match it.

Parity target: `lib/licensee/project_files/readme_file.rb` — filename
scores, the header lookbehind/lookahead content regex (markdown `#`, rdoc
`=`, and underlined headers), and the Reference matcher appended to the
LicenseFile chain.

Perf ADR (r5): the readme route's top featurize item was the one-shot
CONTENT_REGEX (~55us/blob on full-text sections — lazy `(.*?)` with a
line-anchored lookahead evaluated per character); license_content now
runs its two halves as linear scans behind a `licen` substring pre-check
(~4-8us typical), differential-pinned to the one-shot form in
tests/test_file_scoring.py.  Readme e2e: 7.4k -> ~9.7k files/s solo;
the remainder is the native featurize crossing on full-body sections —
the same floor as the license route (host-model ADR,
projects/batch_project.py).
"""

from __future__ import annotations

from licensee_tpu.project_files.license_file import LicenseFile
from licensee_tpu.rubytext import rb, ruby_strip

EXTENSIONS = ("md", "markdown", "mdown", "txt", "rdoc", "rst")

_SCORES = [
    (rb(r"\AREADME\Z", i=True), 1.0),
    (rb(r"\AREADME\.(?:" + "|".join(EXTENSIONS) + r")\Z", i=True), 0.9),
]

_TITLE = r"licen[sc]e:?"
_UNDERLINE = r"\n[-=]+"

# the two halves of the section extraction, shared between the one-shot
# CONTENT_REGEX (the documented Ruby-parity form) and the staged fast
# path below — single source so a parity fix cannot diverge them
_HEADING_SRC = (
    r"(?:[\#=]+\s" + _TITLE + r"\s*[\#=]*|" + _TITLE + _UNDERLINE + r")"
)
_NEXT_SRC = r"(?:[\#=]+|[^\n]+" + _UNDERLINE + r")"

CONTENT_REGEX = rb(
    r"^" + _HEADING_SRC + r"$"
    r"(.*?)"
    r"(?=^" + _NEXT_SRC + r"|\Z)",
    i=True,
    m=True,
)

# license_content runs CONTENT_REGEX's two halves as separate scans:
# the one-shot regex pays its lazy `(.*?)` + line-anchored lookahead at
# every character of a full-text license section (~55us/blob on 10KB
# bodies — the top featurize item of the readme route, bench r4), while
# heading-search + next-section-search are two linear C scans (~5us).
# Equivalence with the one-shot form (pinned differentially by
# tests/test_file_scoring.py::
# test_readme_license_content_matches_one_shot_regex): re.search stops
# at the FIRST heading position, where the remainder `(.*?)(?=NEXT|\Z)`
# always succeeds and lazily stops exactly at the first NEXT match after
# the heading (or end-of-text) —
# i.e. content[heading.end() : next.start() or len].
_HEADING_REGEX = rb(r"^" + _HEADING_SRC + r"$", i=True)
_NEXT_SECTION_REGEX = rb(r"^" + _NEXT_SRC, i=True)


class ReadmeFile(LicenseFile):
    @property
    def possible_matchers(self) -> list:
        from licensee_tpu.matchers import Reference

        return super().possible_matchers + [Reference]

    @staticmethod
    def name_score(filename: str) -> float:
        for pattern, score in _SCORES:
            if pattern.search(filename):
                return score
        return 0.0

    @staticmethod
    def license_content(content: str | None) -> str | None:
        if content is None:
            return None
        if "licen" not in content.lower():
            # every heading the regex accepts contains licen[sc]e; the
            # substring scan is ~10x cheaper than the regex scan for the
            # no-section majority of a real README corpus (and `licen`
            # needs no Unicode-lowercase subtleties: re.A is on anyway)
            return None
        m = _HEADING_REGEX.search(content)
        if m is None:
            return None
        nxt = _NEXT_SECTION_REGEX.search(content, m.end())
        section = content[m.end() : nxt.start() if nxt else len(content)]
        return ruby_strip(section)
