"""Candidate-file classification unit.

Parity target: `lib/licensee/project_files/project_file.rb`.  Owns content
and metadata; sanitizes encoding (UTF-8 with invalid sequences dropped,
universal newlines); runs the first-match-wins matcher chain.
"""

from __future__ import annotations

import os
import re

_UNSET = object()


def sanitize_content(content: str | bytes) -> str:
    """UTF-8 coercion with invalid bytes dropped + universal newlines
    (project_file.rb:37-45)."""
    if isinstance(content, bytes):
        content = content.decode("utf-8", errors="ignore")
    else:
        # Round-trip to drop lone surrogates from earlier lossy decodes
        content = content.encode("utf-8", errors="ignore").decode("utf-8", errors="ignore")
    return content.replace("\r\n", "\n").replace("\r", "\n")


class ProjectFile:
    def __init__(self, content: str | bytes | None, metadata=None):
        self.content = sanitize_content(content) if content is not None else None
        if isinstance(metadata, str):
            metadata = {"name": metadata}
        self.data = metadata or {}

    @property
    def filename(self) -> str | None:
        return self.data.get("name")

    path = filename

    @property
    def directory(self) -> str:
        return self.data.get("dir") or "."

    dir = directory

    @property
    def path_relative_to_root(self) -> str:
        return os.path.join(self.directory, self.filename)

    relative_path = path_relative_to_root

    @property
    def possible_matchers(self) -> list:
        raise NotImplementedError

    @property
    def matcher(self):
        """First matcher in the chain that produces a match
        (project_file.rb:65-71)."""
        cached = self.__dict__.get("_matcher", _UNSET)
        if cached is _UNSET:
            cached = None
            for matcher_cls in self.possible_matchers:
                candidate = matcher_cls(self)
                if candidate.match:
                    cached = candidate
                    break
            self.__dict__["_matcher"] = cached
        return cached

    @property
    def confidence(self):
        return self.matcher.confidence if self.matcher else None

    @property
    def license(self):
        return self.matcher.match if self.matcher else None

    match = license

    @property
    def matched_license(self) -> str | None:
        return self.license.spdx_id if self.license else None

    @property
    def is_copyright(self) -> bool:
        """COPYRIGHT file holding only a copyright statement — excluded when
        deciding if a project is multi-licensed (project_file.rb:90-95)."""
        from licensee_tpu.matchers.copyright_matcher import Copyright
        from licensee_tpu.project_files.license_file import (
            COPYRIGHT_NAME_REGEX,
            LicenseFile,
        )

        if not isinstance(self, LicenseFile):
            return False
        if not isinstance(self.matcher, Copyright):
            return False
        return bool(COPYRIGHT_NAME_REGEX.search(self.filename or ""))

    @property
    def content_hash(self):
        return None

    @property
    def attribution(self):
        return None

    def _serialized_content_normalized(self):
        return None

    def to_h(self) -> dict:
        # project_file.rb:16-19 HASH_METHODS
        return {
            "filename": self.filename,
            "content": self.content,
            "content_hash": self.content_hash,
            "content_normalized": self._serialized_content_normalized(),
            "matcher": self.matcher.to_h() if self.matcher else None,
            "matched_license": self.matched_license,
            "attribution": self.attribution,
        }
