"""Pallas TPU kernel for the Dice bit-matrix scoring hot loop.

Fuses everything `dice_xla.score_pairs` does in XLA HLO — bitset
intersection, popcount, and the exact int32 score algebra of the
reference (`content_helper.rb:128-133`, `337-347`) — into one Mosaic
kernel whose B×T×W intersection never materialises in HBM.  The
(numerator, denominator) output is bit-identical to the XLA path;
ranking/threshold finishing reuses `dice_xla._argmax_exact`.

Structure (deliberately grid-free):
  * the file bitset slab and the per-file scalar columns stay in HBM
    (`memory_space=ANY`); the kernel walks batch tiles with
    `lax.fori_loop`, double-buffering each (TILE_B, W) tile into VMEM
    with explicit `make_async_copy` DMA so the copy of tile i+1
    overlaps the scoring of tile i; results are DMA'd back out of VMEM
    the same way, so HBM-resident output puts no ceiling on batch size.
  * the whole (T, W) template matrix lives in VMEM (T≈48–640, W≈128
    lanes → ≤0.3 MiB) together with a (T, 8) int32 table of the
    per-template score constants.
  * per tile, an inner `fori_loop` walks templates in blocks of 8
    (one sublane group): a (8, TILE_B, W) broadcast intersection
    reduces over lanes to a (8, TILE_B) block whose layout already
    matches the (T, TILE_B) output — no in-kernel transposes, and all
    dynamic indices stay on non-lane dimensions.
  * popcount is SWAR on uint32 (sub/mask/mul/shift — pure VPU ops).

Why no `grid=`: on the axon remote-compile backend every gridded
pallas_call currently dies in Mosaic ("failed to legalize
func.return"); ungridded kernels compile and run fine — and the manual
pipeline gives the same overlap a gridded emission would.

On non-TPU backends the kernel runs in interpreter mode (what the CPU
test suite exercises); numerics are identical either way.

ADR — why the default scoring path is XLA, not these kernels
------------------------------------------------------------
Measured on a real v5e-1 (2026-07-30, B=262144, batch.py shapes; full
table reproduced by `python bench.py`):

    method        T=47 (vendored)   T=608 (full SPDX width)
    xla popcount      35.5 M/s            3.9 M/s
    xla matmul        34.5 M/s            8.6 M/s   <- winner at width
    pallas (SWAR)     23.6 M/s            1.6 M/s
    pallas-mxu         9.0 M/s            3.6 M/s

* The SWAR kernel is VPU-bound: ~20 vector ops per (8, TILE_B, W)
  block scale linearly with T, so it falls furthest behind exactly
  where the corpus grows.  Its DMA pipeline is sound — it just races
  a systolic array with an ALU.
* The MXU variant fuses the int8 unpack into VMEM (the XLA matmul
  path round-trips a ~2 GiB unpacked LHS through HBM), but the
  in-kernel unpack pays a u32->int8 relayout per slice (32-bit (8,128)
  tiling to 8-bit (32,128) tiling) that dominates at small T, and the
  Mosaic int8 dot lowers well below the MXU's int8 peak (~65 TOPS
  observed incl. unpack vs ~394 peak), so fusion never recovers what
  the dot loses.  T-scaling is right (fixed unpack + linear dot); the
  constant is not.
* XLA's own unpack+dot_general pipelines the same MXU at higher
  utilization, and its popcount path vectorizes the whole B×T×W
  intersection better than the hand-tiled loop at small T.

Decision: `BatchClassifier(method="auto")` picks popcount for T<=128
and matmul above; both pallas kernels stay as bit-identical,
fully-tested alternates (`--method pallas|pallas-mxu`) and as the
in-tree reference for manual DMA pipelining and fused MXU feeding on
this toolchain.  Revisit if Mosaic's int8 dot reaches native rate —
the MXU variant's VMEM arithmetic then beats the HBM round-trip by
construction.  The device is >99% idle against the host featurizer
either way (see bench.py end_to_end), so the end-to-end number does
not move with this choice.

ADR addendum (round 4) — TP at full SPDX width does not pay
-----------------------------------------------------------
Measured single-chip at T=608 (bench.py bench_tp_width, v5e-1,
2026-07-30): slicing the lane axis in half — exactly the per-chip
shape of a TP=2 model-axis shard (parallel/mesh.py:127-167) — lifts
matmul only 1.08x (8.41 -> 9.08 M/s; popcount 1.31x), and a real TP=2
pays an ICI psum on top.  So the T=608-vs-T=47 gap (8.6 vs 34.5 M/s)
is NOT the 32x unpack's HBM round-trip: it is template-axis MXU
compute — 12.9x more (blob, template) pairs for a ~4x rate drop, i.e.
MXU utilization actually rises with T.  Model-axis sharding therefore
cannot recover the full-width rate; it remains an HBM-capacity lever
(T x V matrices that outgrow one chip), while throughput scales with
DP over the data axis.  The earlier attribution of the gap to the
unpack (round-3 ADR draft) is corrected by this measurement.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from licensee_tpu.kernels.dice_xla import CorpusArrays, _argmax_exact

LANE = 128          # TPU lane width; W and TILE_B are padded to multiples
SUBLANE = 8         # sublane granularity for 32-bit dtypes
TPL_BLOCK = 8       # templates scored per inner step (one sublane group)
DEFAULT_TILE_B = 256
N_BUFFERS = 2


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


def _popcount_u32(v: jnp.ndarray) -> jnp.ndarray:
    """SWAR popcount over uint32 lanes (Hacker's Delight 5-2)."""
    c55 = jnp.uint32(0x55555555)
    c33 = jnp.uint32(0x33333333)
    c0f = jnp.uint32(0x0F0F0F0F)
    c01 = jnp.uint32(0x01010101)
    v = v - ((v >> jnp.uint32(1)) & c55)
    v = (v & c33) + ((v >> jnp.uint32(2)) & c33)
    v = (v + (v >> jnp.uint32(4))) & c0f
    return ((v * c01) >> jnp.uint32(24)).astype(jnp.int32)


# meta-table column indices (meta is int32[T_pad, 8], one row per template)
_N_WF, _N_FIELDSET, _FIELD_COUNT, _ALT_COUNT, _LENGTH, _CC_FLAG, _VALID = range(7)
_META_COLS = 8  # padded to a full sublane group


def _make_kernel(n_templates: int, tile_b: int, n_tiles: int):
    n_tpl_blocks = n_templates // TPL_BLOCK

    def kernel(meta_ref, tpl_ref, file_hbm, cols_hbm,
               num_hbm, den_hbm, tile_buf, col_buf, out_buf,
               copy_sems, col_sems, out_sems):
        # every literal is pinned to int32: weak-typed Python ints would
        # promote to int64 under the global jax_enable_x64, which Mosaic
        # cannot lower
        i0, i1_, i4, i5 = (jnp.int32(v) for v in (0, 1, 4, 5))
        nb = jnp.int32(N_BUFFERS)

        def in_dma(slot, tile):
            return pltpu.make_async_copy(
                file_hbm.at[pl.ds(tile * tile_b, tile_b), :],
                tile_buf.at[slot],
                copy_sems.at[slot],
            )

        def col_dma(slot, tile):
            # (4, B) layout keeps the sliced dimension on lanes, where
            # tile_b offsets are 128-aligned as DMA requires
            return pltpu.make_async_copy(
                cols_hbm.at[:, pl.ds(tile * tile_b, tile_b)],
                col_buf.at[slot],
                col_sems.at[slot],
            )

        def out_dma(slot, tile):
            # out_buf[slot] is (2, T, TILE_B): num and den planes together.
            # plane indices are pinned int32: a bare Python literal would
            # become an i64 memref index under jax_enable_x64
            return pltpu.make_async_copy(
                out_buf.at[slot, i0],
                num_hbm.at[tile],
                out_sems.at[slot, i0],
            ), pltpu.make_async_copy(
                out_buf.at[slot, i1_],
                den_hbm.at[tile],
                out_sems.at[slot, i1_],
            )

        in_dma(jnp.int32(0), jnp.int32(0)).start()
        col_dma(jnp.int32(0), jnp.int32(0)).start()

        def tile_body(tile, carry):
            slot = lax.rem(tile, nb)
            next_slot = lax.rem(tile + i1_, nb)

            @pl.when(tile + i1_ < jnp.int32(n_tiles))
            def _():
                in_dma(next_slot, tile + i1_).start()
                col_dma(next_slot, tile + i1_).start()

            in_dma(slot, tile).wait()
            col_dma(slot, tile).wait()

            # the result DMA issued for this slot two tiles ago must have
            # drained before out_buf[slot] is overwritten
            @pl.when(tile >= nb)
            def _():
                for d in out_dma(slot, tile - nb):
                    d.wait()

            file_bits = tile_buf[slot]                       # (TILE_B, W)
            cols = col_buf[slot]                             # (4, TILE_B)
            n_words = cols[0:1, :]                           # (1, TILE_B)
            lengths = cols[1:2, :]
            cc_fp = cols[2:3, :]

            def tpl_body(tb, c):
                t0 = tb * jnp.int32(TPL_BLOCK)
                tpl_block = tpl_ref[pl.ds(t0, TPL_BLOCK), :]    # (8, W)
                inter = file_bits[None, :, :] & tpl_block[:, None, :]
                overlap = jnp.sum(_popcount_u32(inter), axis=-1,
                                  dtype=jnp.int32)              # (8, TILE_B)

                mv = meta_ref[pl.ds(t0, TPL_BLOCK), :]          # (8, 8)
                n_wf = mv[:, _N_WF:_N_WF + 1]                   # (8, 1)
                n_fieldset = mv[:, _N_FIELDSET:_N_FIELDSET + 1]
                field_count = mv[:, _FIELD_COUNT:_FIELD_COUNT + 1]
                alt_count = mv[:, _ALT_COUNT:_ALT_COUNT + 1]
                tpl_len = mv[:, _LENGTH:_LENGTH + 1]
                cc_flag = mv[:, _CC_FLAG:_CC_FLAG + 1]
                valid = mv[:, _VALID:_VALID + 1]

                total = n_wf + n_words - n_fieldset             # (8, TILE_B)
                delta = jnp.abs(tpl_len - lengths)
                adj = jnp.maximum(
                    delta - i5 * jnp.maximum(field_count, alt_count), i0)
                denom = total + adj // i4

                excluded = ((cc_flag == i1_) & (cc_fp == i1_)) | (valid == i0)
                num_blk = jnp.where(excluded, jnp.int32(-1), overlap)
                den_blk = jnp.where(excluded | (denom <= i0), i1_, denom)

                out_buf[slot, i0, pl.ds(t0, TPL_BLOCK), :] = num_blk
                out_buf[slot, i1_, pl.ds(t0, TPL_BLOCK), :] = den_blk
                return c

            lax.fori_loop(i0, jnp.int32(n_tpl_blocks), tpl_body, i0)

            for d in out_dma(slot, tile):
                d.start()
            return carry

        lax.fori_loop(jnp.int32(0), jnp.int32(n_tiles), tile_body,
                      jnp.int32(0))

        # drain the last N_BUFFERS result copies
        for k in range(min(N_BUFFERS, n_tiles)):
            tile = jnp.int32(n_tiles - 1 - k)
            for d in out_dma(lax.rem(tile, nb), tile):
                d.wait()

    return kernel


def _should_interpret() -> bool:
    return jax.default_backend() not in ("tpu",)


# ---------------------------------------------------------------------------
# MXU variant: fused unpack + int8 systolic contraction
# ---------------------------------------------------------------------------
#
# The XLA matmul path (`dice_xla._overlap_matmul`) unpacks the whole
# uint32[B, W] slab to int8[B, 32W] before the dot — at B=256k, W=256
# that is a ~2 GiB HBM intermediate written and re-read around the MXU.
# This kernel keeps the blow-up in VMEM: each (TILE_B, W) tile is DMA'd
# in packed, unpacked to int8[TILE_B, 32W] on the VPU (32 unrolled
# shift-and-mask ops), and contracted against the VMEM-resident unpacked
# template matrix on the MXU — so HBM only ever carries the 32×-smaller
# packed bits plus the (B, T) overlap result.
#
# Bit layout is BIT-MAJOR (column i*W + w holds bit i of lane w), not the
# w*32+i order of `dice_xla._unpack_bits`: bit-major lets the in-kernel
# unpack write 32 contiguous lane-aligned (TILE_B, W) slices instead of a
# stride-32 scatter.  The template matrix is unpacked once on host in the
# same order (`_unpack_bits_bitmajor`), and the dot contracts the shared
# V axis, so the order never escapes the kernel.


def _unpack_bits_bitmajor(packed: np.ndarray) -> np.ndarray:
    """uint32[N, W] -> int8[N, 32*W], column i*W + w = bit i of lane w."""
    N, W = packed.shape
    expanded = (
        packed[:, None, :] >> np.arange(32, dtype=np.uint32)[None, :, None]
    ) & np.uint32(1)
    return expanded.astype(np.int8).reshape(N, 32 * W)


def _make_mxu_kernel(n_templates: int, tile_b: int, n_tiles: int, w: int):
    def kernel(tpl_ref, file_hbm, out_hbm, tile_buf, unpacked_buf,
               out_buf, copy_sems, out_sems):
        i0, i1_ = jnp.int32(0), jnp.int32(1)
        nb = jnp.int32(N_BUFFERS)

        def in_dma(slot, tile):
            return pltpu.make_async_copy(
                file_hbm.at[pl.ds(tile * tile_b, tile_b), :],
                tile_buf.at[slot],
                copy_sems.at[slot],
            )

        def out_dma(slot, tile):
            return pltpu.make_async_copy(
                out_buf.at[slot],
                out_hbm.at[pl.ds(tile * tile_b, tile_b), :],
                out_sems.at[slot],
            )

        in_dma(jnp.int32(0), jnp.int32(0)).start()

        def tile_body(tile, carry):
            slot = lax.rem(tile, nb)
            next_slot = lax.rem(tile + i1_, nb)

            @pl.when(tile + i1_ < jnp.int32(n_tiles))
            def _():
                in_dma(next_slot, tile + i1_).start()

            in_dma(slot, tile).wait()

            @pl.when(tile >= nb)
            def _():
                out_dma(slot, tile - nb).wait()

            packed = tile_buf[slot]                      # (TILE_B, W) u32
            # VPU unpack: 32 contiguous (TILE_B, W) int8 slices
            for i in range(32):
                bit = (packed >> jnp.uint32(i)) & jnp.uint32(1)
                unpacked_buf[:, i * w : (i + 1) * w] = bit.astype(jnp.int8)

            # the 128×128 systolic contraction over V = 32W; templates are
            # stored (V, T) so the MXU reads both operands in layout —
            # a (T, V) rhs would cost a VMEM transpose copy (and the VMEM
            # headroom for one: 5 MiB at T=640)
            out_buf[slot] = lax.dot_general(
                unpacked_buf[:, :],
                tpl_ref[:, :],
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32,
            )

            out_dma(slot, tile).start()
            return carry

        lax.fori_loop(jnp.int32(0), jnp.int32(n_tiles), tile_body,
                      jnp.int32(0))
        for k in range(min(N_BUFFERS, n_tiles)):
            tile = jnp.int32(n_tiles - 1 - k)
            out_dma(lax.rem(tile, nb), tile).wait()

    return kernel


@functools.partial(jax.jit, static_argnames=("tile_b", "interpret"))
def _overlap_mxu_padded(tpl_unpacked, file_bits, tile_b: int,
                        interpret: bool):
    """overlap int32[B, T] with B % tile_b == 0, W % LANE == 0, and
    T % MXU_TPL_ALIGN == 0; `tpl_unpacked` is int8[32W, T]."""
    B, W = file_bits.shape
    T = tpl_unpacked.shape[1]
    n_tiles = B // tile_b

    return pl.pallas_call(
        _make_mxu_kernel(T, tile_b, n_tiles, W),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),   # unpacked templates
            pl.BlockSpec(memory_space=pl.ANY),       # packed file slab
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        out_shape=jax.ShapeDtypeStruct((B, T), jnp.int32),
        scratch_shapes=[
            pltpu.VMEM((N_BUFFERS, tile_b, W), jnp.uint32),
            pltpu.VMEM((tile_b, 32 * W), jnp.int8),
            pltpu.VMEM((N_BUFFERS, tile_b, T), jnp.int32),
            pltpu.SemaphoreType.DMA((N_BUFFERS,)),
            pltpu.SemaphoreType.DMA((N_BUFFERS,)),
        ],
        interpret=interpret,
    )(tpl_unpacked, file_bits)


# T is the lane dimension of both the dot result and the out_buf DMA
# slices, so it must be padded to full lanes (int8 sublane tiling would
# allow 32, but Mosaic memref slicing requires 128 on the minor dim)
MXU_TPL_ALIGN = 128

_MXU_CACHE: dict[int, tuple] = {}


def _mxu_corpus_cached(corpus: CorpusArrays):
    """Unpacked bit-major template matrix + padded T, cached like
    `_packed_corpus_cached` (weakref-guarded id keying)."""
    import weakref

    key = id(corpus)
    hit = _MXU_CACHE.get(key)
    if hit is not None and hit[0]() is corpus:
        return hit[1:]
    for k in [k for k, v in _MXU_CACHE.items() if v[0]() is None]:
        del _MXU_CACHE[k]
    bits = np.asarray(corpus.bits)
    T, W = bits.shape
    T_pad = _round_up(max(T, MXU_TPL_ALIGN), MXU_TPL_ALIGN)
    W_pad = _round_up(max(W, LANE), LANE)
    padded = np.zeros((T_pad, W_pad), dtype=np.uint32)
    padded[:T, :W] = bits
    tpl = jnp.asarray(
        np.ascontiguousarray(_unpack_bits_bitmajor(padded).T)
    )  # (V, T): contraction-major for the in-kernel dot
    entry = (tpl, T)
    _MXU_CACHE[key] = (weakref.ref(corpus), *entry)
    return entry


def overlap_pairs_mxu(corpus: CorpusArrays, file_bits,
                      tile_b: int = DEFAULT_TILE_B,
                      interpret: bool | None = None):
    """int32[B, T] intersection sizes via the fused-unpack MXU kernel —
    drop-in for `dice_xla.overlap_pairs` (bit-identical)."""
    if interpret is None:
        interpret = _should_interpret()
    tpl, T = _mxu_corpus_cached(corpus)
    fb, _, B, tile_b = pack_features(
        tpl.shape[0] // 32, file_bits,
        np.zeros(np.asarray(file_bits).shape[0], np.int32),
        np.zeros(np.asarray(file_bits).shape[0], np.int32),
        np.zeros(np.asarray(file_bits).shape[0], bool), tile_b)
    overlap = _overlap_mxu_padded(
        tpl, jnp.asarray(fb), tile_b=tile_b, interpret=interpret
    )
    return overlap[:B, :T]


def make_best_match_fn_pallas_mxu(corpus: CorpusArrays,
                                  tile_b: int = DEFAULT_TILE_B,
                                  interpret: bool | None = None):
    """Drop-in for `dice_xla.make_best_match_fn`, method='pallas-mxu':
    pallas MXU overlap + the shared exact algebra/ranking epilogue."""
    prepare, scorer = make_padded_best_match_fn_mxu(
        corpus, tile_b=tile_b, interpret=interpret
    )

    def fn(file_bits, n_words, lengths, cc_fp):
        B = np.asarray(file_bits).shape[0]
        idx, num, den = scorer(*prepare(file_bits, n_words, lengths, cc_fp))
        return idx[:B], num[:B], den[:B]

    return fn


def make_padded_best_match_fn_mxu(corpus: CorpusArrays,
                                  tile_b: int = DEFAULT_TILE_B,
                                  interpret: bool | None = None):
    """Steady-state (prepare, fn) pair for the MXU kernel; `fn` runs
    kernel + `finish_scores` + `_argmax_exact` as one jitted dispatch."""
    from licensee_tpu.kernels.dice_xla import finish_scores

    if interpret is None:
        interpret = _should_interpret()
    tpl, T = _mxu_corpus_cached(corpus)
    W = tpl.shape[0] // 32

    def prepare(file_bits, n_words, lengths, cc_fp):
        fb, cols, _, _ = pack_features(
            W, file_bits, n_words, lengths, cc_fp, tile_b)
        return jnp.asarray(fb), jnp.asarray(cols)

    @jax.jit
    def fn(fb, cols):
        tb = max(LANE, _round_up(min(tile_b, fb.shape[0]), LANE))
        overlap = _overlap_mxu_padded(tpl, fb, tile_b=tb,
                                      interpret=interpret)[:, :T]
        num, den = finish_scores(
            corpus, overlap, cols[0], cols[1], cols[2].astype(bool)
        )
        return _argmax_exact(num, den)

    return prepare, fn


@functools.partial(jax.jit, static_argnames=("tile_b", "interpret"))
def _score_pairs_padded(meta, tpl_bits, file_bits, cols,
                        tile_b: int, interpret: bool):
    """All shapes pre-padded: B % tile_b == 0, W % LANE == 0,
    T % TPL_BLOCK == 0; `cols` is int32[4, B] (n_words/length/cc_fp)."""
    B, W = file_bits.shape
    T = tpl_bits.shape[0]
    n_tiles = B // tile_b

    num_c, den_c = pl.pallas_call(
        _make_kernel(T, tile_b, n_tiles),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pl.ANY),   # file slab stays in HBM
            pl.BlockSpec(memory_space=pl.ANY),   # per-file scalar columns
        ],
        out_specs=[
            pl.BlockSpec(memory_space=pl.ANY),   # results land in HBM
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_tiles, T, tile_b), jnp.int32),
            jax.ShapeDtypeStruct((n_tiles, T, tile_b), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((N_BUFFERS, tile_b, W), jnp.uint32),
            pltpu.VMEM((N_BUFFERS, 4, tile_b), jnp.int32),
            pltpu.VMEM((N_BUFFERS, 2, T, tile_b), jnp.int32),
            pltpu.SemaphoreType.DMA((N_BUFFERS,)),
            pltpu.SemaphoreType.DMA((N_BUFFERS,)),
            pltpu.SemaphoreType.DMA((N_BUFFERS, 2)),
        ],
        interpret=interpret,
    )(meta, tpl_bits, file_bits, cols)

    # (C, T, TILE_B) -> (B, T)
    num = jnp.moveaxis(num_c, 1, 2).reshape(B, T)
    den = jnp.moveaxis(den_c, 1, 2).reshape(B, T)
    return num, den


def pack_corpus(corpus: CorpusArrays):
    """Pad the corpus constants to kernel-friendly shapes.

    Returns (meta int32[T_pad, 8], tpl_bits uint32[T_pad, W_pad]).
    Padding templates carry valid=0 so the kernel masks them to (-1, 1).
    """
    bits = np.asarray(corpus.bits)
    T, W = bits.shape
    T_pad = _round_up(max(T, TPL_BLOCK), TPL_BLOCK)
    W_pad = _round_up(max(W, LANE), LANE)

    tpl = np.zeros((T_pad, W_pad), dtype=np.uint32)
    tpl[:T, :W] = bits

    meta = np.zeros((T_pad, _META_COLS), dtype=np.int32)
    meta[:T, _N_WF] = np.asarray(corpus.n_wf)
    meta[:T, _N_FIELDSET] = np.asarray(corpus.n_fieldset)
    meta[:T, _FIELD_COUNT] = np.asarray(corpus.field_count)
    meta[:T, _ALT_COUNT] = np.asarray(corpus.alt_count)
    meta[:T, _LENGTH] = np.asarray(corpus.length)
    meta[:T, _CC_FLAG] = np.asarray(corpus.cc_flag).astype(np.int32)
    meta[:T, _VALID] = np.asarray(corpus.valid).astype(np.int32)
    return jnp.asarray(meta), jnp.asarray(tpl)


def pack_features(w_pad: int, file_bits, n_words,
                  lengths, cc_fp, tile_b: int):
    """Pad file features for the kernel: returns (fb uint32[B_pad, W_pad],
    cols int32[4, B_pad], B, tile_b)."""
    file_bits = np.asarray(file_bits, dtype=np.uint32)
    B, W = file_bits.shape
    tile_b = max(LANE, _round_up(min(tile_b, B), LANE))
    B_pad = _round_up(max(B, tile_b), tile_b)

    fb = np.zeros((B_pad, w_pad), dtype=np.uint32)
    fb[:B, :W] = file_bits

    cols = np.zeros((4, B_pad), dtype=np.int32)
    cols[0, :B] = np.asarray(n_words, dtype=np.int32)
    cols[1, :B] = np.asarray(lengths, dtype=np.int32)
    cols[2, :B] = np.asarray(cc_fp).astype(np.int32)
    return fb, cols, B, tile_b


_PACKED_CACHE: dict[int, tuple] = {}


def _packed_corpus_cached(corpus: CorpusArrays):
    """pack_corpus is a host-side D2H+H2D round-trip of the template
    matrix; cache it per CorpusArrays instance so per-chunk calls
    (BatchClassifier) reuse the device-resident constants.  Keyed by id()
    with a weakref guard: if the original corpus was collected and its id
    reused, the stale entry is discarded instead of served."""
    import weakref

    key = id(corpus)
    hit = _PACKED_CACHE.get(key)
    if hit is not None and hit[0]() is corpus:
        return hit[1:]
    # drop entries whose corpus has been collected so discarded corpora
    # don't pin their packed template matrices forever
    for k in [k for k, v in _PACKED_CACHE.items() if v[0]() is None]:
        del _PACKED_CACHE[k]
    meta, tpl = pack_corpus(corpus)
    entry = (meta, tpl, int(np.asarray(corpus.bits).shape[0]))
    _PACKED_CACHE[key] = (weakref.ref(corpus), *entry)
    return entry


def score_pairs_pallas(
    corpus: CorpusArrays,
    file_bits,
    n_words,
    lengths,
    cc_fp,
    tile_b: int = DEFAULT_TILE_B,
    interpret: bool | None = None,
):
    """Exact (numerator, denominator) int32[B, T] — pallas twin of
    `dice_xla.score_pairs` (same masking, same algebra)."""
    if interpret is None:
        interpret = _should_interpret()
    meta, tpl, T = _packed_corpus_cached(corpus)

    fb, cols, B, tile_b = pack_features(
        tpl.shape[1], file_bits, n_words, lengths, cc_fp, tile_b)

    num, den = _score_pairs_padded(
        meta, tpl, jnp.asarray(fb), jnp.asarray(cols),
        tile_b=tile_b, interpret=interpret,
    )
    return num[:B, :T], den[:B, :T]


def best_match_pallas(corpus: CorpusArrays, file_bits, n_words, lengths,
                      cc_fp, tile_b: int = DEFAULT_TILE_B,
                      interpret: bool | None = None):
    """Top-1 (index, overlap, denominator) per blob via the pallas kernel."""
    num, den = score_pairs_pallas(
        corpus, file_bits, n_words, lengths, cc_fp,
        tile_b=tile_b, interpret=interpret,
    )
    return _argmax_exact(num, den)


def make_best_match_fn_pallas(corpus: CorpusArrays,
                              tile_b: int = DEFAULT_TILE_B,
                              interpret: bool | None = None):
    """Drop-in for `dice_xla.make_best_match_fn` backed by the pallas kernel.

    The padding/packing happens per call on host (cheap numpy); scoring
    and the exact ranking run as ONE jitted computation (per padded
    shape), so a call costs a single device dispatch — not one per
    post-kernel slice/astype op."""
    prepare, scorer = make_padded_best_match_fn(
        corpus, tile_b=tile_b, interpret=interpret
    )

    def fn(file_bits, n_words, lengths, cc_fp):
        B = np.asarray(file_bits).shape[0]
        idx, num, den = scorer(*prepare(file_bits, n_words, lengths, cc_fp))
        return idx[:B], num[:B], den[:B]

    return fn


def make_padded_best_match_fn(corpus: CorpusArrays,
                              tile_b: int = DEFAULT_TILE_B,
                              interpret: bool | None = None):
    """Steady-state variant: returns (prepare, fn) where `prepare` packs
    features once into device-ready (fb, cols) arrays and `fn(fb, cols)`
    is the jitted (index, overlap, denominator) scorer.  Use when the same
    feature batch is scored repeatedly (benchmarks) or when the caller
    wants to own H2D placement (`jax.device_put`)."""
    if interpret is None:
        interpret = _should_interpret()
    meta, tpl, _ = _packed_corpus_cached(corpus)

    def prepare(file_bits, n_words, lengths, cc_fp):
        fb, cols, _, _ = pack_features(
            tpl.shape[1], file_bits, n_words, lengths, cc_fp, tile_b)
        return jnp.asarray(fb), jnp.asarray(cols)

    @jax.jit
    def fn(fb, cols):
        tb = max(LANE, _round_up(min(tile_b, fb.shape[0]), LANE))
        num, den = _score_pairs_padded(meta, tpl, fb, cols,
                                       tile_b=tb, interpret=interpret)
        return _argmax_exact(num, den)

    return prepare, fn
