from licensee_tpu.kernels.dice_xla import (
    CorpusArrays,
    score_pairs,
    best_match,
    make_best_match_fn,
)

__all__ = ["CorpusArrays", "score_pairs", "best_match", "make_best_match_fn"]
