"""DiceXLA: the batched Sørensen–Dice scoring kernel.

This is the re-platformed hot loop of the reference
(`matchers/dice.rb:34-48` + `content_helper.rb:128-133`): instead of a Ruby
Set intersection per (file, license) pair, the whole corpus is scored at
once as a bit-matrix intersection:

    overlap[b, t] = popcount(file_bits[b] & template_bits[t])

with the score algebra carried in exact int32.  The kernel returns the
best-candidate (index, overlap, denominator) triple per blob; the final
float64 score `200*overlap/denom` is computed on host so it is bit-identical
to Ruby's Float arithmetic (TPU f64 is emulated and unnecessary for B
scalars).  Ranking on device uses exact int64 cross-multiplication, which
orders identically to float64 whenever the float64 scores differ (rounding
is monotonic) — ties are genuinely unspecified in the reference (unstable
sort_by).

Two compute paths:
  * ``popcount`` — `lax.population_count` over packed uint32 lanes (VPU);
    memory-light, good for small template pools.
  * ``matmul``   — unpack bits to int8 and contract on the MXU with an
    int8×int8→int32 dot; wins when B and the template pool are large.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

# The exact-ranking comparison multiplies int32 (overlap, denominator) pairs;
# products need int64 headroom (emulated on TPU, used only in the tiny
# T-length reduction — the B×T×W main compute stays int32/int8).
jax.config.update("jax_enable_x64", True)


@jax.tree_util.register_dataclass
@dataclass(frozen=True)
class CorpusArrays:
    """Device-ready template constants (see corpus/compiler.py)."""

    bits: jnp.ndarray         # uint32[T, W]
    n_wf: jnp.ndarray         # int32[T]
    n_fieldset: jnp.ndarray   # int32[T]
    field_count: jnp.ndarray  # int32[T]
    alt_count: jnp.ndarray    # int32[T]
    length: jnp.ndarray       # int32[T]
    cc_flag: jnp.ndarray      # bool[T]
    valid: jnp.ndarray        # bool[T] — False for padding templates

    @staticmethod
    def from_compiled(corpus, pad_to: int | None = None) -> "CorpusArrays":
        T = corpus.n_templates
        padded_t = pad_to or T
        def pad(a, fill=0):
            out = np.full((padded_t, *a.shape[1:]), fill, dtype=a.dtype)
            out[:T] = a
            return jnp.asarray(out)

        valid = np.zeros(padded_t, dtype=bool)
        valid[:T] = True
        return CorpusArrays(
            bits=pad(corpus.bits),
            n_wf=pad(corpus.n_wf),
            n_fieldset=pad(corpus.n_fieldset),
            field_count=pad(corpus.field_count),
            alt_count=pad(corpus.alt_count),
            length=pad(corpus.length),
            cc_flag=pad(corpus.cc_flag.astype(bool)),
            valid=jnp.asarray(valid),
        )


def _overlap_popcount(file_bits: jnp.ndarray, tpl_bits: jnp.ndarray) -> jnp.ndarray:
    """popcount(file & template) summed over lanes -> int32[B, T]."""
    inter = jnp.bitwise_and(file_bits[:, None, :], tpl_bits[None, :, :])
    return jnp.sum(lax.population_count(inter).astype(jnp.int32), axis=-1)


def _unpack_bits(packed: jnp.ndarray) -> jnp.ndarray:
    """uint32[N, W] -> int8[N, W*32] (bit i of lane w at column w*32+i)."""
    shifts = jnp.arange(32, dtype=jnp.uint32)
    expanded = (packed[:, :, None] >> shifts[None, None, :]) & jnp.uint32(1)
    return expanded.astype(jnp.int8).reshape(packed.shape[0], -1)


def _overlap_matmul(file_bits: jnp.ndarray, tpl_bits: jnp.ndarray) -> jnp.ndarray:
    """Bit intersection as an int8 contraction on the MXU -> int32[B, T]."""
    lhs = _unpack_bits(file_bits)          # B × V
    rhs = _unpack_bits(tpl_bits)           # T × V
    return lax.dot_general(
        lhs,
        rhs,
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32,
    )


def overlap_pairs(
    corpus: CorpusArrays, file_bits: jnp.ndarray, method: str = "popcount"
) -> jnp.ndarray:
    """int32[B, T] intersection sizes; raises on unknown method."""
    if method == "matmul":
        return _overlap_matmul(file_bits, corpus.bits)
    if method == "popcount":
        return _overlap_popcount(file_bits, corpus.bits)
    raise ValueError(f"unknown scoring method: {method!r}")


def finish_scores(
    corpus: CorpusArrays,
    overlap: jnp.ndarray,     # int32[B, T]
    n_words: jnp.ndarray,     # int32[B]
    lengths: jnp.ndarray,     # int32[B]
    cc_fp: jnp.ndarray,       # bool[B]
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """The exact integer score algebra, shared by every scoring path
    (single-device, TP-sharded partial-popcount, and as the model for the
    fused pallas kernel).

    score = 200*overlap / (n_wf + n_words - n_fieldset + adj_delta//4) with
    adj_delta = max(0, |len_t - len_b| - 5*max(field_count, alt_count))
    (content_helper.rb:128-133, 337-347).  Excluded pairs (CC guard /
    padding) get (-1, 1) so they never win the ranking."""
    total = corpus.n_wf[None, :] + n_words[:, None] - corpus.n_fieldset[None, :]
    delta = jnp.abs(corpus.length[None, :] - lengths[:, None])
    adj = jnp.maximum(
        delta - 5 * jnp.maximum(corpus.field_count, corpus.alt_count)[None, :], 0
    )
    denom = total + adj // 4

    # dice.rb:23-31 CC false-positive guard, plus padding-template mask
    excluded = (corpus.cc_flag[None, :] & cc_fp[:, None]) | ~corpus.valid[None, :]
    num = jnp.where(excluded, -1, overlap)
    den = jnp.where(excluded | (denom <= 0), 1, denom)
    return num, den


def score_pairs(
    corpus: CorpusArrays,
    file_bits: jnp.ndarray,   # uint32[B, W]
    n_words: jnp.ndarray,     # int32[B]
    lengths: jnp.ndarray,     # int32[B]
    cc_fp: jnp.ndarray,       # bool[B]
    method: str = "popcount",
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Exact (numerator, denominator) for every (blob, template) pair."""
    overlap = overlap_pairs(corpus, file_bits, method)
    return finish_scores(corpus, overlap, n_words, lengths, cc_fp)


def _argmax_exact(num: jnp.ndarray, den: jnp.ndarray):
    """Ranking argmax over templates with exact int64 fraction comparison
    (a/b > c/d  ⟺  a*d > c*b for positive denominators).  First-max wins.

    Implemented as a pairwise tournament (log2 T vectorized halvings)
    instead of a T-step sequential fori_loop: at full-SPDX width (T≈600)
    the sequential loop is 600 dependent steps, while the tournament is
    ~10 data-parallel folds on the VPU.  Ties break toward the LOWER
    template index at every fold, which makes the tournament winner
    identical to the sequential first-max scan."""
    B, T = num.shape
    num64 = num.astype(jnp.int64)
    den64 = den.astype(jnp.int64)
    # derive idx from a varying operand (broadcasted iota + 0*num) so the
    # value has the same manual-axes type as num/den under shard_map
    idx = jnp.broadcast_to(
        jnp.arange(T, dtype=jnp.int32)[None, :], num.shape
    ) + jnp.zeros_like(num, dtype=jnp.int32)

    width = T
    while width > 1:
        half = (width + 1) // 2
        rest = width - half  # the right side can be shorter on odd widths
        ln, ld, li = num64[:, :half], den64[:, :half], idx[:, :half]
        rn, rd, ri = (
            num64[:, half:width],
            den64[:, half:width],
            idx[:, half:width],
        )
        lp = ln[:, :rest] * rd
        rp = rn * ld[:, :rest]
        better = (rp > lp) | ((rp == lp) & (ri < li[:, :rest]))
        num64 = jnp.concatenate(
            [jnp.where(better, rn, ln[:, :rest]), ln[:, rest:]], axis=1
        )
        den64 = jnp.concatenate(
            [jnp.where(better, rd, ld[:, :rest]), ld[:, rest:]], axis=1
        )
        idx = jnp.concatenate(
            [jnp.where(better, ri, li[:, :rest]), li[:, rest:]], axis=1
        )
        width = half
    return (
        idx[:, 0],
        num64[:, 0].astype(jnp.int32),
        den64[:, 0].astype(jnp.int32),
    )


def best_match(
    corpus: CorpusArrays,
    file_bits: jnp.ndarray,
    n_words: jnp.ndarray,
    lengths: jnp.ndarray,
    cc_fp: jnp.ndarray,
    method: str = "popcount",
):
    """Top-1 candidate per blob: (index, overlap, denominator) — the host
    turns this into a float64 score and applies the confidence threshold."""
    num, den = score_pairs(corpus, file_bits, n_words, lengths, cc_fp, method)
    return _argmax_exact(num, den)


# Which scorer arguments are donated when the caller opts in: the
# int32[B] rows (n_words, lengths).  They are the only inputs whose
# device buffers can alias an output (the int32[B] idx/num/den triple),
# so donating them frees HBM the moment the kernel consumes them with
# no "donated buffer not usable" warning; the uint32[B, W] bits matrix
# and the bool[B] cc flags have no same-shaped output and would only
# warn.  Donation invalidates DEVICE buffers, never host numpy — safe
# for the staging-ring dispatch path (kernels/batch.py), which always
# feeds host arrays; callers that re-use jax device arrays across calls
# (tests, notebooks) must keep donate=False.
DONATE_ARGNUMS = (1, 2)


def make_best_match_fn(
    corpus: CorpusArrays, method: str = "popcount", donate: bool = False
):
    """A jitted scorer closed over device-resident corpus constants.

    ``donate=True`` donates the int32[B] feature rows (see
    DONATE_ARGNUMS) — the async dispatch pipeline's default, so an
    in-flight chunk's consumed inputs never hold HBM alongside the next
    chunk's transfer."""

    def fn(file_bits, n_words, lengths, cc_fp):
        return best_match(corpus, file_bits, n_words, lengths, cc_fp, method)

    return jax.jit(fn, donate_argnums=DONATE_ARGNUMS if donate else ())


def topk_candidates(num: jnp.ndarray, den: jnp.ndarray, k: int):
    """Top-k (index, num, den) columns in EXACT score order.

    k rounds of the same int64 cross-multiplication tournament the top-1
    path uses, masking each round's winner to the excluded (-1, 1)
    sentinel: the inclusion boundary at rank k is exact, and ties break
    toward the lower template index at every rank — identical semantics
    to running the sequential first-max scan k times.  k is small (the
    CLI's --closest K, plus one), so the unrolled k·log2(T) folds are
    noise next to the B×T×V overlap compute."""
    T = num.shape[1]
    col = jnp.broadcast_to(
        jnp.arange(T, dtype=jnp.int32)[None, :], num.shape
    )
    k_idx, k_num, k_den = [], [], []
    n, d = num, den
    for _ in range(k):
        idx, nn, dd = _argmax_exact(n, d)
        k_idx.append(idx)
        k_num.append(nn)
        k_den.append(dd)
        won = col == idx[:, None]
        n = jnp.where(won, -1, n)
        d = jnp.where(won, 1, d)
    return (
        jnp.stack(k_idx, axis=1),
        jnp.stack(k_num, axis=1),
        jnp.stack(k_den, axis=1),
    )


def make_topk_fn(
    corpus: CorpusArrays, k: int, method: str = "popcount",
    donate: bool = False,
):
    """Jitted scorer returning the EXACT top-1 plus a top-k candidate
    list per blob (the batch analog of the CLI's closest-licenses view,
    commands/detect.rb:44-63).  The top-1 triple uses the exact int64
    tournament (bit-identical to `make_best_match_fn`); the k columns
    use the same exact comparison (`topk_candidates`), so the whole
    candidate list is exact, boundary included."""

    def fn(file_bits, n_words, lengths, cc_fp):
        num, den = score_pairs(
            corpus, file_bits, n_words, lengths, cc_fp, method
        )
        best = _argmax_exact(num, den)
        return (*best, *topk_candidates(num, den, k))

    return jax.jit(fn, donate_argnums=DONATE_ARGNUMS if donate else ())
