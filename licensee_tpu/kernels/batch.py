"""Batch classification: host pre-filters + the DiceXLA device kernel.

Mirrors the first-match-wins matcher chain of the reference
(`project_files/license_file.rb:67-69`: Copyright -> Exact -> Dice) at batch
scale: the cheap host pre-filters short-circuit blobs before they reach HBM
(the EP-style routing of SURVEY.md §2.7), and everything else is scored in
one vmapped bit-matrix pass on device.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

import licensee_tpu
from licensee_tpu.corpus.compiler import CompiledCorpus, default_corpus
from licensee_tpu.normalize.pipeline import COPYRIGHT_FULL_REGEX, NormalizedContent
from licensee_tpu.project_files.license_file import CC_FALSE_POSITIVE_REGEX
from licensee_tpu.project_files.project_file import sanitize_content
from licensee_tpu.rubytext import ruby_strip


class NormalizedBlob(NormalizedContent):
    """A bare content blob run through the normalization engine."""

    def __init__(self, content: str | bytes | None, filename: str | None = None):
        self.content = (
            sanitize_content(content) if content is not None else None
        )
        self.filename = filename


@dataclass
class BlobResult:
    key: str | None
    matcher: str | None
    confidence: float
    score_num: int = 0
    score_den: int = 0

    def as_dict(self) -> dict:
        return {
            "key": self.key,
            "matcher": self.matcher,
            "confidence": self.confidence,
        }


class BatchClassifier:
    """Classify many blobs against a compiled corpus.

    Host side: sanitation, Copyright / Exact pre-filters, normalization +
    tokenization into packed bitsets.  Device side: DiceXLA best-match.
    Scores returned to the host as exact (overlap, denominator) pairs and
    finished in float64 — bit-identical to the scalar Ruby-semantics path.
    """

    def __init__(
        self,
        corpus: CompiledCorpus | None = None,
        method: str = "popcount",
        pad_batch_to: int = 1024,
    ):
        from licensee_tpu.kernels.dice_xla import CorpusArrays, make_best_match_fn

        self.corpus = corpus or default_corpus()
        self.method = method
        self.pad_batch_to = pad_batch_to
        self.arrays = CorpusArrays.from_compiled(self.corpus)
        if method == "pallas":
            from licensee_tpu.kernels.dice_pallas import (
                make_best_match_fn_pallas,
            )

            self._fn = make_best_match_fn_pallas(self.arrays)
        else:
            self._fn = make_best_match_fn(self.arrays, method=method)
        # Exact matcher pre-filter: full wordset (fields included) equality
        # (matchers/exact.rb:6-13), against the corpus's OWN template
        # renderings (not the vendored pool — custom SPDX corpora carry
        # keys License.find doesn't know, and their rendering differs)
        self._exact_map = self.corpus.exact_sets

        # whole-pipeline native path: sanitize -> featurize in 1-2 ctypes
        # crossings per blob (native/pipeline.cpp); falls back to the
        # Python pipeline when the toolchain/libpcre2 is unavailable
        from licensee_tpu.native import pipeline as native_pipeline

        self._nat = native_pipeline.load()
        self._nat_vocab = None
        self._exact_hashes: dict[bytes, str] = {}
        if self._nat is not None:
            self._nat_vocab = self._nat.vocab(
                list(self.corpus.vocab.keys()), self.corpus.n_lanes
            )
            for wordset, key in self.corpus.exact_sets.items():
                self._exact_hashes.setdefault(self._nat.exact_hash(wordset), key)

    # -- host featureization --

    def _prefilter(self, blob: NormalizedBlob) -> BlobResult | None:
        content = blob.content or ""
        if COPYRIGHT_FULL_REGEX.search(ruby_strip(content)):
            return BlobResult("no-license", "copyright", 100.0)
        if blob.wordset is not None and frozenset(blob.wordset) in self._exact_map:
            return BlobResult(self._exact_map[frozenset(blob.wordset)], "exact", 100.0)
        return None

    def features(self, blobs: list[NormalizedBlob]):
        B = len(blobs)
        W = self.corpus.n_lanes
        bits = np.zeros((B, W), dtype=np.uint32)
        n_words = np.zeros(B, dtype=np.int32)
        lengths = np.zeros(B, dtype=np.int32)
        cc_fp = np.zeros(B, dtype=bool)
        for i, blob in enumerate(blobs):
            bits[i], n_words[i], lengths[i] = self.corpus.file_features(blob)
            cc_fp[i] = bool(
                CC_FALSE_POSITIVE_REGEX.search(ruby_strip(blob.content or ""))
            )
        return bits, n_words, lengths, cc_fp

    # -- batch preparation (prefilters + featurization in one pass) --

    def prepare_batch(self, contents: list[str | bytes]):
        """Sanitize, prefilter and featurize a batch of raw blobs.

        Returns (results, bits, n_words, lengths, cc_fp, todo): ``results``
        holds a BlobResult for prefiltered blobs and None for the ``todo``
        indexes, whose feature rows are filled and ready for the device.
        Thread-safe: rows are written independently and the native calls
        release the GIL, so featurization workers can share one classifier."""
        B = len(contents)
        W = self.corpus.n_lanes
        bits = np.zeros((B, W), dtype=np.uint32)
        n_words = np.zeros(B, dtype=np.int32)
        lengths = np.zeros(B, dtype=np.int32)
        cc_fp = np.zeros(B, dtype=bool)
        results: list[BlobResult | None] = [None] * B

        if self._nat is not None:
            for i, raw in enumerate(contents):
                self._prepare_one_native(
                    raw, results, bits, n_words, lengths, cc_fp, i
                )
        else:
            blobs = [NormalizedBlob(c) for c in contents]
            for i, blob in enumerate(blobs):
                results[i] = self._prefilter(blob)
                if results[i] is None:
                    bits[i], n_words[i], lengths[i] = self.corpus.file_features(
                        blob
                    )
                    cc_fp[i] = bool(
                        CC_FALSE_POSITIVE_REGEX.search(
                            ruby_strip(blob.content or "")
                        )
                    )
        todo = [i for i, r in enumerate(results) if r is None]
        return results, bits, n_words, lengths, cc_fp, todo

    def _prepare_one_native(
        self, raw, results, bits, n_words, lengths, cc_fp, i
    ) -> None:
        content = sanitize_content(raw) if raw is not None else ""
        stripped = ruby_strip(content)
        feat = self._nat.featurize_raw(self._nat_vocab, stripped, bits[i])
        if feat is None:
            # non-ASCII: the downcase between the stages must be
            # full-Unicode, so it happens in Python (two crossings)
            s1, flags = self._nat.stage1(stripped)
            _, nw, ln, h = self._nat.featurize(
                self._nat_vocab, s1.lower(), bits[i]
            )
        else:
            _, nw, ln, flags, h = feat
        if flags & 1:
            results[i] = BlobResult("no-license", "copyright", 100.0)
        elif h in self._exact_hashes:
            results[i] = BlobResult(self._exact_hashes[h], "exact", 100.0)
        else:
            n_words[i] = nw
            lengths[i] = ln
            cc_fp[i] = bool(flags & 2)

    # -- classification --

    def classify_blobs(
        self, contents: list[str | bytes], threshold: float | None = None
    ) -> list[BlobResult]:
        threshold = (
            licensee_tpu.confidence_threshold() if threshold is None else threshold
        )
        results, bits, n_words, lengths, cc_fp, todo = self.prepare_batch(contents)
        outs = self.dispatch_chunks(bits, n_words, lengths, cc_fp, todo)
        self.finish_chunks(results, todo, outs, threshold)
        return results  # type: ignore[return-value]

    def dispatch_chunks(self, bits, n_words, lengths, cc_fp, todo):
        """Launch device scoring for the ``todo`` rows in fixed-size padded
        chunks.  The returned device outputs are lazy (JAX dispatch is
        asynchronous): the host featurizes the next batch while the device
        scores this one; finish_chunks() synchronizes."""
        outs = []
        B = self.pad_batch_to
        for start in range(0, len(todo), B):
            chunk = todo[start : start + B]
            b = bits[chunk]
            nw = n_words[chunk]
            ln = lengths[chunk]
            cf = cc_fp[chunk]
            pad = B - len(chunk)
            if pad:
                b = np.pad(b, ((0, pad), (0, 0)))
                nw = np.pad(nw, (0, pad))
                ln = np.pad(ln, (0, pad))
                cf = np.pad(cf, (0, pad))
            outs.append((chunk, self._fn(b, nw, ln, cf)))
        return outs

    def finish_chunks(self, results, todo, outs, threshold) -> None:
        """Synchronize device outputs and finish scores in float64 —
        identical to Ruby's Float score (dice.rb:57-59)."""
        for chunk, (best_idx, best_num, best_den) in outs:
            best_idx = np.asarray(best_idx)[: len(chunk)]
            best_num = np.asarray(best_num)[: len(chunk)]
            best_den = np.asarray(best_den)[: len(chunk)]
            scores = np.where(best_den > 0, (best_num * 200.0) / best_den, 0.0)
            for j, i in enumerate(chunk):
                if best_num[j] >= 0 and scores[j] >= threshold:
                    results[i] = BlobResult(
                        self.corpus.keys[int(best_idx[j])],
                        "dice",
                        float(scores[j]),
                        int(best_num[j]),
                        int(best_den[j]),
                    )
                else:
                    results[i] = BlobResult(None, None, 0.0)


