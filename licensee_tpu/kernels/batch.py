"""Batch classification: host pre-filters + the DiceXLA device kernel.

Mirrors the first-match-wins matcher chain of the reference
(`project_files/license_file.rb:67-69`: Copyright -> Exact -> Dice) at batch
scale: the cheap host pre-filters short-circuit blobs before they reach HBM
(the EP-style routing of SURVEY.md §2.7), and everything else is scored in
one vmapped bit-matrix pass on device.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

import licensee_tpu
from licensee_tpu.corpus.compiler import CompiledCorpus, default_corpus
from licensee_tpu.normalize.pipeline import COPYRIGHT_FULL_REGEX, NormalizedContent
from licensee_tpu.project_files.license_file import CC_FALSE_POSITIVE_REGEX
from licensee_tpu.project_files.project_file import sanitize_content
from licensee_tpu.rubytext import ruby_strip


class NormalizedBlob(NormalizedContent):
    """A bare content blob run through the normalization engine."""

    def __init__(self, content: str | bytes | None, filename: str | None = None):
        self.content = (
            sanitize_content(content) if content is not None else None
        )
        self.filename = filename


@dataclass
class BlobResult:
    key: str | None
    matcher: str | None
    confidence: float
    score_num: int = 0
    score_den: int = 0

    def as_dict(self) -> dict:
        return {
            "key": self.key,
            "matcher": self.matcher,
            "confidence": self.confidence,
        }


class BatchClassifier:
    """Classify many blobs against a compiled corpus.

    Host side: sanitation, Copyright / Exact pre-filters, normalization +
    tokenization into packed bitsets.  Device side: DiceXLA best-match.
    Scores returned to the host as exact (overlap, denominator) pairs and
    finished in float64 — bit-identical to the scalar Ruby-semantics path.
    """

    def __init__(
        self,
        corpus: CompiledCorpus | None = None,
        method: str = "popcount",
        pad_batch_to: int = 1024,
    ):
        from licensee_tpu.kernels.dice_xla import CorpusArrays, make_best_match_fn

        self.corpus = corpus or default_corpus()
        self.method = method
        self.pad_batch_to = pad_batch_to
        self.arrays = CorpusArrays.from_compiled(self.corpus)
        if method == "pallas":
            from licensee_tpu.kernels.dice_pallas import (
                make_best_match_fn_pallas,
            )

            self._fn = make_best_match_fn_pallas(self.arrays)
        else:
            self._fn = make_best_match_fn(self.arrays, method=method)
        # Exact matcher pre-filter: full wordset (fields included) equality
        # (matchers/exact.rb:6-13), against the corpus's OWN template
        # renderings (not the vendored pool — custom SPDX corpora carry
        # keys License.find doesn't know, and their rendering differs)
        self._exact_map = self.corpus.exact_sets

    # -- host featureization --

    def _prefilter(self, blob: NormalizedBlob) -> BlobResult | None:
        content = blob.content or ""
        if COPYRIGHT_FULL_REGEX.search(ruby_strip(content)):
            return BlobResult("no-license", "copyright", 100.0)
        if blob.wordset is not None and frozenset(blob.wordset) in self._exact_map:
            return BlobResult(self._exact_map[frozenset(blob.wordset)], "exact", 100.0)
        return None

    def features(self, blobs: list[NormalizedBlob]):
        B = len(blobs)
        W = self.corpus.n_lanes
        bits = np.zeros((B, W), dtype=np.uint32)
        n_words = np.zeros(B, dtype=np.int32)
        lengths = np.zeros(B, dtype=np.int32)
        cc_fp = np.zeros(B, dtype=bool)
        for i, blob in enumerate(blobs):
            bits[i], n_words[i], lengths[i] = self.corpus.file_features(blob)
            cc_fp[i] = bool(
                CC_FALSE_POSITIVE_REGEX.search(ruby_strip(blob.content or ""))
            )
        return bits, n_words, lengths, cc_fp

    # -- classification --

    def classify_blobs(
        self, contents: list[str | bytes], threshold: float | None = None
    ) -> list[BlobResult]:
        threshold = (
            licensee_tpu.confidence_threshold() if threshold is None else threshold
        )
        blobs = [NormalizedBlob(c) for c in contents]
        results: list[BlobResult | None] = [self._prefilter(b) for b in blobs]

        todo = [i for i, r in enumerate(results) if r is None]
        if todo:
            for start in range(0, len(todo), self.pad_batch_to):
                chunk = todo[start : start + self.pad_batch_to]
                self._classify_chunk(blobs, results, chunk, threshold)
        return results  # type: ignore[return-value]

    def _classify_chunk(self, blobs, results, chunk, threshold) -> None:
        B = self.pad_batch_to
        bits, n_words, lengths, cc_fp = self.features([blobs[i] for i in chunk])
        pad = B - len(chunk)
        if pad:
            bits = np.pad(bits, ((0, pad), (0, 0)))
            n_words = np.pad(n_words, (0, pad))
            lengths = np.pad(lengths, (0, pad))
            cc_fp = np.pad(cc_fp, (0, pad))
        best_idx, best_num, best_den = self._fn(bits, n_words, lengths, cc_fp)
        best_idx = np.asarray(best_idx)[: len(chunk)]
        best_num = np.asarray(best_num)[: len(chunk)]
        best_den = np.asarray(best_den)[: len(chunk)]

        # float64 finish: identical to Ruby's Float score (dice.rb:57-59)
        scores = np.where(
            best_den > 0, (best_num * 200.0) / best_den, 0.0
        )
        for j, i in enumerate(chunk):
            if best_num[j] >= 0 and scores[j] >= threshold:
                results[i] = BlobResult(
                    self.corpus.keys[int(best_idx[j])],
                    "dice",
                    float(scores[j]),
                    int(best_num[j]),
                    int(best_den[j]),
                )
            else:
                results[i] = BlobResult(None, None, 0.0)


def batch_detect_paths(paths: list[str], **kwargs) -> list[dict]:
    """Classify files by path (the CLI `batch-detect` command)."""
    classifier = BatchClassifier(**kwargs)
    contents = []
    for path in paths:
        with open(path, "rb") as f:
            contents.append(f.read())
    return [r.as_dict() for r in classifier.classify_blobs(contents)]
