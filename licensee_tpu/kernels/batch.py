"""Batch classification: host pre-filters + the DiceXLA device kernel.

Mirrors the first-match-wins matcher chain of the reference
(`project_files/license_file.rb:67-69`: Copyright -> Exact -> Dice) at batch
scale: the cheap host pre-filters short-circuit blobs before they reach HBM
(the EP-style routing of SURVEY.md §2.7), and everything else is scored in
one vmapped bit-matrix pass on device.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass

import numpy as np

import licensee_tpu
from licensee_tpu.corpus.compiler import CompiledCorpus, default_corpus
from licensee_tpu.normalize.pipeline import (
    COPYRIGHT_FULL_REGEX,
    COPYRIGHT_REGEX,
    NormalizedContent,
)
from licensee_tpu.project_files.license_file import CC_FALSE_POSITIVE_REGEX
from licensee_tpu.project_files.project_file import sanitize_content
from licensee_tpu.rubytext import ruby_strip


import functools


@functools.lru_cache(maxsize=1)
def _reference_union():
    """The corpus-wide Reference alternation: every license's
    title|source pattern as a named group ``g<pool-index>``, compiled
    once per process (the license pool is process-global and frozen).
    The per-license patterns contain unnamed inner capturing groups (the
    optional version minors), so ``m.lastgroup`` is unreliable; callers
    identify the matched alternative by scanning ``m.groupdict()`` for
    its single non-None named group."""
    from licensee_tpu.corpus.license import License
    from licensee_tpu.rubytext import rb

    lics = tuple(License.all(hidden=True, pseudo=False))
    parts = []
    for i, lic in enumerate(lics):
        inner = [lic.title_regex_pattern]
        source = lic.source_regex_pattern
        if source:
            inner.append(source)
        parts.append(f"(?P<g{i}>" + "|".join(inner) + ")")
    return lics, rb(r"\b(?:" + "|".join(parts) + r")\b")


@functools.lru_cache(maxsize=1)
def _refscan_native():
    """(pipeline, handle) for the JIT-compiled Reference union, or None
    when the native library is unavailable or rejects the pattern."""
    from licensee_tpu.native import pipeline as native_pipeline

    nat = native_pipeline.load()
    if nat is None:
        return None
    lics, union = _reference_union()
    # byte-mode PCRE2 (no UTF/UCP) IS the faithful translation of the
    # Python side: rb() compiles with re.A (Ruby's ASCII-only \b/\w),
    # and in UTF-8 every non-ASCII byte is a non-word byte — exactly
    # how re.A treats non-ASCII characters.  Unicode mode would instead
    # call 'ラ' a word char and miss 'MITライセンス'.
    handle = nat.refscan_new(union)
    if handle is None:
        return None
    # per-license patterns let the exact shadow resolution run in ONE
    # crossing (pipe_refscan_resolve); failure just means the Python
    # shadow loop stays in charge
    nat.refscan_set_singles(handle, [lic.reference_regex for lic in lics])
    return nat, handle


# Measured kernel-method crossover (bench.py bench_method_crossover):
# ascending (max_templates, method) rungs; None = everything above.
# The narrow rung is the v5e VPU measurement (the ADR in
# dice_pallas.py: popcount wins while the loop stays cache-resident).
# Re-benched 2026-08-03 at T = 608 (vendored+SPDX width), 1216 and
# 2432 (padded-template widths standing in for artifact corpora grown
# past the vendored pool): matmul wins from a few hundred templates up
# and its lead GROWS with T (the MXU amortizes the 32x bit unpack over
# ever-larger contractions) — the r5 worry that the crossover might
# invert above vendored width did not materialize.  Re-benched
# 2026-08-04 with the sweep extended to T = 4864 (8x full-SPDX width,
# the ROADMAP's "past vendored" refresh): matmul ~15x popcount at the
# widest rung on this backend, the gap still widening — table
# unchanged, ``auto`` agrees at every measured rung.  ``method="auto"``
# (and every reload's re-resolution through serve/reload.py
# build_classifier_like) consults this table.
METHOD_CROSSOVER: tuple = ((128, "popcount"), (None, "matmul"))


def resolve_method(n_templates: int) -> str:
    """The measured-best scoring method for a corpus of this width."""
    for bound, method in METHOD_CROSSOVER:
        if bound is None or n_templates <= bound:
            return method
    raise AssertionError("METHOD_CROSSOVER must end with a None rung")


class DeviceFuture:
    """A handle to in-flight device scoring.

    Submission already happened (asynchronous JAX dispatch, with the
    device->host output copies started), so holding a DeviceFuture
    costs nothing on the host; :meth:`result` blocks only until those
    copies land and then returns the resolved
    ``[(chunk, (np arrays...)), ...]`` outs list — the exact shape
    ``finish_chunks`` consumes.  ``ready()`` is a non-blocking poll
    for callers that want to peek before awaiting; the batch and serve
    pipelines themselves never use it — they await strictly FIFO via
    :meth:`result` (the ordering contract).  Resolution also
    releases any staging-ring slots the dispatch borrowed, so a future
    must be awaited (or dropped) for its slots to recycle."""

    __slots__ = ("_parts", "_resolved", "_on_resolve")

    def __init__(self, parts, on_resolve=()):
        self._parts = parts
        self._resolved = None
        self._on_resolve = list(on_resolve)

    def __len__(self) -> int:
        return len(self._parts)

    def ready(self) -> bool:
        """True when every output has landed on the host (non-blocking;
        conservatively False only while a copy is still in flight)."""
        if self._resolved is not None:
            return True
        for _chunk, out in self._parts:
            for a in out:
                is_ready = getattr(a, "is_ready", None)
                if is_ready is not None and not is_ready():
                    return False
        return True

    def result(self):
        """Await: resolve every output to host numpy (idempotent)."""
        if self._resolved is None:
            self._resolved = [
                (chunk, tuple(np.asarray(a) for a in out))
                for chunk, out in self._parts
            ]
            self._parts = self._resolved
            callbacks, self._on_resolve = self._on_resolve, []
            for cb in callbacks:
                cb()
        return self._resolved


class _StagingRing:
    """Pre-allocated host staging rows for padded dispatch chunks.

    One free-list of (bits, n_words, lengths, cc_fp) row blocks per
    padded shape: a partial chunk copies its rows in and zeroes the
    tail instead of paying an ``np.pad`` allocation quartet per
    dispatch.  ``acquire`` NEVER blocks — when the ring is dry it
    allocates a fresh slot (the pipeline depth, not the ring, bounds
    in-flight chunks; a blocking acquire here could deadlock the
    single thread that both submits and awaits) — and ``release``
    keeps at most ``depth`` slots per shape, so a burst allocates and
    the steady state recycles."""

    def __init__(self, n_lanes: int, depth: int = 3):
        self.n_lanes = n_lanes
        self.depth = depth
        self._free: dict[int, list] = {}
        self._lock = threading.Lock()

    def acquire(self, B: int):
        with self._lock:
            free = self._free.setdefault(B, [])
            if free:
                return free.pop()
        return (
            np.zeros((B, self.n_lanes), dtype=np.uint32),
            np.zeros(B, dtype=np.int32),
            np.zeros(B, dtype=np.int32),
            np.zeros(B, dtype=bool),
        )

    def release(self, slot) -> None:
        B = len(slot[1])
        with self._lock:
            free = self._free.setdefault(B, [])
            if len(free) < self.depth:
                free.append(slot)

    def fill(self, slot, b, nw, ln, cf):
        """Copy n live rows into the slot and zero the padding tail."""
        n = len(nw)
        sb, snw, sln, scf = slot
        sb[:n] = b
        snw[:n] = nw
        sln[:n] = ln
        scf[:n] = cf
        sb[n:] = 0
        snw[n:] = 0
        sln[n:] = 0
        scf[n:] = False
        return slot


@functools.lru_cache(maxsize=None)
def _has_fullname(key: str) -> bool:
    """Does the vendored license's template carry a [fullname] field?
    Memoized: keys come from a small fixed pool, and License.find walks
    the whole pool — a 10M-row --attribution run must pay one dict hit
    per row, not a list rebuild."""
    from licensee_tpu.corpus.license import License

    lic = License.find(key)
    return lic is not None and bool(lic.content) and "[fullname]" in lic.content


class NormalizedBlob(NormalizedContent):
    """A bare content blob run through the normalization engine."""

    def __init__(self, content: str | bytes | None, filename: str | None = None):
        self.content = (
            sanitize_content(content) if content is not None else None
        )
        self.filename = filename


@dataclass
class BlobResult:
    key: str | None
    matcher: str | None
    confidence: float
    score_num: int = 0
    score_den: int = 0
    error: str | None = None
    # top-k candidate list [(key, confidence), ...] when the classifier
    # runs with closest=K (the CLI's closest-licenses view, batched)
    closest: list | None = None
    # the copyright line, when requested (--attribution) and applicable
    # (license_file.rb:71-77: matched license carries [fullname], or the
    # Copyright matcher fired)
    attribution: str | None = None

    def as_dict(self) -> dict:
        d = {
            "key": self.key,
            "matcher": self.matcher,
            "confidence": self.confidence,
        }
        if self.closest is not None:
            d["closest"] = [[k, c] for k, c in self.closest]
        if self.attribution is not None:
            d["attribution"] = self.attribution
        return d


@dataclass
class PreparedBatch:
    """One featurized batch between the host produce stage and the device.

    ``results`` carries a BlobResult for every blob short-circuited on the
    host (prefilters, package matchers, featurize errors) and None for the
    ``todo`` rows, whose feature arrays are device-ready.  ``sections``
    (readme mode only) keeps each blob's extracted license section so the
    Reference matcher can run as the post-Dice fallback
    (readme_file.rb:32-34 appends Matchers::Reference to the chain)."""

    results: list
    bits: np.ndarray
    n_words: np.ndarray
    lengths: np.ndarray
    cc_fp: np.ndarray
    todo: list
    sections: list | None = None
    # feature arrays hold ONLY the todo rows (row j <-> todo[j]) after
    # compact_features(); results/todo/sections keep full-batch indexing
    compact: bool = False

    def compact_features(self) -> None:
        """Slice the feature arrays down to the todo rows.

        A dedupe-heavy batch carries a dense (batch, lanes) bits array
        for a handful of todo rows; compacting frees that memory while
        the batch waits in the cross-batch coalescing buffer and makes
        merge_prepared a plain concatenation.  Idempotent."""
        if self.compact:
            return
        if len(self.todo) < len(self.results):
            idx = np.asarray(self.todo, dtype=np.int64)
            self.bits = self.bits[idx]
            self.n_words = self.n_words[idx]
            self.lengths = self.lengths[idx]
            self.cc_fp = self.cc_fp[idx]
        self.compact = True


class BatchClassifier:
    """Classify many blobs against a compiled corpus.

    Host side: sanitation, Copyright / Exact pre-filters, normalization +
    tokenization into packed bitsets.  Device side: DiceXLA best-match.
    Scores returned to the host as exact (overlap, denominator) pairs and
    finished in float64 — bit-identical to the scalar Ruby-semantics path.
    """

    def __init__(
        self,
        corpus: CompiledCorpus | None = None,
        method: str = "auto",
        pad_batch_to: int = 1024,
        mesh="auto",
        mode: str = "license",
        closest: int = 0,
        device: bool = True,
        lanes: int | str | None = None,
        staging_depth: int = 3,
    ):
        if mode not in ("license", "readme", "package", "auto"):
            raise ValueError(f"unknown mode {mode!r}")
        self.mode = mode
        # device dispatch attribution (obs): each distinct padded shape
        # jit-compiles exactly once PER DEVICE, so the FIRST dispatch of
        # a (shape, device) pair is compile-dominated and every later
        # one is steady-state enqueue.  Splitting the two is the
        # compile-vs-execute story the serve registry exports (one cold
        # bucket showing up as a p99 cliff is a compile, not a
        # regression); _shape_prof keeps the same split per padded
        # shape, so a serve worker can name WHICH bucket paid what.
        self._dispatch_lock = threading.Lock()
        self._dispatch_prof = {
            "compiles": 0, "compile_s": 0.0,
            "dispatches": 0, "dispatch_s": 0.0,
        }
        self._dispatched_shapes: set = set()  # (pad shape, device key)
        self._shape_prof: dict[int, dict] = {}
        self._rr = 0  # round-robin cursor over self.devices
        self.devices: list | None = None
        self.closest = int(closest)
        if self.closest < 0:
            raise ValueError("closest must be >= 0")
        if mode == "package":
            # package manifests are matched by filename-dispatched lenient
            # regexes alone (package_manager_file.rb matcher table) — the
            # device never sees them, so no corpus is compiled and no
            # scorer built; an explicit mesh is a caller error, not a
            # silently-ignored option
            if mesh is not None and mesh != "auto":
                raise ValueError(
                    "package mode runs host-only; pass mesh=None"
                )
            if self.closest:
                raise ValueError(
                    "closest needs the Dice scorer; package mode never "
                    "runs it"
                )
            self.corpus = corpus
            self.method = method
            self.pad_batch_to = pad_batch_to
            self.mesh = None
            self._staging = None  # host-only: nothing ever dispatches
            self._fn = None
            self.arrays = None
            self._exact_map = {}
            self._nat = None
            self._exact_hashes = {}
            self._exact_feats = {}
            return
        self.corpus = corpus or default_corpus()
        if method == "auto":
            # the measured crossover table (METHOD_CROSSOVER above; the
            # v5e ADR in dice_pallas.py tells the same story): popcount
            # at narrow widths, matmul from a few hundred templates up,
            # re-benched past vendored width by bench_method_crossover
            method = resolve_method(self.corpus.n_templates)
        self.method = method
        self.pad_batch_to = pad_batch_to
        # host staging rows for padded dispatch (the async pipeline's
        # double/triple buffer — see _StagingRing)
        self._staging = _StagingRing(
            self.corpus.n_lanes, depth=max(1, int(staging_depth))
        )
        if not device:
            # host-only twin for featurize worker PROCESSES
            # (--featurize-procs): prepare_batch works in full, but no
            # jax is touched — the worker never initializes a backend,
            # so it cannot contend for the TPU.  dispatch_chunks raises.
            self.mesh = None
            self._fn = None
            self.arrays = None
            self._exact_map = self.corpus.exact_sets
            self._init_native()
            return
        from licensee_tpu.kernels.dice_xla import (
            CorpusArrays,
            make_best_match_fn,
        )

        self.arrays = CorpusArrays.from_compiled(self.corpus)
        # Scale-out is the default product path (SURVEY.md §2.7 DP row):
        # with >1 visible device the scorer is jitted over a
        # ('data', 'model') mesh so the blob batch shards across chips.
        # mesh may be a jax Mesh, an (n_data, n_model) tuple, "auto"
        # (all devices, data-parallel), or None (single device).
        if self.closest and method.startswith("pallas"):
            # the k output columns change the shapes the hand-scheduled
            # pallas kernels were built for
            raise ValueError(
                "closest is not supported with the pallas methods"
            )
        # ``lanes``: in-stripe multi-chip ROUND-ROBIN — successive
        # dispatch chunks go wholly to successive visible chips, so one
        # featurize lane feeds K independent device lanes (the overlap
        # pipeline's scale-out inside one stripe).  Orthogonal to
        # ``mesh`` (which splits ONE chunk across chips and
        # synchronizes them per dispatch): exactly one of the two may
        # be active.  "auto" takes every visible chip; an int takes the
        # first K.
        if lanes is not None:
            if mesh not in (None, "auto"):
                raise ValueError(
                    "lanes round-robins whole chunks per chip; pass "
                    "mesh=None (or leave mesh='auto' to be overridden)"
                )
            if method.startswith("pallas"):
                raise ValueError(
                    "the pallas methods are single-device; lanes cannot "
                    "round-robin them"
                )
            import jax

            local = jax.local_devices()
            k = len(local) if lanes == "auto" else int(lanes)
            if k < 1:
                raise ValueError(f"lanes must be >= 1, got {lanes!r}")
            if k > len(local):
                raise ValueError(
                    f"lanes={k} but only {len(local)} visible devices "
                    "(the chip-partition contract: set "
                    "LICENSEE_TPU_VISIBLE_CHIPS / --chips-per-stripe)"
                )
            mesh = None  # a lanes classifier never shards one chunk
            if k > 1:
                self.devices = list(local[:k])
        self.mesh = self._resolve_mesh(mesh, method, pad_batch_to)
        # top-1 stays exact with or without closest; the k candidate
        # columns are a per-row reduction, so they ride both the
        # single-device jit and the sharded scorer unchanged
        k = (
            min(self.closest + 1, self.corpus.n_templates)
            if self.closest
            else 0
        )
        if self.mesh is not None:
            from licensee_tpu.parallel.mesh import make_sharded_scorer

            self._fn = make_sharded_scorer(
                self.arrays, self.mesh, method=method, topk=k
            )
        elif k:
            from licensee_tpu.kernels.dice_xla import make_topk_fn

            self._fn = make_topk_fn(self.arrays, k, method=method, donate=True)
        elif method == "pallas":
            from licensee_tpu.kernels.dice_pallas import (
                make_best_match_fn_pallas,
            )

            self._fn = make_best_match_fn_pallas(self.arrays)
        elif method == "pallas-mxu":
            from licensee_tpu.kernels.dice_pallas import (
                make_best_match_fn_pallas_mxu,
            )

            self._fn = make_best_match_fn_pallas_mxu(self.arrays)
        else:
            # donate=True: the int32[B] feature rows' device buffers are
            # released to the allocator as the kernel consumes them (see
            # dice_xla.DONATE_ARGNUMS) — the async pipeline keeps
            # several chunks in flight, and their dead inputs must not
            # stack up in HBM behind the live ones
            self._fn = make_best_match_fn(
                self.arrays, method=method, donate=True
            )
        # Exact matcher pre-filter: full wordset (fields included) equality
        # (matchers/exact.rb:6-13), against the corpus's OWN template
        # renderings (not the vendored pool — custom SPDX corpora carry
        # keys License.find doesn't know, and their rendering differs)
        self._exact_map = self.corpus.exact_sets

        self._init_native()

    def _init_native(self) -> None:
        """Load the whole-pipeline native path: sanitize -> featurize in
        1-2 ctypes crossings per blob (native/pipeline.cpp); falls back
        to the Python pipeline when the toolchain/libpcre2 is
        unavailable."""
        from licensee_tpu.native import pipeline as native_pipeline

        self._nat = native_pipeline.load()
        self._nat_vocab = None
        self._exact_hashes: dict[bytes, str] = {}
        # per-hash equality-proof constants: the template's FULL-wordset
        # in-vocab bit projection + |wordset| (see _confirm_exact)
        self._exact_feats: dict[bytes, tuple[np.ndarray, int, str]] = {}
        if self._nat is not None:
            from licensee_tpu.corpus.compiler import pack_ids

            self._nat_vocab = self._nat.vocab(
                list(self.corpus.vocab.keys()), self.corpus.n_lanes
            )
            for wordset, key in self.corpus.exact_sets.items():
                h = self._nat.exact_hash(wordset)
                if h in self._exact_hashes:
                    continue
                self._exact_hashes[h] = key
                ids = [
                    self.corpus.vocab[w]
                    for w in wordset
                    if w in self.corpus.vocab
                ]
                self._exact_feats[h] = (
                    pack_ids(ids, self.corpus.n_lanes),
                    len(wordset),
                    key,
                )

    @staticmethod
    def _resolve_mesh(mesh, method: str, pad_batch_to: int):
        """Resolve the mesh argument to a jax Mesh (or None = single device).

        The dispatch batch is padded to a fixed ``pad_batch_to``, so the
        data axis must divide it; "auto" shrinks the data axis to the
        largest device count that does."""
        if mesh is None:
            return None
        from jax.sharding import Mesh

        from licensee_tpu.parallel.mesh import build_mesh

        if isinstance(mesh, Mesh):
            resolved = mesh
        elif mesh == "auto":
            if method.startswith("pallas"):
                # the hand-scheduled pallas kernels drive one chip; DP over
                # them would need a shard_map wrapper they don't have yet
                return None
            import jax

            # local devices: in multi-host runs each process scores its
            # own manifest stripe on its own ICI-connected chips
            # (parallel/distributed.py), so the mesh never spans DCN
            local = jax.local_devices()
            n = len(local)
            while pad_batch_to % n:
                n -= 1
            if n == 1:
                return None
            resolved = build_mesh(n_data=n, n_model=1, devices=local)
        else:
            n_data, n_model = mesh
            if n_data < 1 or n_model < 1:
                raise ValueError(
                    f"mesh axes must be positive, got ({n_data}, {n_model})"
                )
            resolved = build_mesh(n_data=n_data, n_model=n_model)
        if method.startswith("pallas"):
            raise ValueError(
                "the pallas methods are single-device; pass mesh=None"
            )
        n_data = resolved.shape["data"]
        if pad_batch_to % n_data:
            raise ValueError(
                f"pad_batch_to={pad_batch_to} is not divisible by the "
                f"data axis ({n_data})"
            )
        return resolved

    # -- host featureization --

    def _prefilter(self, blob: NormalizedBlob) -> BlobResult | None:
        content = blob.content or ""
        if COPYRIGHT_FULL_REGEX.search(ruby_strip(content)):
            return BlobResult("no-license", "copyright", 100.0)
        if blob.wordset is not None and frozenset(blob.wordset) in self._exact_map:
            return BlobResult(self._exact_map[frozenset(blob.wordset)], "exact", 100.0)
        return None

    def features(self, blobs: list[NormalizedBlob]):
        B = len(blobs)
        W = self.corpus.n_lanes
        bits = np.zeros((B, W), dtype=np.uint32)
        n_words = np.zeros(B, dtype=np.int32)
        lengths = np.zeros(B, dtype=np.int32)
        cc_fp = np.zeros(B, dtype=bool)
        for i, blob in enumerate(blobs):
            bits[i], n_words[i], lengths[i] = self.corpus.file_features(blob)
            cc_fp[i] = bool(
                CC_FALSE_POSITIVE_REGEX.search(ruby_strip(blob.content or ""))
            )
        return bits, n_words, lengths, cc_fp

    # -- batch preparation (prefilters + featurization in one pass) --

    def prepare_batch(
        self,
        contents: list[str | bytes],
        prefilter: bool = True,
        filenames: list[str | None] | None = None,
        preset: list | None = None,
        routes: list | None = None,
    ):
        """Sanitize, prefilter and featurize a batch of raw blobs.

        Returns (results, bits, n_words, lengths, cc_fp, todo): ``results``
        holds a BlobResult for prefiltered blobs and None for the ``todo``
        indexes, whose feature rows are filled and ready for the device.
        Thread-safe: rows are written independently and the native calls
        release the GIL, so featurization workers can share one classifier.

        ``prefilter=False`` skips the Copyright/Exact short-circuits so the
        result is pure Dice semantics (the DiceXLA registry matcher runs in
        a chain where Copyright and Exact already had their turn,
        project_files/license_file.rb:67-69).

        ``filenames`` (optional, parallel to ``contents``) enables the
        filename-gated normalizations — today that is the HTML->markdown
        conversion for ``*.html`` license files (content_helper.rb:293-299
        applies reverse_markdown; the gate lives in
        normalize/pipeline.py:_strip_html).

        In readme mode each blob is first reduced to its "## License"
        section (readme_file.rb CONTENT_REGEX via
        ReadmeFile.license_content — the same extraction Project#readme
        applies before constructing the file, project.rb:74-80); a blob
        with no such section matches nothing.  The extracted sections are
        kept on the returned batch for the Reference fallback.

        ``preset`` (optional, parallel to ``contents``) pre-assigns
        result rows — the dedupe cache's hits (BatchProject) — so those
        blobs skip featurization and the device entirely.

        In auto mode each row runs the chain its FILENAME dispatches to
        (``route_for``): license rows the Copyright/Exact/Dice chain,
        readme rows the extraction + chain + Reference fallback, package
        rows the host matcher table, and unrecognized filenames match
        nothing.  ``routes`` (parallel to ``contents``) lets the caller
        pass precomputed routes (BatchProject resolves them before even
        reading the files); otherwise they are derived here.

        A blob whose featurization raises is contained: it gets an
        ``error`` result row and the rest of the batch proceeds (a single
        poisoned blob must not wedge a 10M-file run)."""
        if self.mode == "package":
            return self._prepare_package_batch(contents, filenames, preset)
        B = len(contents)
        W = self.corpus.n_lanes
        bits = np.zeros((B, W), dtype=np.uint32)
        n_words = np.zeros(B, dtype=np.int32)
        lengths = np.zeros(B, dtype=np.int32)
        cc_fp = np.zeros(B, dtype=bool)
        results: list[BlobResult | None] = (
            list(preset) if preset is not None else [None] * B
        )
        # per-row HTML gate: cleared for readme rows below once the
        # pre-extraction conversion has happened, so the featurize paths
        # never convert the same blob twice
        html = [
            self._is_html(filenames[i] if filenames else None)
            for i in range(B)
        ]
        readme_sel: list[bool] | None = None
        if self.mode == "readme":
            readme_sel = [True] * B
        elif self.mode == "auto":
            if routes is None:
                routes = [
                    self.route_for(filenames[i] if filenames else None)
                    for i in range(B)
                ]
            readme_sel = [r == "readme" for r in routes]
            for i, route in enumerate(routes):
                if results[i] is not None:
                    continue
                if route == "package":
                    results[i] = self._package_match_one(
                        contents[i], filenames[i] if filenames else None
                    )
                elif route is None:
                    # no table scores this filename: the reference never
                    # constructs a project file for it (find_files drops
                    # score-0 entries, project.rb:111-117)
                    results[i] = BlobResult(None, None, 0.0)
        sections: list | None = None
        if readme_sel is not None and any(readme_sel):
            from licensee_tpu.project_files.readme_file import ReadmeFile

            sections = [None] * B
            extracted: list = []
            for i, raw in enumerate(contents):
                if results[i] is not None or not readme_sel[i]:
                    # preset (dedupe) rows and non-readme routes skip
                    extracted.append(None)
                    continue
                try:
                    content = (
                        sanitize_content(raw) if raw is not None else ""
                    )
                    if html[i]:
                        # an HTML README must be markdown BEFORE the
                        # header-shaped CONTENT_REGEX scan, not after —
                        # the section it extracts is markdown from here
                        # on, so the later featurize stages see it as
                        # plain text (no second conversion)
                        from licensee_tpu.normalize.html2md import (
                            html_to_markdown,
                        )

                        content = html_to_markdown(content)
                        html[i] = False
                    extracted.append(ReadmeFile.license_content(content))
                except Exception as exc:  # noqa: BLE001 — per-blob containment
                    extracted.append(
                        BlobResult(
                            None, None, 0.0, error=f"featurize_error: {exc}"
                        )
                    )
            for i, section in enumerate(extracted):
                if results[i] is not None or not readme_sel[i]:
                    continue
                if isinstance(section, BlobResult):
                    results[i] = section
                elif section is None:
                    # no license section in the README -> the project never
                    # constructs a ReadmeFile at all (project.rb:76-78)
                    results[i] = BlobResult(None, None, 0.0)
                else:
                    sections[i] = section
            # readme rows proceed with their extracted section (or
            # nothing); license-routed rows keep their raw content
            contents = [
                (sections[i] if sections[i] is not None else "")
                if readme_sel[i]
                else contents[i]
                for i in range(B)
            ]

        from licensee_tpu.native.pipeline import NativeResourceError

        # Whole-batch native fast path: every non-HTML blob goes through
        # ONE ctypes crossing (newline fix + strip + featurize in C++,
        # GIL dropped for the whole batch).  Rows the native side can't
        # take (non-ASCII, PCRE2 resource limits) come back with a
        # non-zero status and fall through to the per-blob paths below.
        done = bytearray(B)
        if self._nat is not None:
            fast: list[int] = []
            fast_bytes: list[bytes] = []
            for i in range(B):
                if results[i] is not None:
                    continue
                if html[i]:
                    continue
                raw = contents[i]
                if isinstance(raw, str):
                    # errors="ignore" drops lone surrogates exactly like
                    # sanitize_content's round-trip (project_file.py:16)
                    raw = raw.encode("utf-8", errors="ignore")
                elif not isinstance(raw, bytes):
                    continue
                fast.append(i)
                fast_bytes.append(raw)
            if fast:
                # token bits land zero-copy in the final batch rows: a
                # full batch writes row i directly, a sparse subset
                # (preset/dedupe rows interleaved) routes through the
                # native row map — no staging matrix, no per-blob
                # copy-out on either shape
                whole = len(fast) == B
                rows = (
                    None if whole else np.asarray(fast, dtype=np.int64)
                )
                meta = np.zeros((len(fast), 3), dtype=np.int32)
                hashes = np.zeros((len(fast), 16), dtype=np.uint8)
                try:
                    status = self._nat.featurize_batch(
                        self._nat_vocab, fast_bytes, bits, meta, hashes,
                        rows=rows,
                    )
                except Exception:  # noqa: BLE001 — whole-batch containment
                    # the per-blob loop below re-does every row with its
                    # own per-blob error containment
                    status = np.full(len(fast), 3, dtype=np.int8)
                    bits[fast] = 0
                for j, i in enumerate(fast):
                    if status[j] != 0:
                        bits[i] = 0  # failed-over row: wiped for Python
                        continue  # per-blob fallback below
                    done[i] = 1
                    flags = int(meta[j, 2])
                    if prefilter and flags & 1:
                        results[i] = BlobResult("no-license", "copyright", 100.0)
                        continue
                    if prefilter:
                        h = hashes[j].tobytes()
                        if h in self._exact_hashes:
                            key = self._confirm_exact(h, bits[i], int(meta[j, 0]))
                            if key is not None:
                                results[i] = BlobResult(key, "exact", 100.0)
                                continue
                    n_words[i] = meta[j, 0]
                    lengths[i] = meta[j, 1]
                    cc_fp[i] = bool(flags & 2)

        for i, raw in enumerate(contents):
            if results[i] is not None or done[i]:
                continue
            try:
                if self._nat is not None:
                    try:
                        self._prepare_one_native(
                            raw, results, bits, n_words, lengths, cc_fp, i,
                            prefilter=prefilter, html=html[i],
                        )
                    except NativeResourceError:
                        # PCRE2 hit a match/depth limit on this blob;
                        # Python re has no such limit — redo just this
                        # blob on the pure-Python pipeline (same answer,
                        # slower) instead of emitting a false error row
                        self._prepare_one_python(
                            raw, results, bits, n_words, lengths, cc_fp, i,
                            prefilter=prefilter, html=html[i],
                        )
                else:
                    self._prepare_one_python(
                        raw, results, bits, n_words, lengths, cc_fp, i,
                        prefilter=prefilter, html=html[i],
                    )
            except Exception as exc:  # noqa: BLE001 — per-blob containment
                results[i] = BlobResult(
                    None, None, 0.0, error=f"featurize_error: {exc}"
                )
                bits[i] = 0
                n_words[i] = 0
                lengths[i] = 0
                cc_fp[i] = False
        todo = [i for i, r in enumerate(results) if r is None]
        return PreparedBatch(
            results, bits, n_words, lengths, cc_fp, todo, sections
        )

    def _prepare_package_batch(
        self, contents, filenames, preset=None
    ) -> PreparedBatch:
        """Package-manifest mode: the whole chain is host regexes.

        Each blob runs the filename-dispatched matcher table of
        package_manager_file.rb (gemspec/npm/cabal/nuget by extension,
        DESCRIPTION/dist.ini/LICENSE.spdx/Cargo.toml by name) and reports
        the declared license — `other` for declared-but-unknown values,
        no match when no matcher claims the filename."""
        B = len(contents)
        results: list[BlobResult | None] = (
            list(preset) if preset is not None else [None] * B
        )
        for i, raw in enumerate(contents):
            if results[i] is not None:
                continue
            results[i] = self._package_match_one(
                raw, filenames[i] if filenames else None
            )
        empty = np.zeros((B, 0), dtype=np.uint32)
        zeros = np.zeros(B, dtype=np.int32)
        return PreparedBatch(
            results, empty, zeros, zeros, np.zeros(B, dtype=bool), []
        )

    def attribution_for(
        self,
        raw,
        filename: str | None,
        result: BlobResult,
        route: str | None = None,
    ) -> str | None:
        """The copyright/attribution line for one matched blob — the batch
        twin of LicenseFile#attribution (license_file.rb:71-77): applicable
        when the Copyright matcher fired or the matched license's template
        carries a [fullname] field; the line is the COPYRIGHT_REGEX hit on
        the stage-1 normalized content.

        Post-match only: a 10M-file run pays this ONLY for matched rows
        (and with dedupe, once per unique content).  Readme rows scan the
        extracted section, exactly like Project#readme constructing the
        ReadmeFile from license_content (project.rb:74-80).  Package rows
        have no attribution (the reference defines it on LicenseFile
        only).  Custom-corpus keys unknown to License.find report None —
        there is no template to prove a [fullname] placeholder from."""
        if result.key is None or result.error:
            return None
        route = route or self.mode
        if route not in ("license", "readme"):
            return None
        # the copyright? gate needs BOTH the Copyright matcher and a
        # "copyright(.ext)" filename (project_file.rb:90-95); otherwise
        # the matched license's template must carry [fullname]
        applicable = False
        if result.matcher == "copyright" and filename is not None:
            from licensee_tpu.project_files.license_file import (
                COPYRIGHT_NAME_REGEX,
            )

            applicable = bool(COPYRIGHT_NAME_REGEX.search(filename))
        if not applicable and not _has_fullname(result.key):
            return None
        content = sanitize_content(raw) if raw is not None else ""
        if route == "readme":
            from licensee_tpu.project_files.readme_file import ReadmeFile

            if self._is_html(filename):
                from licensee_tpu.normalize.html2md import html_to_markdown

                content = html_to_markdown(content)
                filename = None  # gate consumed, same as prepare_batch
            content = ReadmeFile.license_content(content)
            if content is None:
                return None
        blob = NormalizedBlob(content, filename=filename)
        m = COPYRIGHT_REGEX.search(blob.content_without_title_and_version)
        return m.group(0) if m else None

    def _package_match_one(
        self, raw, filename: str | None
    ) -> BlobResult:
        """One blob through the filename-dispatched package matcher table
        (package_manager_file.rb + the matcher family's lenient regexes),
        with the same per-blob error containment as every other chain."""
        from licensee_tpu.project_files.package_manager_file import (
            PackageManagerFile,
        )

        try:
            pf = PackageManagerFile(raw, filename)
            matcher = pf.matcher
            lic = matcher.match if matcher is not None else None
            if matcher is not None and lic is not None:
                return BlobResult(
                    lic.key, matcher.name, float(matcher.confidence)
                )
            return BlobResult(None, None, 0.0)
        except Exception as exc:  # noqa: BLE001 — per-blob containment
            return BlobResult(
                None, None, 0.0, error=f"featurize_error: {exc}"
            )

    def _prepare_one_python(
        self, raw, results, bits, n_words, lengths, cc_fp, i, prefilter=True,
        html=False,
    ) -> None:
        """The pure-Python twin of _prepare_one_native — the fallback when
        the native library is absent or failed this blob over.

        ``html`` is the per-row gate prepare_batch resolved (and possibly
        already consumed, for readme sections) — the helpers never
        re-derive it from a filename.  The sentinel name below only
        re-arms NormalizedContent's own stage-ordered _strip_html."""
        t0 = time.perf_counter()
        blob = NormalizedBlob(raw, filename="x.html" if html else None)
        results[i] = self._prefilter(blob) if prefilter else None
        if results[i] is None:
            bits[i], n_words[i], lengths[i] = self.corpus.file_features(blob)
            cc_fp[i] = bool(
                CC_FALSE_POSITIVE_REGEX.search(ruby_strip(blob.content or ""))
            )
        # fallback parity for the native stage.*/count.* profile surface
        # (native/pipeline.py profile_dump): blobs featurized on the
        # pure-Python path account under the same keys
        from licensee_tpu.native.pipeline import py_profile_add

        py_profile_add(**{
            "count.blobs": 1,
            "count.bytes_in": len(raw) if raw is not None else 0,
            "stage.normalize_s": time.perf_counter() - t0,
        })

    @staticmethod
    def _is_html(filename: str | None) -> bool:
        return bool(filename) and filename.lower().endswith((".html", ".htm"))

    @staticmethod
    def route_for(filename: str | None) -> str | None:
        """Per-file chain dispatch for mixed manifests (--mode auto).

        The reference selects each project-file class by its own filename
        score table (project.rb:111-117 via LicenseFile.name_score
        license_file.rb:38-59, ReadmeFile.name_score readme_file.rb:6-12,
        PackageManagerFile.name_score package_manager_file.rb:30-41).  A
        batch manifest emits ONE row per entry, so the top-scoring class
        wins; ties prefer license > package > readme (the reference's
        Project#license consults license_files first).  A filename no
        table scores is never read at all — exactly like find_files
        dropping score-0 entries."""
        if not filename:
            return None
        from licensee_tpu.project_files.license_file import LicenseFile
        from licensee_tpu.project_files.package_manager_file import (
            PackageManagerFile,
        )
        from licensee_tpu.project_files.readme_file import ReadmeFile

        score, route = max(
            (LicenseFile.name_score(filename), "license"),
            (PackageManagerFile.name_score(filename), "package"),
            (ReadmeFile.name_score(filename), "readme"),
            key=lambda t: t[0],
        )
        return route if score > 0 else None

    def _prepare_one_native(
        self, raw, results, bits, n_words, lengths, cc_fp, i, prefilter=True,
        html=False,
    ) -> None:
        content = sanitize_content(raw) if raw is not None else ""
        if html:
            # the native PCRE2 pipeline has no HTML parser; convert here so
            # the stages see markdown, exactly like the scalar path
            from licensee_tpu.normalize.html2md import html_to_markdown

            content = html_to_markdown(content)
        stripped = ruby_strip(content)
        feat = self._nat.featurize_raw(self._nat_vocab, stripped, bits[i])
        if feat is None:
            # non-ASCII: the downcase between the stages must be
            # full-Unicode, so it happens in Python (two crossings)
            s1, flags = self._nat.stage1(stripped)
            _, nw, ln, h = self._nat.featurize(
                self._nat_vocab, s1.lower(), bits[i]
            )
        else:
            _, nw, ln, flags, h = feat
        if prefilter and flags & 1:
            results[i] = BlobResult("no-license", "copyright", 100.0)
            return
        if prefilter and h in self._exact_hashes:
            # the 128-bit additive multiset hash only TRIGGERS the check;
            # the answer rests on a complete equality proof (below)
            key = self._confirm_exact(h, bits[i], nw)
            if key is not None:
                results[i] = BlobResult(key, "exact", 100.0)
                return
        n_words[i] = nw
        lengths[i] = ln
        cc_fp[i] = bool(flags & 2)

    def _confirm_exact(self, h, blob_bits, nw) -> str | None:
        """Set-equality proof for an exact-hash hit, O(n_lanes) per blob.

        The compiler's vocab covers every template's FULL wordset
        (corpus/compiler.py), so for template T with word count c and
        in-vocab bit projection P:  a blob with |wordset| == c and bit
        projection == P has exactly c in-vocab words forming T's set and
        c - c = 0 out-of-vocab words — i.e. wordset equality
        (matchers/exact.rb:6-13), independent of any hash property.  The
        additive hash (linear, collidable in principle) is never trusted,
        only used to pick the candidate template."""
        tpl_bits, tpl_count, key = self._exact_feats[h]
        if nw != tpl_count or not np.array_equal(blob_bits, tpl_bits):
            return None
        return key

    # -- classification --

    def classify_blobs(
        self,
        contents: list[str | bytes],
        threshold: float | None = None,
        prefilter: bool = True,
        filenames: list[str | None] | None = None,
        routes: list | None = None,
    ) -> list[BlobResult]:
        threshold = (
            licensee_tpu.confidence_threshold() if threshold is None else threshold
        )
        prepared = self.prepare_batch(
            contents, prefilter=prefilter, filenames=filenames, routes=routes
        )
        outs = self.dispatch_chunks(prepared)
        self.finish_chunks(prepared, outs, threshold)
        return prepared.results  # type: ignore[return-value]

    def dispatch_chunks_async(
        self, prepared: PreparedBatch, pad_to: int | None = None
    ) -> DeviceFuture:
        """Submit device scoring for the ``todo`` rows — NON-BLOCKING.

        The returned :class:`DeviceFuture` resolves to the
        ``[(chunk, outs), ...]`` list ``finish_chunks`` consumes; until
        then the device computes (and the device->host copies stream)
        while the host featurizes the next chunk — the overlap seam of
        the whole pipeline.  Nothing on this path synchronizes: no
        ``block_until_ready``, no ``np.asarray`` on device values (the
        ``blocking-device-call`` analysis rule holds the pipeline
        callers to the same contract).  The one blocking exception is
        the FIRST dispatch of a new (shape, device) pair, which pays
        its jit compile inline — pre-compile shapes (serve warmup, the
        bench warm loop) to keep the steady state flat.

        Padded chunks borrow host staging rows from a small
        pre-allocated ring (double/triple buffer, ``staging_depth``)
        instead of paying an ``np.pad`` allocation quartet; the slots
        recycle when the future resolves.  With multi-chip ``lanes``,
        successive chunks round-robin across the visible devices —
        K device lanes behind one featurize lane.

        ``pad_to`` overrides the chunk shape for this dispatch — the
        online micro-batcher (serve/scheduler.py) pads each flush to
        the smallest fitting BUCKET so a 3-row deadline flush doesn't
        pay a 4096-row padded batch.  Each distinct shape jit-compiles
        once per device and is reused forever after (the bucket list
        is fixed), so the steady state never recompiles per request."""
        if prepared.todo and self._fn is None:
            raise RuntimeError(
                "device=False classifier cannot dispatch (featurize "
                "workers only prepare batches)"
            )
        if pad_to is not None:
            if pad_to < 1:
                raise ValueError(f"pad_to must be >= 1, got {pad_to!r}")
            if self.mesh is not None and pad_to % self.mesh.shape["data"]:
                raise ValueError(
                    f"pad_to={pad_to} is not divisible by the data axis "
                    f"({self.mesh.shape['data']})"
                )
        bits, n_words, lengths, cc_fp, todo = (
            prepared.bits,
            prepared.n_words,
            prepared.lengths,
            prepared.cc_fp,
            prepared.todo,
        )
        parts = []
        slots = []
        B = int(pad_to) if pad_to is not None else self.pad_batch_to
        for start in range(0, len(todo), B):
            chunk = todo[start : start + B]
            # compacted batches store only the todo rows: row j <-> todo[j]
            rows = (
                slice(start, start + len(chunk)) if prepared.compact else chunk
            )
            b = bits[rows]
            nw = n_words[rows]
            ln = lengths[rows]
            cf = cc_fp[rows]
            if B - len(chunk):
                slot = self._staging.acquire(B)
                slots.append(slot)
                b, nw, ln, cf = self._staging.fill(slot, b, nw, ln, cf)
            dev = None
            if self.devices is not None:
                with self._dispatch_lock:
                    dev = self.devices[self._rr % len(self.devices)]
                    self._rr += 1
                import jax

                # commit the host rows to THIS lane's chip; the jitted
                # scorer runs where its (committed) arguments live, so
                # successive chunks land on successive chips
                b, nw, ln, cf = jax.device_put((b, nw, ln, cf), dev)
            elif self.mesh is not None:
                from licensee_tpu.parallel.mesh import shard_batch

                b, nw, ln, cf = shard_batch(self.mesh, b, nw, ln, cf)
            t0 = time.perf_counter()
            out = self._fn(b, nw, ln, cf)
            dt = time.perf_counter() - t0
            self._note_dispatch(B, dev, dt)
            # start the device->host copies NOW so the await finds them
            # ready instead of paying a synchronous transfer per array
            # (the main loop's serial section at 10M-file scale)
            for a in out:
                try:
                    a.copy_to_host_async()
                except AttributeError:
                    break  # non-jax arrays (interpret/test paths)
            parts.append((chunk, out))
        release = [
            (lambda s=s: self._staging.release(s)) for s in slots
        ]
        return DeviceFuture(parts, on_resolve=release)

    def dispatch_chunks(self, prepared: PreparedBatch, pad_to: int | None = None):
        """Synchronous convenience over :meth:`dispatch_chunks_async`:
        submit and await in one call, returning resolved host-numpy
        outs.  For the one-shot paths (classify_blobs, the reload
        validation probe, benches); the pipelines keep the future."""
        return self.dispatch_chunks_async(prepared, pad_to=pad_to).result()

    def _note_dispatch(self, B: int, dev, dt: float) -> None:
        """Account one submit: compile (first dispatch of this
        (shape, device) pair) vs steady-state enqueue, totals and
        per-shape."""
        key = (B, None if dev is None else getattr(dev, "id", str(dev)))
        with self._dispatch_lock:
            shape = self._shape_prof.setdefault(
                B,
                {
                    "compiles": 0, "compile_s": 0.0,
                    "dispatches": 0, "dispatch_s": 0.0,
                },
            )
            if key not in self._dispatched_shapes:
                self._dispatched_shapes.add(key)
                self._dispatch_prof["compiles"] += 1
                self._dispatch_prof["compile_s"] += dt
                shape["compiles"] += 1
                shape["compile_s"] += dt
            else:
                self._dispatch_prof["dispatches"] += 1
                self._dispatch_prof["dispatch_s"] += dt
                shape["dispatches"] += 1
                shape["dispatch_s"] += dt

    def dispatch_stats(self) -> dict:
        """The device compile-vs-execute split: counts and seconds of
        first-dispatch-per-(shape, device) (jit compile included) vs
        steady-state dispatches, the compiled shape set, and the same
        split PER SHAPE (``per_shape`` — the serve cold-start story:
        which bucket paid which compile, and what it cost).  Scraped
        into the obs registry; resets with the classifier, never
        midstream."""
        with self._dispatch_lock:
            out = dict(self._dispatch_prof)
            out["shapes"] = sorted({b for b, _dev in self._dispatched_shapes})
            out["per_shape"] = {
                b: {
                    k: (round(v, 6) if isinstance(v, float) else v)
                    for k, v in prof.items()
                }
                for b, prof in sorted(self._shape_prof.items())
            }
        return out

    def merge_prepared(self, group: list[PreparedBatch]) -> PreparedBatch:
        """Coalesce the ``todo`` rows of several prepared batches into ONE
        device batch.

        A dedupe-heavy stream leaves each manifest batch with a handful
        of device rows; dispatching those per-batch pays a full padded
        chunk and a device round trip each (the dominant stage of the 1M
        dup-heavy run, ~78% of elapsed).  Merging the sparse tails of
        many batches into full ``pad_batch_to`` chunks amortizes that
        round trip; finish_chunks on the merged batch then applies the
        readme Reference fallback and the closest trim exactly as it
        would per-batch (sections travel with their rows, and fallback /
        trim rows are todo rows by construction — a preset row is never
        section-carrying).  Use scatter_merged to write results back."""
        if len(group) == 1 and not group[0].compact:
            return group[0]
        parts = [p for p in group if p.todo]
        any_sections = any(p.sections is not None for p in parts)
        bits_parts, nw_parts, ln_parts, cc_parts = [], [], [], []
        sections: list | None = [] if any_sections else None
        total = 0
        for p in parts:
            idx = None if p.compact else np.asarray(p.todo, dtype=np.int64)
            n = len(p.todo)
            bits_parts.append(p.bits[:n] if idx is None else p.bits[idx])
            nw_parts.append(p.n_words[:n] if idx is None else p.n_words[idx])
            ln_parts.append(p.lengths[:n] if idx is None else p.lengths[idx])
            cc_parts.append(p.cc_fp[:n] if idx is None else p.cc_fp[idx])
            if sections is not None:
                sections.extend(
                    p.sections[i] if p.sections is not None else None
                    for i in p.todo
                )
            total += n
        W = self.corpus.n_lanes
        return PreparedBatch(
            results=[None] * total,
            bits=(
                np.concatenate(bits_parts)
                if bits_parts
                else np.zeros((0, W), np.uint32)
            ),
            n_words=(
                np.concatenate(nw_parts)
                if nw_parts
                else np.zeros(0, np.int32)
            ),
            lengths=(
                np.concatenate(ln_parts)
                if ln_parts
                else np.zeros(0, np.int32)
            ),
            cc_fp=(
                np.concatenate(cc_parts) if cc_parts else np.zeros(0, bool)
            ),
            todo=list(range(total)),
            sections=sections,
            compact=True,
        )

    @staticmethod
    def scatter_merged(group: list[PreparedBatch], merged: PreparedBatch):
        """Copy a merged batch's finished results back into the source
        batches' ``todo`` rows (inverse of merge_prepared's row order)."""
        if len(group) == 1 and merged is group[0]:
            return
        off = 0
        for p in group:
            for j, i in enumerate(p.todo):
                p.results[i] = merged.results[off + j]
            off += len(p.todo)

    def finish_chunks(self, prepared: PreparedBatch, outs, threshold) -> None:
        """Synchronize device outputs and finish scores in float64 —
        identical to Ruby's Float score (dice.rb:57-59).

        In readme mode a blob the Dice pass left unmatched falls through
        to the Reference matcher (the last entry of the readme chain,
        readme_file.rb:32-34): a license named by title or source URL in
        the extracted section matches at confidence 90.

        ``outs`` may be the resolved list or a still-in-flight
        :class:`DeviceFuture` — awaiting it here IS the synchronize."""
        if isinstance(outs, DeviceFuture):
            outs = outs.result()
        results = prepared.results
        for chunk, out in outs:
            best_idx, best_num, best_den = (
                np.asarray(a)[: len(chunk)] for a in out[:3]
            )
            k_rows: list | None = None
            if len(out) == 6:  # closest=K: top-k candidate columns
                k_idx, k_num, k_den = (
                    np.asarray(a)[: len(chunk)] for a in out[3:]
                )
                k_scores = np.where(
                    (k_num >= 0) & (k_den > 0), (k_num * 200.0) / k_den, -1.0
                )
                k_rows = (k_idx, k_scores)
            scores = np.where(best_den > 0, (best_num * 200.0) / best_den, 0.0)
            for j, i in enumerate(chunk):
                if best_num[j] >= 0 and scores[j] >= threshold:
                    results[i] = BlobResult(
                        self.corpus.keys[int(best_idx[j])],
                        "dice",
                        float(scores[j]),
                        int(best_num[j]),
                        int(best_den[j]),
                    )
                else:
                    results[i] = BlobResult(None, None, 0.0)
                if k_rows is not None:
                    results[i].closest = self._closest_list(
                        k_rows[0][j], k_rows[1][j], results[i].key
                    )
        if self.mode in ("readme", "auto") and prepared.sections is not None:
            for i, section in enumerate(prepared.sections):
                r = results[i]
                if section is None or r is None or r.key or r.error:
                    continue
                lic = self._reference_match(section)
                if lic is not None:
                    # the kept candidate list was built with no matched
                    # key (the Dice pass left the row unmatched); now
                    # that Reference names one, hold the documented
                    # invariant: closest excludes the matched key (the
                    # list is still untrimmed, so the row keeps K
                    # entries after the cut below)
                    kept = r.closest
                    if kept is not None:
                        kept = [(kk, c) for kk, c in kept if kk != lic.key]
                    results[i] = BlobResult(
                        lic.key, "reference", 90.0, closest=kept
                    )
        if self.closest:
            # trim ONLY the rows this call built (the device-scored todo
            # chunks — the readme fallback rows above are a subset): a
            # preset row from the dedupe cache was trimmed by the batch
            # that created it, and a finished result must never be
            # mutated again (cached objects alias many output rows)
            for chunk, _ in outs:
                for i in chunk:
                    r = results[i]
                    if r is not None and r.closest is not None:
                        r.closest = r.closest[: self.closest]

    def _closest_list(self, idx_row, score_row, matched_key):
        """The top-k candidates as [(key, confidence), ...], float64-
        sorted, excluding the matched key and masked (score<0) rows —
        the batch analog of the CLI's closest-licenses list.

        Returns the UNtrimmed list (up to k entries): finish_chunks cuts
        it to ``closest`` only after the readme Reference fallback has
        had its chance to exclude a late-matched key, so reference rows
        keep a full K entries too."""
        rows = [
            (self.corpus.keys[int(t)], float(s))
            for t, s in zip(idx_row, score_row)
            if s >= 0 and self.corpus.keys[int(t)] != matched_key
        ]
        rows.sort(key=lambda r: -r[1])
        return rows

    @staticmethod
    def _reference_match(section: str):
        """The Reference matcher over one extracted section
        (matchers/reference.rb:7-11): first license IN POOL ORDER whose
        title/source regex hits anywhere in the section.

        Batched with the reference's own union trick
        (content_helper.rb:199-215): ONE corpus-wide alternation scans
        the section instead of ~46 sequential searches — the no-mention
        majority of a 50M-readme run pays a single regex.  The union
        alone cannot answer exactly, though: the scan returns hits by
        POSITION, while the chain semantics is by POOL ORDER, and an
        early-pool license whose only hit lies strictly inside another
        alternative's matched span is shadowed in the scan.  So the union
        resolves a floor — min pool index over every scan hit — and only
        the (few, usually zero) licenses BELOW that floor re-run their
        own regex; the first individual hit wins, else the floor does.
        Exact by construction: the true answer t satisfies
        t <= floor (the floor's license provably matches), and every
        i < floor is checked individually.

        The scan itself runs in PCRE2+JIT (pipe_refscan_min, byte mode —
        the faithful twin of rb()'s re.A ASCII classes over UTF-8) when
        the native library is up — Python re walks a 46-branch
        alternation ~10x slower than it walks one branch, PCRE2's JIT
        does not.  The floor is always re-confirmed with the license's
        own Python regex; any divergence degrades to the exact
        sequential chain."""
        lics, union = _reference_union()

        def exact_chain():
            # the reference's own sequential chain — the last-resort
            # answer on (never-observed) scan/backtracker divergence
            for lic in lics:
                if lic.reference_regex.search(section):
                    return lic
            return None

        nat = _refscan_native()
        if nat is not None:
            f = nat[0].refscan_resolve(nat[1], section)
            if f == -1:
                return None
            if f >= 0:
                # already shadow-resolved in C; one Python confirm guards
                # the divergence case
                if lics[f].reference_regex.search(section):
                    return lics[f]
                return exact_chain()
            # f == -2: PCRE2 resource failure -> Python scan below
        floor = None
        for m in union.finditer(section):
            # exactly one alternative (named group) matches per hit;
            # groupdict preserves pattern (= pool) order, so the first
            # non-None entry is it
            i = next(
                int(name[1:])
                for name, val in m.groupdict().items()
                if val is not None
            )
            if floor is None or i < floor:
                floor = i
            if floor == 0:
                break
        if floor is None:
            return None
        if not lics[floor].reference_regex.search(section):
            return exact_chain()
        for i in range(floor):
            if lics[i].reference_regex.search(section):
                return lics[i]
        return lics[floor]


