"""The durable-jobs selftest (``licensee-tpu fleet --selftest-jobs``).

The one end-to-end crash drill the jobs tier promises: a REAL fleet
process (stub or serve workers + router + front socket + HTTP edge +
JobExecutor, booted by this module's ``__main__`` child mode) takes a
tar-manifest job over ``POST /jobs``, and the WHOLE process tree —
executor, its stripe children, the workers — is SIGKILLed mid-drain.
A second fleet process booted on the same jobs dir must replay the
journal, resume the interrupted job from its stripe shards, and serve
merged results byte-identical to a direct ``StripeRunner`` run of the
same spec (the ``batch-detect --stripes`` machinery).  The gates:

* the job completed BEFORE the kill stays completed after replay;
* the killed job resumes (``resumed`` in its status) and completes;
* its merged results JSONL and container-verdict sidecar are
  sha256-identical to the direct striped reference run;
* zero client-visible errors: every HTTP round trip answers its
  expected code (202 accepted, 200 status/results, 401 bad token,
  404 unknown id, 409 results-before-done, 200 duplicate submit —
  idempotency keys survive the restart via the journal);
* a job submitted to the restarted fleet assembles ONE trace tree
  joining the edge's submit span (proc ``router``) and the executor's
  queue-wait/stripe/merge spans (proc ``jobs``) over the front
  socket's ``{"op": "traces"}`` verb.

``stub=True`` (the CI path) runs the protocol-faithful stub worker
behind the router; the stripe children are ALWAYS real batch-detect
processes on CPU — resume byte-identity is the whole point.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import signal
import subprocess
import sys
import tarfile
import threading
import time

_EDGE_TOKEN = "jobs-selftest-token"


# -- the child: one fleet process with a jobs tier -----------------------


def _stub_argv(name: str, sock: str) -> list[str]:
    return [
        sys.executable, "-m", "licensee_tpu.fleet.faults",
        "--socket", sock, "--name", name, "--service-ms", "5",
    ]


def _serve_argv(name: str, sock: str) -> list[str]:
    return [
        sys.executable, "-m", "licensee_tpu.cli.main", "serve",
        "--socket", sock, "--max-delay-ms", "5",
    ]


def _serve_child(jobs_dir: str, stub: bool) -> int:
    """Boot worker + router + front socket + HTTP edge + JobExecutor
    over ``jobs_dir``, write one READY line (JSON: edge port, front
    socket path) to stdout, and serve until killed.  The drill parent
    SIGKILLs this process's whole group — there is no graceful exit."""
    from licensee_tpu.fleet.http_edge import HttpEdgeServer
    from licensee_tpu.fleet.router import FrontServer, Router
    from licensee_tpu.fleet.supervisor import Supervisor, worker_env
    from licensee_tpu.jobs.executor import JobExecutor

    run_dir = os.path.join(jobs_dir, "run")
    os.makedirs(run_dir, exist_ok=True)
    # per-boot socket names: the previous incarnation's files survive
    # its SIGKILL, and a rebind on the same path would refuse
    worker_sock = os.path.join(run_dir, f"w0-{os.getpid()}.sock")
    front_sock = os.path.join(run_dir, f"front-{os.getpid()}.sock")
    env = worker_env(None, None)
    env.setdefault("JAX_PLATFORMS", "cpu")
    boot_timeout = 30.0 if stub else 300.0
    supervisor = Supervisor(
        {"w0": worker_sock},
        argv_for=(_stub_argv if stub else _serve_argv),
        env_for=lambda name, chips: env,
        probe_interval_s=0.25,
        startup_grace_s=boot_timeout,
    )
    supervisor.start()
    if not supervisor.wait_healthy(boot_timeout):
        sys.stderr.write(
            f"jobs-selftest child: worker never healthy: "
            f"{supervisor.status()}\n"
        )
        supervisor.stop()
        return 1
    router = Router(
        {"w0": worker_sock},
        supervisor=supervisor,
        probe_interval_s=0.25,
        trace_sample=1.0,
    )
    router.start()
    executor = JobExecutor(
        jobs_dir,
        max_concurrent=1,
        registry=router.obs.registry,
        base_env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    executor.start()
    router.collector.add_source("jobs", executor.trace_tail)
    front = FrontServer(front_sock, router, stall_timeout_s=5.0)
    edge = HttpEdgeServer(
        "127.0.0.1:0", router,
        tokens={_EDGE_TOKEN: "drill"},
        rate_per_client=100000.0,
        stall_timeout_s=5.0,
        jobs=executor,
    )
    threading.Thread(
        target=front.serve_forever, kwargs={"poll_interval": 0.05},
        daemon=True,
    ).start()
    sys.stdout.write(json.dumps({
        "ready": True,
        "port": edge.bound_port,
        "front": front_sock,
        "resumed": executor.resumed_jobs,
    }) + "\n")
    sys.stdout.flush()
    try:
        edge.serve_forever(poll_interval=0.05)
    except KeyboardInterrupt:
        pass
    return 0


# -- the drill parent ----------------------------------------------------


def _build_corpus(tmpdir: str) -> tuple[list[str], str]:
    """42 synthetic license files plus a tarball of all of them under
    their absolute names (so per-blob JSONL rows from the tar run are
    byte-identical to a loose-file run — the stripes selftest's
    construction)."""
    import re

    from licensee_tpu.corpus.license import License

    bodies = [
        re.sub(r"\[(\w+)\]", "example", License.find(k).content or "")
        for k in ("mit", "isc", "bsd-3-clause")
    ]
    paths = []
    for i in range(42):
        p = os.path.join(tmpdir, f"LICENSE_{i}")
        with open(p, "w", encoding="utf-8") as f:
            f.write(
                f"Copyright (c) {2000 + i} Example Author {i}\n\n"
                + bodies[i % len(bodies)]
            )
        paths.append(p)
    tar_path = os.path.join(tmpdir, "archive.tar")
    with tarfile.open(tar_path, "w") as tf:
        for p in paths:
            with open(p, "rb") as f:
                data = f.read()
            info = tarfile.TarInfo(name=p)
            info.size = len(data)
            tf.addfile(info, io.BytesIO(data))
    return paths, tar_path


def _reference_run(
    tmpdir: str, tar_path: str, stripes: int, say,
) -> tuple[bytes, bytes]:
    """The direct ``batch-detect --stripes`` run the job's results
    must byte-match: same manifest, same stripe count, same forwarded
    knobs, no jobs tier in the path."""
    from licensee_tpu.parallel.stripes import StripeRunner

    manifest = os.path.join(tmpdir, "ref_manifest.txt")
    with open(manifest, "w", encoding="utf-8") as f:
        f.write(f"{tar_path}::*\n")
    out = os.path.join(tmpdir, "ref.jsonl")
    runner = StripeRunner(
        manifest, out, stripes,
        forward_args=("--batch-size", "16", "--mesh", "none"),
        base_env={**os.environ, "JAX_PLATFORMS": "cpu"},
        on_event=say,
    )
    runner.run()
    with open(out, "rb") as f:
        results = f.read()
    with open(f"{out}.containers.jsonl", "rb") as f:
        containers = f.read()
    return results, containers


def _spawn_fleet(
    jobs_dir: str, stub: bool, log_path: str, timeout_s: float,
) -> tuple[subprocess.Popen | None, dict | None]:
    """Start one fleet child in its OWN session (so ``killpg`` takes
    the executor AND its stripe children down in one blow) and wait
    for its READY line."""
    argv = [
        sys.executable, "-m", "licensee_tpu.jobs.selftest",
        "--serve", "--jobs-dir", jobs_dir,
    ]
    if stub:
        argv.append("--stub")
    log = open(log_path, "ab")
    try:
        proc = subprocess.Popen(
            argv, stdout=subprocess.PIPE, stderr=log,
            start_new_session=True,
        )
    finally:
        log.close()
    box: dict = {}

    def read() -> None:
        line = proc.stdout.readline()
        try:
            row = json.loads(line)
            if isinstance(row, dict):
                box.update(row)
        except json.JSONDecodeError:
            box["raw"] = line.decode("utf-8", "replace")

    t = threading.Thread(target=read, daemon=True)
    t.start()
    t.join(timeout=timeout_s)
    if not box.get("ready"):
        _killpg(proc)
        return None, None
    return proc, box


def _killpg(proc: subprocess.Popen) -> None:
    try:
        os.killpg(proc.pid, signal.SIGKILL)
    except (OSError, ProcessLookupError):
        pass
    try:
        proc.wait(timeout=10.0)
    except subprocess.TimeoutExpired:
        pass
    if proc.stdout is not None:
        proc.stdout.close()


def _tail_of(path: str, n: int = 800) -> str:
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        return ""
    return data[-n:].decode("utf-8", "replace")


def selftest_jobs(verbose: bool = True, stub: bool = True) -> int:
    """Run the drill; returns 0 on success, 1 with a problem report."""
    import tempfile

    from licensee_tpu.fleet.wire import WireError, oneshot
    from licensee_tpu.jobs.client import JobsClient, JobsClientError

    stream = sys.stderr

    def say(msg: str) -> None:
        if verbose:
            stream.write(f"jobs-selftest: {msg}\n")
            stream.flush()

    problems: list[str] = []
    boot_timeout = 30.0 if stub else 300.0
    job_timeout = 180.0 if stub else 600.0
    kill_had_shard_bytes = False
    resumed_row: dict = {}
    sha_match = False
    procs_joined: list[str] = []
    child_a = child_b = None
    client = None
    with tempfile.TemporaryDirectory(prefix="licensee-jobs-") as tmpdir:
        jobs_dir = os.path.join(tmpdir, "jobs")
        os.makedirs(jobs_dir)
        log_a = os.path.join(tmpdir, "fleet-a.log")
        log_b = os.path.join(tmpdir, "fleet-b.log")
        try:
            paths, tar_path = _build_corpus(tmpdir)
            say("reference run: direct 2-stripe batch-detect")
            ref_results, ref_containers = _reference_run(
                tmpdir, tar_path, 2, say
            )
            ref_sha = hashlib.sha256(ref_results).hexdigest()

            say("booting fleet A (stub workers)" if stub
                else "booting fleet A (serve workers)")
            child_a, ready = _spawn_fleet(
                jobs_dir, stub, log_a, boot_timeout
            )
            if child_a is None:
                problems.append(
                    f"fleet A never became ready: {_tail_of(log_a)!r}"
                )
                raise _Abort()
            target = f"127.0.0.1:{ready['port']}"

            # -- auth: a wrong bearer token answers 401 --
            bad = JobsClient(target, token="wrong-token")
            try:
                code, _row = bad.submit({"manifest": ["x"]})
            finally:
                bad.close()
            if code != 401:
                problems.append(f"bad token answered {code}, wanted 401")

            client = JobsClient(target, token=_EDGE_TOKEN)

            # -- a 404 for an id the journal has never seen --
            code, row = client.status("deadbeefdead")
            if code != 404:
                problems.append(
                    f"unknown job id answered {code}: {row}"
                )

            # -- job 1: small, completes before the kill --
            spec1 = {
                "manifest": paths[:6],
                "stripes": 1,
                "options": {"batch_size": 16, "mesh": "none"},
                "idempotency_key": "drill-job1",
            }
            code, row = client.submit(spec1)
            if code != 202:
                problems.append(f"job1 submit answered {code}: {row}")
                raise _Abort()
            job1 = row["job_id"]
            row = client.wait(job1, timeout_s=job_timeout)
            if row.get("state") != "completed":
                problems.append(f"job1 never completed: {row}")
                raise _Abort()
            say(f"job1 {job1}: completed "
                f"({row.get('rows_written')} rows)")

            # -- duplicate submit, same idempotency key: original id --
            code, row = client.submit(spec1)
            if code != 200 or row.get("job_id") != job1 or not row.get(
                "duplicate"
            ):
                problems.append(
                    f"duplicate submit answered {code}: {row}"
                )

            # -- job 2: the victim — tar manifest, 2 stripes --
            spec2 = {
                "manifest": [f"{tar_path}::*"],
                "stripes": 2,
                "options": {"batch_size": 16, "mesh": "none"},
                "idempotency_key": "drill-job2",
            }
            code, row = client.submit(spec2)
            if code != 202:
                problems.append(f"job2 submit answered {code}: {row}")
                raise _Abort()
            job2 = row["job_id"]

            # -- results before completion: 409 --
            code, payload = client.results(job2)
            if code != 409:
                problems.append(
                    f"early results answered {code}, wanted 409"
                )

            # -- SIGKILL the whole fleet A tree mid-drain --
            deadline = time.perf_counter() + job_timeout
            killed = False
            while time.perf_counter() < deadline:
                code, row = client.status(job2)
                if code != 200:
                    problems.append(
                        f"job2 status poll answered {code}: {row}"
                    )
                    raise _Abort()
                if row.get("state") in ("completed", "failed"):
                    problems.append(
                        f"job2 reached {row['state']} before the kill "
                        "landed — the drill never drilled"
                    )
                    raise _Abort()
                if row.get("state") == "running" and row.get(
                    "first_progress"
                ):
                    kill_had_shard_bytes = bool(row.get("shard_bytes"))
                    say(
                        f"job2 {job2}: running "
                        f"(shard_bytes={row.get('shard_bytes')}) — "
                        "SIGKILL fleet A"
                    )
                    _killpg(child_a)
                    killed = True
                    break
                time.sleep(0.05)
            if not killed:
                problems.append("job2 never reached running+progress")
                raise _Abort()
            client.close()
            client = None

            # -- fleet B on the same jobs dir: replay + resume --
            say("booting fleet B on the same jobs dir")
            child_b, ready = _spawn_fleet(
                jobs_dir, stub, log_b, boot_timeout
            )
            if child_b is None:
                problems.append(
                    f"fleet B never became ready: {_tail_of(log_b)!r}"
                )
                raise _Abort()
            if ready.get("resumed") != 1:
                problems.append(
                    f"fleet B resumed {ready.get('resumed')} job(s), "
                    "wanted exactly the killed one"
                )
            target = f"127.0.0.1:{ready['port']}"
            front_sock = ready["front"]
            client = JobsClient(target, token=_EDGE_TOKEN)

            # -- the executor's black box survived the SIGKILL: fleet
            # A's flight recorder spilled its submit/finish events to
            # the jobs dir before the kill, and the harvest must not
            # come back empty (the whole point of a black box) --
            from licensee_tpu.obs import load_flight_dump

            box = load_flight_dump(os.path.join(jobs_dir, "flight.json"))
            box_kinds = {
                e.get("kind") for e in (box or {}).get("events") or ()
            }
            if not box or not box_kinds:
                problems.append(
                    "executor flight recorder left no harvest after "
                    f"the SIGKILL drill: {box}"
                )
            elif not box_kinds & {"job_submit", "job_resume"}:
                # fleet A's box carries the submits; if fleet B's
                # flusher already rewrote the file, its replay carries
                # the resume of the killed job — either proves the
                # black box closed the loop
                problems.append(
                    f"flight harvest has no job events: {box_kinds}"
                )
            else:
                say(f"flight harvest: {sorted(box_kinds)}")

            # the completed job survived the journal replay
            code, row = client.status(job1)
            if code != 200 or row.get("state") != "completed":
                problems.append(
                    f"job1 after replay: {code} {row} — a terminal "
                    "state was lost"
                )

            # the idempotency key survived too: resubmit folds to the
            # SAME job id across the restart
            code, row = client.submit(spec2)
            if code != 200 or row.get("job_id") != job2:
                problems.append(
                    f"job2 resubmit after restart answered {code}: "
                    f"{row} — the idempotency fence broke"
                )

            resumed_row = client.wait(job2, timeout_s=job_timeout)
            if resumed_row.get("state") != "completed":
                problems.append(f"job2 never completed: {resumed_row}")
                raise _Abort()
            if not resumed_row.get("resumed"):
                problems.append(
                    f"job2 completed without the resumed flag: "
                    f"{resumed_row} — did the replay re-run it fresh?"
                )
            say(f"job2 {job2}: resumed and completed "
                f"({resumed_row.get('rows_written')} rows)")

            # -- byte identity against the direct striped run --
            code, payload = client.results(job2)
            if code != 200:
                problems.append(f"job2 results answered {code}")
                raise _Abort()
            got_sha = hashlib.sha256(payload).hexdigest()
            sha_match = got_sha == ref_sha
            if not sha_match:
                problems.append(
                    f"job2 results sha {got_sha[:16]} != direct-run "
                    f"sha {ref_sha[:16]} ({len(payload)} vs "
                    f"{len(ref_results)} bytes)"
                )
            code, payload = client.containers(job2)
            if code != 200 or payload != ref_containers:
                problems.append(
                    f"job2 container sidecar mismatch (code {code}, "
                    f"{len(payload)} vs {len(ref_containers)} bytes)"
                )

            # -- the assembled trace: edge submit + executor spans --
            spec3 = {
                "manifest": paths[:4],
                "stripes": 1,
                "options": {"batch_size": 16, "mesh": "none"},
            }
            code, row = client.submit(spec3)
            if code != 202 or not row.get("trace"):
                problems.append(
                    f"job3 submit answered {code}: {row} (no trace id)"
                )
                raise _Abort()
            job3, trace_id = row["job_id"], row["trace"]
            row = client.wait(job3, timeout_s=job_timeout)
            if row.get("state") != "completed":
                problems.append(f"job3 never completed: {row}")
                raise _Abort()
            try:
                answer = oneshot(
                    front_sock,
                    {"op": "traces", "n": 5, "trace_id": trace_id},
                    10.0,
                )
            except WireError as exc:
                problems.append(f"traces verb failed: {exc}")
                answer = {}
            trees = answer.get("traces") or []
            if not trees:
                problems.append(
                    f"no assembled tree for job3 trace {trace_id}"
                )
            else:
                procs_joined = trees[0].get("procs") or []
                span_names = _span_names(trees[0].get("root") or {})
                if "jobs" not in procs_joined:
                    problems.append(
                        f"assembled tree joined procs {procs_joined} "
                        "— the executor's spans are missing"
                    )
                if "router" not in procs_joined:
                    problems.append(
                        f"assembled tree joined procs {procs_joined} "
                        "— the edge submit span is missing"
                    )
                if not any(n.startswith("stripe") for n in span_names):
                    problems.append(
                        f"no stripe span in the tree: {span_names}"
                    )
        except _Abort:
            pass
        except (OSError, JobsClientError, KeyError) as exc:
            problems.append(
                f"selftest crashed: {type(exc).__name__}: {exc}"
            )
        finally:
            if client is not None:
                client.close()
            for child in (child_a, child_b):
                if child is not None:
                    _killpg(child)
        if problems:
            for log in (log_a, log_b):
                tail = _tail_of(log)
                if tail:
                    say(f"{os.path.basename(log)} tail: {tail!r}")
    if verbose:
        stream.write(json.dumps({
            "jobs_selftest": "ok" if not problems else "FAIL",
            "stub_workers": stub,
            "resumed_state": resumed_row.get("state"),
            "results_sha_match": sha_match,
            "shards_had_bytes_at_kill": kill_had_shard_bytes,
            "trace_procs": procs_joined,
            "problems": problems,
        }) + "\n")
        stream.flush()
    return 0 if not problems else 1


class _Abort(Exception):
    """Bail out of the drill body into cleanup; the problem that
    triggered it is already recorded."""


def _span_names(node: dict) -> list[str]:
    names = [node.get("name", "")]
    for child in node.get("children") or []:
        if isinstance(child, dict):
            names.extend(_span_names(child))
    return names


def _main(argv: list[str]) -> int:
    import argparse

    parser = argparse.ArgumentParser(prog="jobs-selftest")
    parser.add_argument("--serve", action="store_true")
    parser.add_argument("--jobs-dir", default=None)
    parser.add_argument("--stub", action="store_true")
    args = parser.parse_args(argv)
    if args.serve:
        if not args.jobs_dir:
            sys.stderr.write("--serve needs --jobs-dir\n")
            return 2
        return _serve_child(args.jobs_dir, args.stub)
    return selftest_jobs(stub=args.stub)


if __name__ == "__main__":
    raise SystemExit(_main(sys.argv[1:]))
