"""The job executor: a library parent over the striped batch runner.

``JobExecutor`` owns a jobs directory (journal + one subdirectory per
job), a bounded pool of job-runner threads, and a ``proc="jobs"``
tracer whose tail the fleet's TraceCollector pulls so job spans join
the assembled trace trees.  Each running job IS a
:class:`~licensee_tpu.parallel.stripes.StripeRunner` — the executor
adds exactly what the CLI parent never needed: durable submission
(the journal), idempotent duplicate detection, bounded concurrency,
per-job cancellation, and resume-on-restart.

Resume is the executor's one hard promise: a SIGKILLed executor
replays the journal on ``start()``, re-enqueues every job that never
reached a terminal state, and the re-run StripeRunner resumes each
stripe from its shard's completed prefix — the merged output is
bit-identical to an uninterrupted run because the shards and the
merge are (parallel/stripes.py's contract, drilled by the jobs
selftest).

Threading: ``submit``/``cancel``/``status`` are thread-safe and
non-blocking apart from journal fsyncs and small file reads — the
HTTP edge calls them from the router's ops executor, never the event
loop.  House rules: monotonic clocks only, no prints.
"""

from __future__ import annotations

import json
import os
import threading
import time

from licensee_tpu.obs import FlightRecorder, MetricsRegistry, Tracer
from licensee_tpu.parallel.distributed import shard_output_path
from licensee_tpu.parallel.stripes import (
    StripeError,
    StripeRunner,
    StripeStopped,
)

from licensee_tpu.jobs.journal import JobJournal

__all__ = [
    "JOB_STATES",
    "Job",
    "JobExecutor",
    "TERMINAL_STATES",
    "validate_spec",
]

# the lifecycle: queued -> running -> one terminal state.  A resumed
# job re-enters "queued" (its journal already says "running"; replay
# folds to the LAST record, and the executor re-appends "running" when
# a thread picks it up again).
JOB_STATES: tuple[str, ...] = (
    "queued", "running", "completed", "failed", "cancelled",
)
TERMINAL_STATES: frozenset[str] = frozenset(
    ("completed", "failed", "cancelled")
)

# submit-spec "options" the executor will forward to the batch-detect
# children, typed: everything else in the options dict is refused (an
# authenticated client still never composes child argv directly)
_OPTION_FORWARD: dict[str, tuple[type, str]] = {
    "batch_size": (int, "--batch-size"),
    "workers": (int, "--workers"),
    "mesh": (str, "--mesh"),
    "mode": (str, "--mode"),
    "corpus": (str, "--corpus"),
    "method": (str, "--method"),
    "confidence": (float, "--confidence"),
}

# runner-level options, typed like the forwarded ones but consumed by
# the SUPERVISOR (the elastic autoscaler's bounds/cooldown) — they
# never reach child argv, and they only make sense with
# spec.stripes == "elastic"
_RUNNER_OPTIONS: dict[str, type] = {
    "autoscale_min": int,
    "autoscale_max": int,
    "autoscale_cooldown_s": float,
}

_MAX_MANIFEST_ENTRIES = 1_000_000
_MAX_STRIPES = 64


def validate_spec(spec) -> tuple[dict | None, str | None]:
    """Normalize a submit spec: returns ``(normalized, None)`` or
    ``(None, reason)``.  A spec names the work (manifest entries in
    the ingest grammar — loose paths and ``tar::*``/``zip::*``/
    ``repo.git::REV`` container forms), the stripe count, and typed
    child options; it never carries raw argv."""
    if not isinstance(spec, dict):
        return None, "spec must be a JSON object"
    manifest = spec.get("manifest")
    if not isinstance(manifest, list) or not manifest:
        return None, "spec.manifest must be a non-empty list of entries"
    if len(manifest) > _MAX_MANIFEST_ENTRIES:
        return None, (
            f"spec.manifest has {len(manifest)} entries, over the "
            f"{_MAX_MANIFEST_ENTRIES} cap"
        )
    entries: list[str] = []
    for entry in manifest:
        if not isinstance(entry, str) or not entry.strip():
            return None, "spec.manifest entries must be non-empty strings"
        if "\n" in entry:
            return None, "spec.manifest entries must not embed newlines"
        entries.append(entry.strip())
    stripes = spec.get("stripes", 1)
    if stripes != "elastic" and (
        not isinstance(stripes, int) or isinstance(stripes, bool)
        or not (1 <= stripes <= _MAX_STRIPES)
    ):
        return None, (
            f"spec.stripes must be an int in [1, {_MAX_STRIPES}] or "
            "'elastic'"
        )
    options = spec.get("options", {})
    if not isinstance(options, dict):
        return None, "spec.options must be an object"
    normalized_options: dict = {}
    for name, value in options.items():
        typed = _OPTION_FORWARD.get(name)
        if typed is None:
            want = _RUNNER_OPTIONS.get(name)
            if want is None:
                return None, f"unknown option {name!r}"
            if stripes != "elastic":
                return None, (
                    f"option {name!r} needs spec.stripes = 'elastic'"
                )
        else:
            want, _flag = typed
        if want is float and isinstance(value, int):
            value = float(value)
        if not isinstance(value, want) or isinstance(value, bool):
            return None, f"option {name!r} must be {want.__name__}"
        normalized_options[name] = value
    if stripes == "elastic":
        lo = normalized_options.get("autoscale_min", 1)
        hi = normalized_options.get("autoscale_max", 8)
        if not 1 <= lo <= hi <= _MAX_STRIPES:
            return None, (
                "need 1 <= autoscale_min <= autoscale_max <= "
                f"{_MAX_STRIPES}, got [{lo}, {hi}]"
            )
        if normalized_options.get("autoscale_cooldown_s", 30.0) < 0:
            return None, "autoscale_cooldown_s must be >= 0"
    key = spec.get("idempotency_key")
    if key is not None and (
        not isinstance(key, str) or not key or len(key) > 200
    ):
        return None, "spec.idempotency_key must be a short string"
    problem = _probe_remote_entries(entries)
    if problem is not None:
        return None, problem
    return {
        "manifest": entries,
        "stripes": stripes,
        "options": normalized_options,
        "idempotency_key": key,
    }, None


def _probe_remote_entries(entries: list[str]) -> str | None:
    """Submit-time validation of remote-source manifest entries: a
    cheap HEAD + 1-byte ranged probe of each distinct remote container
    URL (ingest/remote.py), so an unreachable artifact, a server
    without Range support, or a refused shape (git-over-HTTP) is a 400
    at SUBMIT — not a stripe crash minutes into the job.  validate_spec
    runs on the edge's ops thread (like check_corpus_source's
    submit-time IO), never on the event loop."""
    from licensee_tpu.ingest.remote import (
        RemoteError,
        probe_remote,
        remote_entry_kind,
    )
    from licensee_tpu.ingest.sources import SEP

    seen: set[str] = set()
    for entry in entries:
        container = entry.split(SEP, 1)[0]
        if container in seen or remote_entry_kind(container) is None:
            continue
        seen.add(container)
        try:
            probe_remote(container, timeout_s=5.0)
        except RemoteError as exc:
            return f"remote source {container!r} failed its probe: {exc}"
    return None


def forward_args_for(options: dict) -> tuple[str, ...]:
    """The child argv fragment a normalized options dict forwards."""
    forward: list[str] = []
    for name, value in sorted(options.items()):
        typed = _OPTION_FORWARD.get(name)
        if typed is None:
            continue  # runner-level option (autoscale_*): never argv
        _want, flag = typed
        forward += [flag, str(value)]
    return tuple(forward)


class Job:
    """One job's in-memory state: identity, normalized spec, lifecycle,
    and live progress (fed by the runner's structured callbacks plus
    the per-stripe stats artifacts as each stripe completes)."""

    def __init__(self, job_id: str, spec: dict, job_dir: str,
                 trace_id: str | None = None):
        self.job_id = job_id
        self.spec = spec
        self.job_dir = job_dir
        self.trace_id = trace_id
        self.manifest_path = os.path.join(job_dir, "manifest.txt")
        self.output_path = os.path.join(job_dir, "results.jsonl")
        self.state = "queued"
        self.error: str | None = None
        self.resumed = False
        self.cancel_requested = False
        self.runner: StripeRunner | None = None
        self.summary: dict | None = None
        # progress, updated by the runner's on_progress callback on
        # the job thread and read by status() on ops threads — plain
        # dict swaps under the executor lock
        self.stripes_done = 0
        self.shard_bytes: list[int] = []
        self.first_progress = False
        self.stripe_stats: dict[int, dict] = {}
        self.enqueued_at = time.perf_counter()

    def write_manifest(self) -> None:
        os.makedirs(self.job_dir, exist_ok=True)
        tmp = f"{self.manifest_path}.tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write("\n".join(self.spec["manifest"]) + "\n")
        os.replace(tmp, self.manifest_path)

    def status_row(self) -> dict:
        files_done = sum(
            int(s.get("total", 0)) for s in self.stripe_stats.values()
        )
        row = {
            "job_id": self.job_id,
            "state": self.state,
            "stripes": self.spec["stripes"],
            "stripes_done": self.stripes_done,
            "entries": len(self.spec["manifest"]),
            "first_progress": self.first_progress,
            "files_classified": files_done,
            "shard_bytes": sum(self.shard_bytes),
            "resumed": self.resumed,
        }
        if self.trace_id:
            row["trace"] = self.trace_id
        if self.error is not None:
            row["error"] = self.error
        if self.summary is not None:
            row["rows_written"] = self.summary.get("rows_written")
            row["elapsed_s"] = self.summary.get("elapsed_s")
        return row


class JobExecutor:
    """Bounded job-runner pool + durable journal over one jobs dir.

    ``runner_factory(job, on_progress)`` overrides StripeRunner
    construction so tests drive the full submit/journal/resume
    machinery over stub runners; production leaves it None."""

    def __init__(
        self,
        jobs_dir: str,
        *,
        max_concurrent: int = 1,
        registry: MetricsRegistry | None = None,
        base_env: dict | None = None,
        runner_factory=None,
        on_event=None,
        trace_capacity: int = 256,
    ):
        if max_concurrent < 1:
            raise ValueError(
                f"max_concurrent must be >= 1, got {max_concurrent!r}"
            )
        self.jobs_dir = jobs_dir
        self.journal = JobJournal(os.path.join(jobs_dir, "journal.jsonl"))
        # the jobs tier's black box: every submit/resume/cancel/merge
        # transition lands in the ring, spilled to jobs_dir/flight.json
        # — after a SIGKILL the harvest tells the story the journal's
        # terse state rows cannot
        self.flight = FlightRecorder(
            os.path.join(jobs_dir, "flight.json"), proc="jobs"
        )
        self.max_concurrent = int(max_concurrent)
        self.base_env = base_env
        self.runner_factory = runner_factory
        self._on_event = on_event
        # every job trace is retained: jobs are few and coarse, and
        # the fleet collector joins their spans into the edge's trees
        self.tracer = Tracer(
            sample_rate=1.0, slow_ms=0.0, capacity=trace_capacity,
            proc="jobs",
        )
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._jobs: dict[str, Job] = {}
        self._by_key: dict[str, str] = {}
        self._queue: list[str] = []
        self._threads: list[threading.Thread] = []
        self._closing = False
        self._started = False
        self._seq = 0
        self.resumed_jobs = 0
        self._register_metrics(registry)

    # -- metrics --

    def _register_metrics(self, registry: MetricsRegistry | None) -> None:
        reg = registry if registry is not None else MetricsRegistry()
        self.registry = reg
        self._submitted = reg.counter(
            "jobs_submitted_total", "Jobs accepted at the edge"
        )
        self._completed = reg.counter(
            "jobs_completed_total", "Jobs that reached completed"
        )
        self._failed = reg.counter(
            "jobs_failed_total", "Jobs that reached failed"
        )
        self._cancelled = reg.counter(
            "jobs_cancelled_total", "Jobs that reached cancelled"
        )
        self._resumed = reg.counter(
            "jobs_resumed_total",
            "In-flight jobs re-enqueued by journal replay after a restart",
        )
        reg.gauge(
            "jobs_queue_depth", "Jobs queued behind the runner pool"
        ).set_fn(lambda: len(self._queue))
        reg.gauge(
            "jobs_running", "Jobs currently draining through stripes"
        ).set_fn(self._running_count)

    def _running_count(self) -> int:
        with self._lock:
            return sum(
                1 for j in self._jobs.values() if j.state == "running"
            )

    def _event(self, message: str) -> None:
        if self._on_event is not None:
            self._on_event(message)

    # -- identity --

    def _mint_job_id(self) -> str:
        with self._lock:
            self._seq += 1
        return os.urandom(6).hex()

    def job_dir_for(self, job_id: str) -> str:
        return os.path.join(self.jobs_dir, job_id)

    def save_upload(self, name: str, data: bytes) -> str:
        """Stage an uploaded archive under the jobs dir, content-
        addressed: an idempotent resubmit of the same bytes lands on
        the same path and writes nothing.  Returns the saved path (the
        manifest references it through the ingest ``::*`` grammar)."""
        import hashlib

        digest = hashlib.sha256(data).hexdigest()[:16]
        safe = os.path.basename(name.strip()) or "archive"
        updir = os.path.join(self.jobs_dir, "uploads")
        os.makedirs(updir, exist_ok=True)
        path = os.path.join(updir, f"{digest}-{safe}")
        if not os.path.exists(path):
            tmp = f"{path}.tmp-{os.getpid()}"
            with open(tmp, "wb") as f:
                f.write(data)
            os.replace(tmp, path)
        return path

    # -- lifecycle --

    def start(self) -> None:
        """Replay the journal, re-enqueue every non-terminal job, and
        start the runner pool.  Idempotent."""
        records = self.journal.replay()
        with self._lock:
            if self._started:
                return
            self._started = True
            for rec in records:
                kind = rec.get("rec")
                if kind == "submit":
                    spec = rec.get("spec")
                    job_id = rec.get("job")
                    if not (
                        isinstance(spec, dict)
                        and isinstance(job_id, str)
                    ):
                        continue
                    job = Job(
                        job_id, spec, self.job_dir_for(job_id),
                        trace_id=rec.get("trace"),
                    )
                    self._jobs[job_id] = job
                    key = spec.get("idempotency_key")
                    if key:
                        self._by_key[key] = job_id
                elif kind == "state":
                    job = self._jobs.get(rec.get("job"))
                    if job is not None and rec.get("state") in JOB_STATES:
                        job.state = rec["state"]
                        job.error = rec.get("error")
            for job_id, job in self._jobs.items():
                if job.state in TERMINAL_STATES:
                    continue
                # an interrupted "running" job resumes from its stripe
                # shards; a "queued" one simply runs for the first time
                if job.state == "running":
                    job.resumed = True
                    self.resumed_jobs += 1
                    self._resumed.inc()
                job.state = "queued"
                job.enqueued_at = time.perf_counter()
                self._queue.append(job_id)
            n_resumed = self.resumed_jobs
            n_queued = len(self._queue)
            for job_id in self._queue:
                if self._jobs[job_id].resumed:
                    self.flight.record("job_resume", job=job_id)
        self.flight.start()
        if n_queued:
            self._event(
                f"journal replay: {n_queued} job(s) re-enqueued "
                f"({n_resumed} resumed mid-run)"
            )
        for i in range(self.max_concurrent):
            t = threading.Thread(
                target=self._worker, name=f"job-runner-{i}", daemon=True
            )
            t.start()
            self._threads.append(t)

    def close(self, wait: bool = True, timeout_s: float = 30.0) -> None:
        """Stop accepting work and stop the pool.  Running jobs get a
        ``request_stop()`` (their shards stay resume-safe); a later
        ``start()`` on the same dir resumes them."""
        with self._lock:
            self._closing = True
            runners = [
                j.runner for j in self._jobs.values()
                if j.state == "running" and j.runner is not None
            ]
            self._wake.notify_all()
        for runner in runners:
            runner.request_stop()
        if wait:
            deadline = time.perf_counter() + timeout_s
            for t in self._threads:
                t.join(timeout=max(0.1, deadline - time.perf_counter()))
        self.journal.close()
        self.flight.stop()

    # -- the client surface (ops threads) --

    def submit(self, spec: dict, trace_id: str | None = None) -> tuple[Job, bool]:
        """Accept one normalized spec (see :func:`validate_spec`).
        Returns ``(job, created)`` — a duplicate idempotency key
        returns the ORIGINAL job with ``created=False`` and appends
        nothing."""
        key = spec.get("idempotency_key")
        with self._lock:
            if self._closing:
                raise RuntimeError("executor is closing")
            if key:
                existing = self._by_key.get(key)
                if existing is not None:
                    return self._jobs[existing], False
        job_id = self._mint_job_id()
        job = Job(job_id, spec, self.job_dir_for(job_id), trace_id=trace_id)
        job.write_manifest()
        record = {"rec": "submit", "job": job_id, "spec": spec}
        if trace_id:
            record["trace"] = trace_id
        with self._lock:
            if key:
                # re-check under the lock: two racing submits with the
                # same key must converge on one job
                existing = self._by_key.get(key)
                if existing is not None:
                    return self._jobs[existing], False
                self._by_key[key] = job_id
            self._jobs[job_id] = job
        self.journal.append(record)
        self._submitted.inc()
        self.flight.record(
            "job_submit", job=job_id, entries=len(spec["manifest"])
        )
        with self._lock:
            self._queue.append(job_id)
            self._wake.notify()
        self._event(f"job {job_id}: accepted ({len(spec['manifest'])} entries)")
        return job, True

    def cancel(self, job_id: str) -> dict | None:
        """Request cancellation; returns the status row or None when
        the id is unknown.  A queued job cancels immediately; a
        running one drains via ``request_stop()`` and lands in
        "cancelled" with resume-safe shards."""
        with self._lock:
            job = self._jobs.get(job_id)
            if job is None:
                return None
            job.cancel_requested = True
            runner = job.runner
            was_queued = job.state == "queued"
            if was_queued:
                try:
                    self._queue.remove(job_id)
                except ValueError:
                    pass
                job.state = "cancelled"
        self.flight.record(
            "job_cancel", job=job_id, queued=was_queued
        )
        if was_queued:
            self._append_state(job, "cancelled")
            self._cancelled.inc()
        elif runner is not None:
            runner.request_stop()
        return self.status(job_id)

    def status(self, job_id: str) -> dict | None:
        with self._lock:
            job = self._jobs.get(job_id)
            return job.status_row() if job is not None else None

    def results_path(self, job_id: str) -> str | None:
        """The merged output path, only once the job completed."""
        with self._lock:
            job = self._jobs.get(job_id)
            if job is None or job.state != "completed":
                return None
            return job.output_path

    def job(self, job_id: str) -> Job | None:
        with self._lock:
            return self._jobs.get(job_id)

    def trace_tail(self, n: int = 200) -> list[dict]:
        return self.tracer.tail(n)

    # -- the runner pool --

    def _worker(self) -> None:
        while True:
            with self._lock:
                while not self._queue and not self._closing:
                    self._wake.wait(timeout=0.5)
                if self._closing:
                    return
                job_id = self._queue.pop(0)
                job = self._jobs[job_id]
                if job.state == "cancelled":
                    continue
                job.state = "running"
            self._run_job(job)

    def _append_state(self, job: Job, state: str,
                      error: str | None = None) -> None:
        record: dict = {"rec": "state", "job": job.job_id, "state": state}
        if error is not None:
            record["error"] = error[:2000]
        self.journal.append(record)

    def _build_runner(self, job: Job, on_progress) -> StripeRunner:
        spec = job.spec
        forward = forward_args_for(spec["options"])
        stripes = spec["stripes"]
        elastic = None
        if stripes == "elastic":
            from licensee_tpu.parallel.autoscale import AutoscaleConfig

            opts = spec["options"]
            elastic = AutoscaleConfig(
                min_units=opts.get("autoscale_min", 1),
                max_units=opts.get("autoscale_max", 8),
                cooldown_s=opts.get("autoscale_cooldown_s", 30.0),
            )
            stripes = elastic.min_units
        return StripeRunner(
            job.manifest_path,
            job.output_path,
            stripes,
            forward_args=forward,
            resume=True,
            auto_clamp=True,
            base_env=self.base_env,
            progress_every=0.25,
            on_progress=on_progress,
            elastic=elastic,
        )

    def _run_job(self, job: Job) -> None:
        self._append_state(job, "running")
        trace = self.tracer.start(job.job_id, trace_id=job.trace_id)
        t_run0 = time.perf_counter()
        with self._lock:
            enqueued_at = job.enqueued_at
        trace.add_span(
            "job.queue_wait", t_run0 - enqueued_at, t0=enqueued_at
        )
        stripe_t0: dict[int, float] = {}
        last_done_t = [t_run0]

        def on_progress(kind: str, info: dict) -> None:
            now = time.perf_counter()
            if kind == "spawn":
                stripe_t0.setdefault(info["stripe"], now)
                with self._lock:
                    job.first_progress = True
            elif kind == "stripe_done":
                index = info["stripe"]
                t0 = stripe_t0.get(index, t_run0)
                trace.add_span(f"stripe{index}", now - t0, t0=t0)
                last_done_t[0] = now
                stats = self._read_stripe_stats(job, index)
                with self._lock:
                    job.stripes_done += 1
                    if stats is not None:
                        job.stripe_stats[index] = stats
            elif kind == "progress":
                with self._lock:
                    job.first_progress = True
                    job.shard_bytes = list(info.get("shard_bytes", ()))

        try:
            factory = self.runner_factory or self._build_runner
            runner = factory(job, on_progress)
            with self._lock:
                job.runner = runner
                if job.cancel_requested:
                    runner.request_stop()
            summary = runner.run()
        except StripeStopped as exc:
            with self._lock:
                was_cancel = job.cancel_requested
            if was_cancel:
                self._finish(job, trace, "cancelled", str(exc))
                self._cancelled.inc()
            else:
                # the executor itself is draining (close()): leave the
                # job non-terminal so the next start() resumes it
                self._append_state(job, "queued")
                with self._lock:
                    job.state = "queued"
                    job.runner = None
                self.tracer.finish(trace, "stopped")
            return
        except (StripeError, ValueError, OSError) as exc:
            self._finish(job, trace, "failed", str(exc))
            self._failed.inc()
            return
        t_end = time.perf_counter()
        trace.add_span(
            "job.merge", t_end - last_done_t[0], t0=last_done_t[0]
        )
        self.flight.record(
            "job_merge", job=job.job_id,
            rows=summary.get("rows_written"),
            merge_ms=round((t_end - last_done_t[0]) * 1000.0, 3),
        )
        with self._lock:
            job.summary = {
                k: summary.get(k)
                for k in ("rows_written", "elapsed_s", "stripes",
                          "files_per_sec", "already_complete")
            }
            # the runner may have clamped the stripe count to the
            # manifest length: done == what actually ran
            job.stripes_done = summary.get("stripes", job.spec["stripes"])
        self._finish(job, trace, "completed")
        self._completed.inc()
        self._event(
            f"job {job.job_id}: completed "
            f"({summary.get('rows_written')} rows)"
        )

    def _read_stripe_stats(self, job: Job, index: int) -> dict | None:
        """The per-stripe ``--stats-file`` artifact, once that stripe's
        child exited clean — the progress the status verb reports."""
        stripes = job.spec["stripes"]
        if not isinstance(stripes, int):
            # elastic: the shard layout is whatever the runner is
            # currently at (an autoscale rescale renames the shards)
            runner = job.runner
            if runner is None:
                return None
            stripes = runner.stripes
        shard = shard_output_path(job.output_path, index, stripes)
        try:
            with open(f"{shard}.stats.json", encoding="utf-8") as f:
                row = json.load(f)
        except (OSError, json.JSONDecodeError):
            return None
        return row if isinstance(row, dict) else None

    def _finish(self, job: Job, trace, state: str,
                error: str | None = None) -> None:
        self._append_state(job, state, error)
        with self._lock:
            job.state = state
            job.error = error
            job.runner = None
        self.flight.record("job_finish", job=job.job_id, state=state)
        self.tracer.finish(
            trace, "ok" if state == "completed" else state
        )
