"""A minimal HTTP/1.1 client for the jobs API: one keep-alive TCP
connection, sequential round trips, real status-line/Content-Length
parsing — stdlib only, shared by the ``licensee-tpu jobs`` CLI verb
and the jobs selftest so both drive the edge exactly the way an
external submitter would.
"""

from __future__ import annotations

import json
import time

__all__ = ["JobsClient", "JobsClientError"]


class JobsClientError(RuntimeError):
    """The edge answered something the verb cannot use (a non-2xx
    status, an unparsable body) or the connection failed."""


class JobsClient:
    """Sequential jobs-API client against one edge target.

    ``submit``/``status``/``cancel`` return the decoded JSON row;
    ``results``/``containers`` return raw bytes (the merged JSONL is
    a byte-identity contract — decoding it would be a lie)."""

    def __init__(self, target: str, token: str | None = None,
                 timeout_s: float = 30.0):
        from licensee_tpu.fleet.faults import _dial_stream

        self.sock = _dial_stream(target, timeout_s=timeout_s)
        self.reader = self.sock.makefile("rb")
        self.token = token

    def close(self) -> None:
        try:
            self.reader.close()
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass

    # -- one HTTP round trip --

    def request(self, method: str, path: str,
                body: bytes | None = None) -> tuple[int, dict, bytes]:
        auth = (
            f"Authorization: Bearer {self.token}\r\n" if self.token else ""
        )
        body = body if body is not None else b""
        head = (
            f"{method} {path} HTTP/1.1\r\n"
            f"Host: edge\r\n{auth}"
            f"Content-Type: application/json\r\n"
            f"Content-Length: {len(body)}\r\n\r\n"
        ).encode("utf-8")
        self.sock.sendall(head + body)
        status_line = self.reader.readline()
        parts = status_line.decode("utf-8", "replace").split(" ", 2)
        if len(parts) < 2 or not parts[1].isdigit():
            raise JobsClientError(f"bad status line {status_line!r}")
        code = int(parts[1])
        headers: dict = {}
        while True:
            line = self.reader.readline()
            if line in (b"\r\n", b"\n", b""):
                break
            name, _, value = line.decode("utf-8", "replace").partition(":")
            headers[name.strip().lower()] = value.strip()
        length = int(headers.get("content-length", "0"))
        payload = self.reader.read(length) if length else b""
        return code, headers, payload

    def _json(self, method: str, path: str,
              body: bytes | None = None) -> tuple[int, dict]:
        code, _headers, payload = self.request(method, path, body)
        try:
            row = json.loads(payload) if payload else {}
        except json.JSONDecodeError:
            raise JobsClientError(
                f"{method} {path}: unparsable body {payload[:200]!r}"
            ) from None
        if not isinstance(row, dict):
            raise JobsClientError(f"{method} {path}: non-object body")
        return code, row

    # -- the jobs verbs --

    def submit(self, spec: dict) -> tuple[int, dict]:
        body = json.dumps(spec).encode("utf-8")
        return self._json("POST", "/jobs", body)

    def status(self, job_id: str) -> tuple[int, dict]:
        return self._json("GET", f"/jobs/{job_id}")

    def results(self, job_id: str) -> tuple[int, bytes]:
        code, _headers, payload = self.request(
            "GET", f"/jobs/{job_id}/results"
        )
        return code, payload

    def containers(self, job_id: str) -> tuple[int, bytes]:
        code, _headers, payload = self.request(
            "GET", f"/jobs/{job_id}/containers"
        )
        return code, payload

    def cancel(self, job_id: str) -> tuple[int, dict]:
        return self._json("DELETE", f"/jobs/{job_id}")

    def wait(self, job_id: str, timeout_s: float = 600.0,
             poll_s: float = 0.25, on_poll=None) -> dict:
        """Poll until the job reaches a terminal state; returns the
        final status row.  Raises on timeout or a non-200 poll."""
        from licensee_tpu.jobs.executor import TERMINAL_STATES

        deadline = time.perf_counter() + timeout_s
        while True:
            code, row = self.status(job_id)
            if code != 200:
                raise JobsClientError(
                    f"status poll answered {code}: {row}"
                )
            if on_poll is not None:
                on_poll(row)
            if row.get("state") in TERMINAL_STATES:
                return row
            if time.perf_counter() >= deadline:
                raise JobsClientError(
                    f"job {job_id} not terminal after {timeout_s}s "
                    f"(state {row.get('state')!r})"
                )
            time.sleep(poll_s)
