"""The async jobs tier: durable batch jobs through the HTTP edge.

The repo grew both halves of the paper's "scanning service" shape —
the resume-safe striped batch engine (parallel/stripes.py) and the
authenticated HTTP/1.1 edge (fleet/http_edge.py) — and this package is
where they meet: ``POST /jobs`` accepts a manifest (or an uploaded
archive routed through the ``ingest`` container grammar), a durable
append-only journal makes the submission crash-proof, and a
:class:`JobExecutor` drains accepted jobs through the exact
StripeRunner machinery the CLI uses, resuming in-flight jobs from
their stripe shards after a SIGKILL.

House rules (script/lint): monotonic clocks only, no prints — job
ordering is journal order, progress surfaces through callbacks and
the HTTP status verb.
"""

from __future__ import annotations

from licensee_tpu.jobs.executor import (
    JOB_STATES,
    TERMINAL_STATES,
    Job,
    JobExecutor,
    validate_spec,
)
from licensee_tpu.jobs.journal import JobJournal, JournalError

__all__ = [
    "JOB_STATES",
    "Job",
    "JobExecutor",
    "JobJournal",
    "JournalError",
    "TERMINAL_STATES",
    "validate_spec",
]
