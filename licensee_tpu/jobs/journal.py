"""The append-only job journal: one JSONL record per lifecycle edge.

Durability is the whole design.  Every record is a single newline-
terminated ``write()`` followed by flush + fsync, so a record is
either fully on disk or not there at all — the only partial state a
crash can leave is a TORN TAIL (the final line missing its newline,
or cut mid-JSON), and replay tolerates exactly that: the tail is
dropped, everything before it is law.  A torn or invalid line
ANYWHERE ELSE is real corruption and replay refuses loudly rather
than silently resurrecting half a fleet's worth of jobs.

The journal never rewrites: job state folds at replay time (last
state record wins), which keeps appends O(record) and makes the
on-disk format trivially inspectable with ``tail -f``.  Records carry
no wall-clock stamps — ordering IS the journal order, and the batch
tier's house rule (monotonic clocks only) holds here too.
"""

from __future__ import annotations

import json
import os
import threading

__all__ = ["JobJournal", "JournalError"]


class JournalError(RuntimeError):
    """The journal is corrupt beyond the torn-tail contract (an
    invalid record that IS newline-terminated, i.e. was fully
    written once) — replay must not guess."""


class JobJournal:
    """One on-disk journal file, append-only, thread-safe.

    ``append`` serializes the record to one JSON line and fsyncs it;
    ``replay`` yields every durable record in order.  The file handle
    stays open across appends (the executor appends on job lifecycle
    edges, a few per job)."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._f = None
        self.appended = 0

    def _handle(self):
        if self._f is None:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            self._f = open(self.path, "ab")
        return self._f

    def append(self, record: dict) -> None:
        """Durably append one record: a single write of the full
        newline-terminated line, then flush + fsync — after this
        returns, the record survives a SIGKILL."""
        line = json.dumps(record, separators=(",", ":")) + "\n"
        data = line.encode("utf-8")
        if b"\n" in data[:-1]:
            raise ValueError("journal record serialized with embedded newline")
        with self._lock:
            f = self._handle()
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
            self.appended += 1

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None

    def replay(self) -> list[dict]:
        """Every durable record, in append order.  A torn tail (the
        last line lacking its newline, or the last line not parsing)
        is dropped — that is the one state an fsync'd single-write
        append can leave after a crash.  An invalid NON-tail record
        raises :class:`JournalError`."""
        try:
            with open(self.path, "rb") as f:
                raw = f.read()
        except FileNotFoundError:
            return []
        records: list[dict] = []
        lines = raw.split(b"\n")
        # a newline-terminated file splits with a trailing empty
        # element; anything after the final newline is the torn tail
        torn_tail = lines.pop() if lines else b""
        for i, line in enumerate(lines):
            if not line.strip():
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as exc:
                if i == len(lines) - 1 and not torn_tail:
                    # final newline made it to disk but the line body
                    # didn't survive the crash intact: still the tail
                    continue
                raise JournalError(
                    f"{self.path}: corrupt record at line {i + 1}: {exc}"
                ) from None
            if isinstance(rec, dict):
                records.append(rec)
        return records
