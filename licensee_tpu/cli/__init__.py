from licensee_tpu.cli.main import main

__all__ = ["main"]
