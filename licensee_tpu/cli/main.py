"""Command-line interface: detect / diff / license-path / version /
batch-detect / serve / stats / traces / slo / fleet / corpus-build.

Parity target: `bin/licensee` + `lib/licensee/commands/*.rb` (Thor CLI).
`batch-detect` is new: the TPU batch path over a manifest of files.
`serve` is new: the persistent online micro-batching worker (JSONL over
stdio or a Unix socket, serve/).
`stats` scrapes one worker (JSON/Prometheus/traces) or a whole fleet
(merged table with --watch, merged exposition).
`traces` renders ASSEMBLED cross-process trace trees from a fleet
front socket (router + worker tails joined by trace ID with critical-
path self-times, obs/collect.py).
`slo` evaluates the multi-window SLO burn verdict from a stats scrape
(obs/slo.py; exit 1 when burning).
`fleet` supervises N serve workers behind one health-checked, load-
balanced, hedging front socket (fleet/).
`corpus-build` compiles any corpus source into a versioned, content-
fingerprinted artifact (corpus/artifact.py) that serve workers load
without recompiling and hot-swap via the `{"op": "reload"}` verb.
"""

from __future__ import annotations

import argparse
import io
import json
import os
import subprocess
import sys
import tempfile

import licensee_tpu
from licensee_tpu.normalize.pipeline import format_percent


def _print_table(rows, indent: int = 0) -> None:
    if not rows:
        return
    width = max(len(str(row[0])) for row in rows)
    for label, value in rows:
        print(" " * indent + f"{str(label):<{width}}  {value}")


def _resolve_path(args) -> str:
    import re

    path = args.path or os.getcwd()
    if args.remote and not re.match(r"^https://", path):
        return f"https://github.com/{path}"
    return path


def _build_project(args, path: str):
    return licensee_tpu.project(
        path,
        detect_packages=args.packages,
        detect_readme=args.readme,
        ref=getattr(args, "ref", None),
    )


def _licenses_by_similarity(matched_file):
    """Rank all candidates by similarity for the closest-licenses display.

    commands/detect.rb:97-102 tries to inject a hidden-inclusive pool, but
    sets @potential_licenses while the memoized reader checks
    @potential_matches — a no-op, so the effective pool is Dice's default
    (hidden included, pseudo excluded).  We reproduce the effective
    behavior."""
    from licensee_tpu.matchers import Dice

    return Dice(matched_file).matches_by_similarity


def cmd_detect(args) -> int:
    from licensee_tpu.project_files.license_file import LicenseFile

    licensee_tpu.set_confidence_threshold(args.confidence)
    path = _resolve_path(args)
    project = _build_project(args, path)

    if args.json:
        print(json.dumps(project.to_h()))
        return 0 if project.licenses else 1

    rows = []
    if project.license:
        rows.append(["License:", project.license.spdx_id])
    elif project.licenses:
        rows.append(["Licenses:", [lic.spdx_id for lic in project.licenses]])
    else:
        rows.append(["License:", "None"])
    if project.matched_files:
        rows.append(
            ["Matched files:", ", ".join(f.filename for f in project.matched_files)]
        )
    _print_table(rows)

    for matched_file in project.matched_files:
        print(f"{matched_file.filename}:")
        rows = []
        if matched_file.content_hash:
            rows.append(["Content hash:", matched_file.content_hash])
        if matched_file.attribution:
            rows.append(["Attribution:", matched_file.attribution])
        if matched_file.confidence is not None:
            rows.append(["Confidence:", format_percent(matched_file.confidence)])
        if matched_file.matcher:
            matcher_cls = type(matched_file.matcher)
            rows.append(
                ["Matcher:", f"{matcher_cls.__module__}.{matcher_cls.__name__}"]
            )
        if matched_file.license:
            rows.append(["License:", matched_file.license.spdx_id])
        _print_table(rows, indent=2)

        if not isinstance(matched_file, LicenseFile):
            continue
        if matched_file.confidence == 100:
            continue
        ranked = _licenses_by_similarity(matched_file)
        if not ranked:
            continue
        print("  Closest non-matching licenses:")
        _print_table(
            [
                [f"{lic.meta['spdx-id']} similarity:", format_percent(sim)]
                for lic, sim in ranked[:3]
            ],
            indent=4,
        )

    if project.license_file and (args.license or args.diff):
        license_key = args.license
        if not license_key:
            ranked = _licenses_by_similarity(project.license_file)
            license_key = ranked[0][0].key if ranked else None
        if license_key:
            return _diff(license_key, project.license_file)

    return 0 if project.licenses else 1


def _diff(license_key: str, license_to_diff) -> int:
    from licensee_tpu.corpus.license import License

    expected = License.find(license_key)
    if expected is None:
        print(f"{license_key} is not a valid license", file=sys.stderr)
        keys = ", ".join(lic.key for lic in License.all(hidden=True))
        print(f"Valid licenses: {keys}", file=sys.stderr)
        return 1

    print(f"Comparing to {expected.name}:")
    left = expected.content_normalized(wrap_at=80)
    right = license_to_diff.content_normalized(wrap_at=80)
    similarity = expected.similarity(license_to_diff)
    _print_table(
        [
            ["Input Length:", license_to_diff.length],
            ["License length:", expected.length],
            ["Similarity:", format_percent(similarity)],
        ]
    )
    if left == right:
        print("Exact match!")
        return 0

    # word-diff of normalized+wrapped text (commands/diff.rb:27-37 shells to
    # git in a tmpdir; we do the same — git is this framework's diff engine)
    with tempfile.TemporaryDirectory() as tmpdir:
        path = os.path.join(tmpdir, "LICENSE")
        env = {**os.environ, "GIT_CONFIG_GLOBAL": "/dev/null", "GIT_CONFIG_SYSTEM": "/dev/null"}
        subprocess.run(["git", "init", "-q"], cwd=tmpdir, check=True, env=env)
        with open(path, "w", encoding="utf-8") as f:
            f.write(left or "")
        subprocess.run(["git", "add", "LICENSE"], cwd=tmpdir, check=True, env=env)
        subprocess.run(
            ["git", "-c", "user.email=licensee@tpu.invalid", "-c", "user.name=licensee-tpu",
             "commit", "-q", "-m", "left"],
            cwd=tmpdir,
            check=True,
            env=env,
        )
        with open(path, "w", encoding="utf-8") as f:
            f.write(right or "")
        result = subprocess.run(
            ["git", "diff", "--word-diff"],
            cwd=tmpdir,
            capture_output=True,
            text=True,
            env=env,
        )
        print(result.stdout)
    return 0


def cmd_diff(args) -> int:
    from licensee_tpu.project_files.license_file import LicenseFile

    if not args.license and not args.socket:
        print(
            "Usage: provide a license to diff against with --license (spdx name)",
            file=sys.stderr,
        )
        return 1

    path = _resolve_path(args)
    file = None
    # diff.rb:43-47: prefer the project's license file on a tty, else STDIN
    if not sys.stdin.isatty():
        try:
            content = sys.stdin.read()
        except OSError:
            content = ""
        if content:
            file = LicenseFile(content, "LICENSE")
    if file is None:
        project = _build_project(args, path)
        file = project.license_file
        if file is None:
            print("No license file found", file=sys.stderr)
            return 1
    if args.socket:
        return _diff_via_worker(args, file)
    return _diff(args.license, file)


def _diff_via_worker(args, file) -> int:
    """The wire form of the diff command: one ``{"op": "diff"}`` round
    trip to a serving worker, which normalizes the blob through the
    featurizer's own pipeline and word-diffs it against the closest
    (or ``--license``-named) template — no local corpus build, no git
    subprocess, so it works against any live worker socket."""
    request = {"op": "diff", "content": file.content or ""}
    if file.filename:
        request["filename"] = file.filename
    if args.license:
        request["license"] = args.license
    try:
        row = _scrape_row(args.socket, request, args.timeout)
    except OSError as exc:
        print(f"error: cannot reach worker: {exc}", file=sys.stderr)
        return 1
    if row.get("error"):
        print(f"error: {row['error']}", file=sys.stderr)
        return 1
    diff = row.get("diff") or {}
    if diff.get("key") is None:
        print("No comparable license template", file=sys.stderr)
        return 1
    print(f"Comparing to {diff.get('spdx_id') or diff.get('key')}:")
    _print_table(
        [
            ["Input Length:", diff.get("input_length")],
            ["License length:", diff.get("license_length")],
            ["Similarity:", format_percent(diff.get("similarity") or 0.0)],
        ]
    )
    if diff.get("identical"):
        print("Exact match!")
        return 0
    print(diff.get("diff") or "")
    return 0


def cmd_license_path(args) -> int:
    path = _resolve_path(args)
    project = licensee_tpu.project(path)
    if not project.license_file:
        return 1
    if path.startswith("https://"):
        print(project.license_file.path)
    else:
        print(os.path.abspath(os.path.join(path, project.license_file.path)))
    return 0


def cmd_version(_args) -> int:
    print(licensee_tpu.__version__)
    return 0


def cmd_help(args) -> int:
    """Thor-style command listing (parity: `licensee help`, bin_spec.rb:21
    expects a "commands:" header naming every subcommand)."""
    if args.topic:
        # `help detect` -> that subcommand's own --help text (argparse
        # raises SystemExit(0) after printing; keep main() returnable)
        try:
            args.parser.parse_args([args.topic, "--help"])
        except SystemExit as exc:
            return int(exc.code or 0)
        return 0
    print("Licensee commands:")
    for choice, help_text in COMMANDS:
        print(f"  licensee-tpu {choice:<24} # {help_text}")
    return 0


def _atomic_write(path: str, text: str) -> None:
    """Write-then-replace so a supervisor polling ``path`` never reads
    a torn file (the --stats-file/--prom-file contract)."""
    tmp = f"{path}.tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(text)
    os.replace(tmp, path)


class _PromHeartbeat:
    """Periodic atomic ``--prom-file`` dumps while a batch run is in
    flight, each stamped with a monotonically increasing
    ``stripe_scrape_epoch`` gauge.  The elastic stripe supervisor
    scrapes these for the live ``pipeline_*_busy`` lane gauges; the
    epoch is its freshness proof — a just-killed stripe's last dump
    stops advancing and reads as stale, never as a live lane snapshot.
    The final end-of-run dump (_dump_run_artifacts) then overwrites
    the heartbeat with the complete exposition the merge consumes."""

    def __init__(self, path: str, interval_s: float = 1.0):
        import threading

        from licensee_tpu.obs import get_registry, render_prometheus

        self.path = path
        self.interval_s = float(interval_s)
        self._epoch = 0
        self._render = render_prometheus
        self._registry = get_registry()
        self._registry.gauge(
            "stripe_scrape_epoch",
            "Monotonic heartbeat counter stamped into every periodic "
            "--prom-file dump; an autoscaler accepts the exposition "
            "only while this advances",
        ).set_fn(lambda: self._epoch)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="prom-heartbeat", daemon=True
        )

    def _beat(self) -> None:
        self._epoch += 1
        try:
            _atomic_write(self.path, self._render(self._registry))
        except OSError:
            pass  # a torn disk must not kill the run; the merge retries

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self._beat()

    def start(self) -> "_PromHeartbeat":
        self._beat()  # first exposition lands before the first batch
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5.0)


def _check_output_dir(output: str) -> str | None:
    """Preflight the one --output misconfiguration we can name
    precisely; returns the error message, or None when fine.  Shared by
    the single-process and striped paths so the two cannot drift."""
    out_dir = os.path.dirname(os.path.abspath(output))
    if os.path.isdir(out_dir):
        return None
    problem = (
        "is not a directory" if os.path.exists(out_dir) else "does not exist"
    )
    return f"output directory {problem}: {out_dir}"


def _run_striped(args) -> int:
    """`batch-detect --stripes N|auto`: the one-command co-located
    scale-out (parallel/stripes.py).  This process never initializes a
    backend — it only supervises N child batch-detect workers (each a
    manifest stripe writing its own resume-safe shard) and merges their
    shards/stats/expositions when they all finish."""
    from licensee_tpu.parallel.stripes import (
        StripeError,
        StripeRunner,
        parse_stripes_arg,
    )

    if os.environ.get("LICENSEE_TPU_COORDINATOR") or os.environ.get(
        "LICENSEE_TPU_DISTRIBUTED"
    ):
        print(
            "error: --stripes is the single-host co-located launcher; "
            "it cannot run under the multi-host env contract "
            "(LICENSEE_TPU_COORDINATOR / LICENSEE_TPU_DISTRIBUTED) — "
            "launch one striped runner per host instead",
            file=sys.stderr,
        )
        return 1
    if args.stripe_index is not None or args.stripe_count is not None:
        print(
            "error: --stripes cannot be combined with the internal "
            "--stripe-index/--stripe-count worker flags",
            file=sys.stderr,
        )
        return 1
    if not args.output:
        print(
            "error: --stripes needs --output (per-stripe JSONL shards "
            "merge there)",
            file=sys.stderr,
        )
        return 1
    if args.profile:
        print(
            "error: --profile traces one process; run the worker "
            "directly (--stripe-index/--stripe-count) to profile a "
            "single stripe",
            file=sys.stderr,
        )
        return 1
    try:
        n_stripes = parse_stripes_arg(args.stripes)
    except ValueError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 1
    # --stripes elastic: start at the lower bound and let the runner's
    # autoscaler walk the stripe count against the measured per-stripe
    # featurize-lane occupancy (scraped from each worker's --prom-file
    # heartbeat); every scale event is a drain + resume-safe respawn
    elastic = None
    if n_stripes == "elastic":
        from licensee_tpu.parallel.autoscale import AutoscaleConfig

        try:
            elastic = AutoscaleConfig(
                min_units=args.autoscale_min,
                max_units=args.autoscale_max,
                cooldown_s=args.autoscale_cooldown,
            )
        except ValueError as exc:
            print(f"error: {exc}", file=sys.stderr)
            return 1
        n_stripes = elastic.min_units
    # preflight the cheap misconfigurations here instead of paying one
    # restart-backoff cycle per stripe for them
    dir_err = _check_output_dir(args.output)
    if dir_err:
        print(f"error: {dir_err}", file=sys.stderr)
        return 1
    if args.corpus not in ("vendored", "spdx") and not os.path.isdir(
        args.corpus
    ) and not os.path.isfile(args.corpus):
        print(
            f"error: cannot load corpus {args.corpus!r}: not a "
            "directory or artifact file",
            file=sys.stderr,
        )
        return 1
    # resume-config preflight over the merged output's sidecar: the
    # single-process path refuses a resume whose row-shaping config
    # changed (ResumeConfigError), and each stripe worker enforces the
    # same over its own shard — but a COMPLETE merged output would
    # otherwise short-circuit before any worker runs, silently handing
    # back rows of the old shape.  Run the REAL check (corpus
    # fingerprint included): building the probe project compiles the
    # corpus once in this process (~seconds), paid only when a resume
    # target exists — and a mismatch fails here instead of through one
    # restart-backoff cycle per stripe.
    probe_layout = None
    if not args.no_resume and os.path.exists(args.output) and (
        os.path.exists(f"{args.output}.meta.json")
    ):
        kwargs, err = _load_corpus(args.corpus)
        if err:
            print(f"error: {err}", file=sys.stderr)
            return 1
        from licensee_tpu.kernels.batch import BatchClassifier
        from licensee_tpu.projects.batch_project import (
            BatchProject,
            ResumeConfigError,
        )

        # container manifests: the sidecar's expansion fingerprint is
        # part of the compared config, so the probe must expand the
        # SAME manifest (metadata-only pass; handles closed below) —
        # a rewritten archive then refuses here, before any spawn
        from licensee_tpu.ingest.sources import is_container_entry

        probe_paths: list[str] = []
        with open(args.manifest, encoding="utf-8") as f:
            if any(is_container_entry(line.strip()) for line in f):
                with open(args.manifest, encoding="utf-8") as f2:
                    probe_paths = [
                        line.strip() for line in f2 if line.strip()
                    ]
        probe = None
        try:
            # device=False: the probe needs only the compiled corpus
            # fingerprint — the supervisor process must never claim a
            # chip (libtpu visibility is exclusive; the stripes own it)
            classifier = BatchClassifier(
                corpus=kwargs.get("corpus"),
                method=args.method,
                pad_batch_to=args.batch_size,
                mesh=None,
                mode=args.mode,
                closest=args.closest,
                device=False,
            )
            probe = BatchProject(
                probe_paths,
                classifier=classifier,
                batch_size=args.batch_size,
                threshold=args.confidence,
                attribution=args.attribution,
                process_index=0,
                process_count=1,
                tracer=False,
                corpus_source=args.corpus,
            )
            probe._check_resume_config(args.output, resume=True)
            if probe.ingest is not None:
                # hand the probe's (unrestricted) expansion layout to
                # the runner so it never re-scans the same archives
                probe_layout = probe.ingest.layout()
        except ResumeConfigError as exc:
            print(f"error: {exc}", file=sys.stderr)
            return 1
        except ValueError as exc:
            print(f"error: {exc}", file=sys.stderr)
            return 1
        finally:
            if probe is not None:
                probe.close()

    # everything row-shaping or perf-relevant forwards verbatim to the
    # workers; --workers splits the host's cores across stripes unless
    # the operator pinned a per-stripe count
    forward: list[str] = ["--batch-size", str(args.batch_size)]
    workers = args.workers or max(
        1, (os.cpu_count() or 1) // n_stripes
    )
    forward += ["--workers", str(workers)]
    for flag, value, default in (
        ("--corpus", args.corpus, "vendored"),
        ("--method", args.method, "auto"),
        ("--mode", args.mode, "license"),
        ("--mesh", args.mesh, None),
        ("--confidence", args.confidence, None),
        ("--coalesce-batches", args.coalesce_batches, 32),
        ("--pipeline-depth", args.pipeline_depth, 2),
    ):
        if value is not None and value != default:
            forward += [flag, str(value)]
    if args.device_lanes is not None:
        forward += ["--device-lanes", str(args.device_lanes)]
    elif (
        args.chips_per_stripe is not None
        and args.chips_per_stripe > 1
        and args.mesh in (None, "auto", "none")
    ):
        # a --chips-per-stripe K worker sees exactly K chips (the
        # visible-chips env contract); round-robin them by default so
        # the K device lanes sit behind that stripe's one featurize
        # lane — the in-stripe scale-out the flag exists for.  An
        # explicit numeric --mesh means the operator chose per-dispatch
        # sharding instead, and lanes are mutually exclusive with it
        forward += ["--device-lanes", "auto"]
    if args.closest:
        forward += ["--closest", str(args.closest)]
    if args.attribution:
        forward += ["--attribution"]
    if args.no_dedupe:
        forward += ["--no-dedupe"]
    if args.featurize_procs:
        forward += ["--featurize-procs", str(args.featurize_procs)]

    def event(message: str) -> None:
        print(f"stripes: {message}", file=sys.stderr, flush=True)

    try:
        runner = StripeRunner(
            args.manifest,
            args.output,
            n_stripes,
            forward_args=tuple(forward),
            resume=not args.no_resume,
            auto_clamp=args.stripes == "auto",
            chips_per_stripe=args.chips_per_stripe,
            progress_every=args.progress,
            on_event=event,
            container_layout=probe_layout,
            elastic=elastic,
        )
    except (OSError, ValueError) as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 1

    import signal as signallib

    previous = {}

    def _stop(signum, _frame):
        event(f"signal {signum}: draining stripes (resume-safe)")
        runner.request_stop()

    for sig in (signallib.SIGTERM, signallib.SIGINT):
        try:
            previous[sig] = signallib.signal(sig, _stop)
        except ValueError:
            pass  # not the main thread (tests drive this in-process)
    try:
        summary = runner.run()
    except StripeError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 1
    finally:
        for sig, handler in previous.items():
            try:
                signallib.signal(sig, handler)
            except ValueError:
                pass
    # --stats-file / --prom-file apply at the MERGED level here (each
    # worker's per-shard dumps are the runner's internal merge inputs):
    # the operator-requested paths must exist when the flags were given
    if args.stats_file:
        if summary.get("stats") is not None:
            _atomic_write(
                args.stats_file, json.dumps(summary["stats"]) + "\n"
            )
        else:
            event(
                f"warning: no merged stats available; {args.stats_file} "
                "not written"
            )
    if args.prom_file:
        if summary.get("prom"):
            if os.path.abspath(args.prom_file) != os.path.abspath(
                summary["prom"]
            ):
                import shutil

                tmp = f"{args.prom_file}.tmp"
                shutil.copyfile(summary["prom"], tmp)
                os.replace(tmp, args.prom_file)
        else:
            event(
                f"warning: no merged exposition available; "
                f"{args.prom_file} not written"
            )
    if args.stats and summary.get("stats") is not None:
        print(json.dumps(summary["stats"]), file=sys.stderr)
    if summary.get("autoscale"):
        auto = summary["autoscale"]
        event(
            f"autoscale: {auto['initial_stripes']} -> "
            f"{auto['final_stripes']} stripes over "
            f"{auto['scale_events']} rescale(s)"
        )
    event(
        f"done: {summary['rows_written']} rows in "
        f"{summary.get('elapsed_s', 0.0)}s"
        + (
            f" ({summary['files_per_sec']} files/sec)"
            if summary.get("files_per_sec")
            else ""
        )
    )
    return 0


def _dump_run_artifacts(args, stats) -> None:
    """--stats-file / --prom-file: machine-readable per-run dumps (the
    stripe runner's merge inputs, also useful standalone).  Atomic
    replace so a supervisor never reads a torn file."""
    if args.stats_file:
        _atomic_write(
            args.stats_file, json.dumps(stats.as_dict()) + "\n"
        )
    if args.prom_file:
        from licensee_tpu.obs import (
            NativeProfileSource,
            get_registry,
            render_prometheus,
        )

        registry = get_registry()
        # fold the native featurizer's profile counters in, and publish
        # the run's stage seconds / result counters so a striped fleet's
        # merged exposition carries the per-stripe pipeline split
        NativeProfileSource(registry)
        stage_g = registry.gauge(
            "batch_stage_seconds",
            "Per-stage seconds of the last batch run (thread-seconds "
            "for read/featurize, wall for elapsed)",
            labels=("stage",),
        )
        for stage, seconds in stats.stage_seconds.items():
            stage_g.labels(stage=stage).set(seconds)
        rows_g = registry.gauge(
            "batch_rows",
            "Result counters of the last batch run",
            labels=("kind",),
        )
        for kind in (
            "total", "dice_matched", "reference_matched",
            "package_matched", "prefiltered_copyright",
            "prefiltered_exact", "unmatched", "read_errors",
            "featurize_errors", "dedupe_hits", "skipped_oversized",
        ):
            rows_g.labels(kind=kind).set(getattr(stats, kind))
        _atomic_write(args.prom_file, render_prometheus(registry))


def cmd_batch_detect(args) -> int:
    """Batch classification of a manifest of files via the TPU Dice kernel.

    Without --output, rows print to stdout (small manifests).  With
    --output, the full pipelined BatchProject runs: featurization worker
    threads, double-buffered device dispatch, resume-on-restart, and
    per-stage timers (--stats).  --stripes N|auto scales out across N
    co-located worker processes (parallel/stripes.py)."""
    if args.selftest:
        from licensee_tpu.parallel.stripes import selftest

        return selftest()
    if args.selftest_autoscale:
        from licensee_tpu.parallel.stripes import selftest_autoscale

        return selftest_autoscale()
    if args.selftest_remote:
        from licensee_tpu.parallel.stripes import selftest_remote

        return selftest_remote()
    if not args.manifest:
        print(
            "error: need a manifest (one path per line), or --selftest",
            file=sys.stderr,
        )
        return 1
    if not os.path.exists(args.manifest):
        print(
            f"error: cannot read manifest: {args.manifest!r} not found",
            file=sys.stderr,
        )
        return 1
    if args.stripes is not None:
        # container manifests stripe too: spans are denominated in
        # EXPANDED blob counts (ingest/sources.py expanded_layout), so
        # the runner and the workers agree on span arithmetic and a
        # single million-member tarball splits across stripes
        return _run_striped(args)
    kwargs, err = _load_corpus(args.corpus)
    if err:
        print(f"error: {err}", file=sys.stderr)
        return 1

    mesh = "auto"
    if args.mesh:
        if args.mesh == "none":
            mesh = None
        else:
            try:
                parts = [int(p) for p in args.mesh.split(",")]
                mesh = (parts[0], parts[1] if len(parts) > 1 else 1)
            except ValueError:
                print(f"error: bad --mesh {args.mesh!r} (want DATA[,MODEL])",
                      file=sys.stderr)
                return 1

    # multi-host opt-in via env (LICENSEE_TPU_COORDINATOR / _NUM_PROCESSES /
    # _PROCESS_ID): this process classifies its manifest stripe and writes
    # its own output shard
    from licensee_tpu.parallel.distributed import maybe_initialize

    process_index, process_count = maybe_initialize()
    if process_count > 1 and not args.output:
        print(
            "error: multi-host runs need --output (per-host JSONL shards)",
            file=sys.stderr,
        )
        return 1
    if process_count > 1:
        from licensee_tpu.ingest.sources import is_container_entry

        with open(args.manifest, encoding="utf-8") as f:
            if any(is_container_entry(line.strip()) for line in f):
                # containers stripe by expanded count across hosts, but
                # with no merge step there is no merged output to derive
                # the container sidecar from — say so instead of letting
                # the missing artifact pass silently
                print(
                    "warning: container entries in a multi-host run "
                    "write per-host blob shards only; the "
                    ".containers.jsonl sidecar is derived from a MERGED "
                    "output (the single-host --stripes runner does this "
                    "automatically)",
                    file=sys.stderr,
                )
    # the stripe-worker rank (internal: the --stripes runner spawns
    # workers with these): same striping math as the multi-host path,
    # minus the jax.distributed bootstrap — co-located stripes share no
    # collectives, so no coordinator is needed
    if (args.stripe_index is None) != (args.stripe_count is None):
        print(
            "error: --stripe-index and --stripe-count must be given "
            "together",
            file=sys.stderr,
        )
        return 1
    if args.stripe_index is not None:
        if process_count > 1:
            print(
                "error: stripe-worker flags cannot be combined with the "
                "multi-host env contract",
                file=sys.stderr,
            )
            return 1
        if not args.output:
            print(
                "error: stripe workers need --output (the shard path "
                "derives from it)",
                file=sys.stderr,
            )
            return 1
        if not 0 <= args.stripe_index < args.stripe_count:
            print(
                f"error: --stripe-index {args.stripe_index} out of range "
                f"for --stripe-count {args.stripe_count}",
                file=sys.stderr,
            )
            return 1
        kwargs["process_index"] = args.stripe_index
        kwargs["process_count"] = args.stripe_count

    from licensee_tpu.projects.batch_project import BatchProject

    try:
        # from_manifest_file materializes only this host's stripe of the
        # manifest — at 50M lines that is the difference between ~1/N
        # and the whole path list in RAM per host
        project = BatchProject.from_manifest_file(
            args.manifest,
            method=args.method,
            batch_size=args.batch_size,
            workers=args.workers,
            mesh=mesh,
            mode=args.mode,
            dedupe=not args.no_dedupe,
            threshold=args.confidence,
            closest=args.closest,
            attribution=args.attribution,
            featurize_procs=args.featurize_procs,
            progress_every=args.progress,
            coalesce_batches=args.coalesce_batches,
            corpus_source=args.corpus,
            pipeline_depth=args.pipeline_depth,
            device_lanes=args.device_lanes,
            **kwargs,
        )
    except OSError as exc:
        print(f"error: cannot read manifest: {exc}", file=sys.stderr)
        return 1
    except ValueError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 1
    paths = project.paths

    profiler = None
    if args.profile:
        import jax

        jax.profiler.start_trace(args.profile)
        profiler = args.profile
    # live --prom-file heartbeat (epoch-stamped): what the elastic
    # stripe supervisor scrapes mid-run for the lane gauges; the final
    # _dump_run_artifacts exposition overwrites it at exit
    heartbeat = None
    if args.prom_file and args.output:
        heartbeat = _PromHeartbeat(args.prom_file).start()
    try:
        if args.output:
            # preflight the one misconfiguration we can name precisely;
            # everything else surfaces as a neutral I/O failure (run()
            # touches much more than the output file — resume reads,
            # JAX caches — so the message must not overclaim)
            dir_err = _check_output_dir(args.output)
            if dir_err:
                print(f"error: {dir_err}", file=sys.stderr)
                return 1
            from licensee_tpu.projects.batch_project import (
                ResumeConfigError,
            )

            try:
                stats = project.run(args.output, resume=not args.no_resume)
            except OSError as exc:
                print(
                    f"error: batch run I/O failure: {exc}", file=sys.stderr
                )
                return 1
            except ResumeConfigError as exc:
                # a resume whose mode/corpus/threshold differs from the
                # run that wrote the output (the .meta.json sidecar);
                # any other ValueError keeps its traceback — it's a bug
                print(f"error: {exc}", file=sys.stderr)
                return 1
        else:
            # the shared route -> read -> classify -> attribute pass
            # (identical semantics to the pipelined run(), minus dedupe)
            from licensee_tpu.ingest import SkippedBlob

            contents, results = project.classify_paths(paths)
            rows = []
            for path, content, result in zip(paths, contents, results):
                row = {"path": path, **result.as_dict()}
                if content is None:
                    # same accounting as the --output pipeline: a read
                    # failure is not a classification.  This is a BATCH
                    # output row on stdout, not a serve wire response —
                    # the wire-protocol checker has no business here.
                    # analysis: disable=protocol-drift
                    row["error"] = "read_error"
                    project.stats.read_errors += 1
                elif isinstance(content, SkippedBlob):
                    # the 64 KiB cap: skipped, never truncated-and-
                    # scored (the marker's own code, e.g. "oversized")
                    row["error"] = content.error
                    project.stats.skipped_oversized += 1
                elif result.error:
                    row["error"] = result.error
                    project.stats.featurize_errors += 1
                else:
                    project._count(result)
                project.stats.total += 1
                rows.append(row)
                print(json.dumps(row))
            if project.ingest is not None and (
                project.ingest.spans or project.ingest.subsets
            ):
                # container-level verdict rows (the reference's
                # Project#license algebra) after the per-blob stream —
                # whole-container spans AND explicitly-listed member
                # subsets, same grouping as the sidecar writer
                from licensee_tpu.ingest.verdict import (
                    container_groups,
                    container_verdict,
                )

                for label, members in container_groups(
                    project.ingest.spans, project.ingest.subsets
                ):
                    group_rows = [
                        (
                            member
                            if member is not None
                            else rows[i]["path"],
                            rows[i],
                        )
                        for i, member in members
                    ]
                    print(
                        json.dumps(container_verdict(label, group_rows))
                    )
            stats = project.stats
    finally:
        if heartbeat is not None:
            heartbeat.stop()
        project.close()
        if profiler:
            import jax

            jax.profiler.stop_trace()
            print(f"profile trace written to {profiler}", file=sys.stderr)
    _dump_run_artifacts(args, stats)
    if args.stats:
        print(json.dumps(stats.as_dict()), file=sys.stderr)
    return 0


def _load_corpus(corpus_arg: str):
    """Resolve a --corpus value to (kwargs-with-corpus | error message).
    Shared by batch-detect and serve.  Sources: 'vendored', 'spdx', an
    SPDX license-list-XML src/ directory, or a corpus ARTIFACT file
    built by `licensee-tpu corpus-build` (loads without recompiling,
    integrity-checked against its fingerprint manifest)."""
    kwargs = {}
    if corpus_arg and corpus_arg != "vendored":
        from licensee_tpu.corpus.artifact import ArtifactError, resolve_corpus

        try:
            corpus, _fp, _manifest = resolve_corpus(corpus_arg)
        except (ArtifactError, OSError) as exc:
            return None, f"cannot load corpus {corpus_arg!r}: {exc}"
        kwargs["corpus"] = corpus
    return kwargs, None


def cmd_corpus_build(args) -> int:
    """Compile a corpus source into a versioned, content-fingerprinted
    artifact bundle (corpus/artifact.py) — the unit of corpus rollout:
    build once, ship the file, `serve --corpus art.npz` / the
    `{"op": "reload"}` verb / `fleet reload` all load it without
    recompiling, and its fingerprint names the corpus everywhere
    (response rows, caches, resume sidecars, Prometheus)."""
    from licensee_tpu.corpus.artifact import (
        ArtifactError,
        load_artifact,
        resolve_corpus,
        write_artifact,
    )

    if args.inspect:
        try:
            _corpus, manifest = load_artifact(args.inspect)
        except ArtifactError as exc:
            print(f"error: {exc}", file=sys.stderr)
            return 1
        print(json.dumps(manifest))
        return 0
    if not args.output:
        print(
            "error: need --output PATH (or --inspect ARTIFACT)",
            file=sys.stderr,
        )
        return 1
    dir_err = _check_output_dir(args.output)
    if dir_err:
        print(f"error: {dir_err}", file=sys.stderr)
        return 1
    try:
        corpus, _fp, _manifest = resolve_corpus(args.corpus)
    except (ArtifactError, OSError) as exc:
        print(f"error: cannot load corpus {args.corpus!r}: {exc}",
              file=sys.stderr)
        return 1
    manifest = write_artifact(args.output, corpus, source=args.corpus)
    print(json.dumps(manifest))
    return 0


def cmd_serve(args) -> int:
    """The online serving worker: a persistent micro-batching front end
    over the device scorer (serve/scheduler.py).  Speaks newline-
    delimited JSON on stdin/stdout, or on a Unix domain socket with
    --socket (one session per connection, one shared cache/batcher).
    The `{"op": "stats"}` verb dumps scheduler/cache/latency counters."""
    from licensee_tpu.serve.server import (
        selftest,
        selftest_reload,
        serve_stdio,
        serve_unix,
    )

    if args.selftest:
        return selftest()
    if args.selftest_reload:
        return selftest_reload()

    kwargs, err = _load_corpus(args.corpus)
    if err:
        print(f"error: {err}", file=sys.stderr)
        return 1
    mesh = None
    if args.mesh and args.mesh != "none":
        if args.mesh == "auto":
            mesh = "auto"
        else:
            try:
                parts = [int(p) for p in args.mesh.split(",")]
                mesh = (parts[0], parts[1] if len(parts) > 1 else 1)
            except ValueError:
                print(f"error: bad --mesh {args.mesh!r} (want DATA[,MODEL])",
                      file=sys.stderr)
                return 1
    buckets = None
    if args.buckets:
        try:
            buckets = tuple(int(b) for b in args.buckets.split(","))
        except ValueError:
            print(f"error: bad --buckets {args.buckets!r} (want N,N,...)",
                  file=sys.stderr)
            return 1

    from licensee_tpu.serve.scheduler import MicroBatcher

    # socket workers get a fleet identity (the basename the supervisor
    # names them by) and a flight recorder on the black-box convention
    # the supervisor harvests (obs/flight.py) — a stdio session keeps
    # the in-process defaults
    flight = None
    proc_name = "serve"
    if args.socket:
        from licensee_tpu.obs.flight import (
            FlightRecorder,
            flight_path_for_socket,
        )

        proc_name = os.path.basename(args.socket)
        if proc_name.endswith(".sock"):
            proc_name = proc_name[: -len(".sock")]
        flight = FlightRecorder(
            flight_path_for_socket(args.socket), proc=proc_name
        )
    try:
        batcher = MicroBatcher(
            method=args.method,
            mode=args.mode,
            mesh=mesh,
            max_batch=args.max_batch,
            max_delay_ms=args.max_delay_ms,
            queue_depth=args.queue_depth,
            cache_entries=args.cache_entries,
            cache_bytes=args.cache_bytes,
            deadline_ms=args.deadline_ms,
            threshold=args.confidence,
            buckets=buckets,
            pipeline_depth=args.pipeline_depth,
            # the product worker always pre-compiles its bucket shapes:
            # no live request pays a jit compile (tests/libraries opt in)
            warm_start=True,
            tracing=not args.no_tracing,
            trace_sample=args.trace_sample,
            trace_slow_ms=args.trace_slow_ms,
            trace_log=args.trace_log,
            trace_proc=proc_name,
            flight=flight,
            corpus_source=args.corpus,
            **kwargs,
        )
    except ValueError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 1
    if flight is not None:
        flight.register_metrics(batcher.obs.registry)
        flight.start()
        flight.record("boot", socket=args.socket)
    try:
        if args.socket:
            print(f"serving on {args.socket}", file=sys.stderr)
            serve_unix(batcher, args.socket)
        else:
            serve_stdio(batcher)
    except KeyboardInterrupt:
        pass
    finally:
        batcher.close()
        if flight is not None:
            # the SIGTERM/clean-shutdown black box: final dump to disk
            flight.record("shutdown")
            flight.stop()
        if args.stats:
            print(json.dumps(batcher.stats()), file=sys.stderr)
    return 0


def _scrape_row(socket_path: str, request: dict, timeout: float) -> dict:
    """One control-verb round trip to a worker socket; raises OSError
    (WireError) on any transport or parse failure.  The wire protocol
    lives in ONE place — fleet/wire.py — shared with the router and
    supervisor probes (the module is stdlib-only, no device imports)."""
    from licensee_tpu.fleet.wire import oneshot

    return oneshot(socket_path, request, timeout)


def socket_labels(sockets: list[str]) -> dict[str, str]:
    """Display label per scraped socket: the basename — unless two
    sockets share one (two fleets, each with a w0.sock), in which case
    the colliding entries keep their full paths so no worker silently
    vanishes from the merged view."""
    from collections import Counter

    counts = Counter(os.path.basename(s) for s in sockets)
    return {
        s: (
            os.path.basename(s)
            if counts[os.path.basename(s)] == 1
            else s
        )
        for s in sockets
    }


def stats_table_rows(
    snaps: dict, prev: dict | None = None, dt: float | None = None,
    rates: dict | None = None,
) -> list[list[str]]:
    """The merged fleet table: one row per scraped worker socket.
    ``snaps`` maps label -> stats dict (or None for an unreachable
    worker); ``prev``/``dt`` from the previous --watch round turn
    completed-counter deltas into a live req/s column.  ``rates``
    overrides REQ_S per label with a store-backed ``rate()`` (a target
    that serves ``{"op": "query"}`` has retained history, so the rate
    is honest from the FIRST frame); a None value there means the
    store is reachable but has no window yet — render "-", never a
    fabricated 0.0."""
    header = ["WORKER", "UP_S", "DONE", "Q", "INFL", "CACHE%", "CORPUS",
              "P50_MS", "P99_MS", "REQ_S"]
    rows = [header]
    for label, snap in snaps.items():
        if not snap:
            rows.append([label, "-", "-", "-", "-", "-", "-", "-", "-",
                         "down"])
            continue
        sched = snap.get("scheduler") or {}
        cache = snap.get("cache") or {}
        total = (snap.get("latency_ms") or {}).get("total") or {}
        hit_rate = cache.get("hit_rate")
        done = sched.get("completed")
        rate = "-"
        if rates is not None and label in rates:
            value = rates[label]
            rate = "-" if value is None else f"{value:.1f}"
        elif prev and dt and label in prev and prev[label]:
            before = (prev[label].get("scheduler") or {}).get("completed")
            if isinstance(done, (int, float)) and isinstance(
                before, (int, float)
            ) and dt >= 0.2 and done >= before:
                # done < before means the counter reset (the supervisor
                # restarted the worker): no honest rate this frame.
                # dt < 0.2s means two frames landed near-instantly
                # (--watch 0 drills): a delta over ~no time is noise —
                # keep "-" rather than print a made-up 0.0
                rate = f"{(done - before) / dt:.1f}"

        def cell(value, fmt="{}"):
            return "-" if value is None else fmt.format(value)

        # the serving fingerprint, short form — in a multi-tenant
        # fleet this is the column that shows which corpus each pool's
        # workers are actually on (and a roll sweeping through them)
        corpus_fp = (snap.get("corpus") or {}).get("fingerprint")
        rows.append([
            label,
            cell(snap.get("uptime_s"), "{:.0f}"),
            cell(done),
            cell(sched.get("queue_depth")),
            cell(sched.get("in_flight")),
            "-" if hit_rate is None else f"{hit_rate * 100:.1f}",
            corpus_fp[:12] if isinstance(corpus_fp, str) else "-",
            cell(total.get("p50_ms")),
            cell(total.get("p99_ms")),
            rate,
        ])
    return rows


def _render_table(rows: list[list[str]], stream) -> None:
    widths = [
        max(len(str(row[i])) for row in rows)
        for i in range(len(rows[0]))
    ]
    for row in rows:
        stream.write(
            "  ".join(str(c).ljust(w) for c, w in zip(row, widths)).rstrip()
            + "\n"
        )


def _store_req_rate(
    sock: str, timeout: float, window: float
) -> tuple[bool, float | None]:
    """REQ_S from the target's telemetry store: ``rate()`` over the
    stored completion counter via ``{"op": "query"}``.  Returns
    ``(capable, rate)``: capable False means the target is a bare
    worker with no store verb — the caller falls back to the
    completed-counter delta path; rate None means the store answered
    but has no two-sample window yet (render "-", never 0.0)."""
    try:
        row = _scrape_row(
            sock,
            {
                "op": "query", "series": "fleet_requests_total",
                "fn": "rate", "window": window,
                "labels": {"event": "ok"},
            },
            timeout,
        )
    except (OSError, ValueError):
        return False, None
    if "query" in row:
        value = (row["query"] or {}).get("value")
        return True, (None if value is None else float(value))
    if str(row.get("error", "")).startswith("unknown_series"):
        # a store-capable front whose scrape rounds have not minted
        # the series yet (cold start): keep querying, show "-" so far
        return True, None
    return False, None


def _stats_watch(
    sockets: list[str], interval: float, timeout: float,
    iterations: int | None = None,
) -> int:
    """The operator view of a fleet: scrape every socket, print ONE
    merged table, redraw every ``interval`` seconds (Ctrl-C stops).
    ``iterations`` bounds the loop (None = forever) — tests use it.

    REQ_S prefers the target's retained telemetry store (the fleet
    front's ``{"op": "query"}`` verb) — honest from the first frame;
    a bare worker without the verb keeps the two-frame
    completed-counter delta."""
    import itertools
    import time as timelib

    labels = socket_labels(sockets)
    prev: dict = {}
    prev_t: float | None = None
    # None = unprobed; the probe result is remembered so a bare worker
    # is asked exactly once, not re-probed into an error every frame
    capable: dict[str, bool | None] = {s: None for s in sockets}
    window = max(10.0, 2.0 * interval)
    for i in itertools.count():
        if iterations is not None and i >= iterations:
            return 0
        snaps = {}
        rates: dict = {}
        for sock in sockets:
            try:
                row = _scrape_row(sock, {"op": "stats"}, timeout)
                snaps[labels[sock]] = row.get("stats")
            except (OSError, ValueError):
                snaps[labels[sock]] = None
                continue
            if capable[sock] is not False:
                ok, value = _store_req_rate(sock, timeout, window)
                capable[sock] = ok
                if ok:
                    rates[labels[sock]] = value
        now = timelib.perf_counter()
        dt = None if prev_t is None else now - prev_t
        table = stats_table_rows(snaps, prev, dt, rates=rates)
        if interval > 0 and sys.stdout.isatty():
            sys.stdout.write("\x1b[2J\x1b[H")  # clear + home, like watch(1)
        _render_table(table, sys.stdout)
        sys.stdout.flush()
        prev, prev_t = snaps, now
        if interval <= 0:
            return 0
        try:
            timelib.sleep(interval)
        except KeyboardInterrupt:
            return 0


def cmd_stats(args) -> int:
    """Observability exporter client: scrape running serve workers'
    metrics (JSON, Prometheus exposition, or — for several --socket
    flags or --watch — one merged fleet table) or a trace tail over the
    Unix-socket control verbs, or run the obs-layer selftest."""
    if args.selftest:
        from licensee_tpu.obs.selftest import selftest as obs_selftest

        return obs_selftest()
    sockets = args.socket or []
    if not sockets:
        print(
            "error: need --socket PATH (a running `licensee-tpu serve "
            "--socket` worker; repeat for a fleet) or --selftest",
            file=sys.stderr,
        )
        return 1
    if args.trace is not None:
        if len(sockets) > 1:
            print(
                "error: --trace reads one worker at a time (one --socket)",
                file=sys.stderr,
            )
            return 1
        try:
            row = _scrape_row(
                sockets[0], {"op": "trace", "n": args.trace}, args.timeout
            )
        except (OSError, ValueError) as exc:
            print(
                f"error: cannot scrape {sockets[0]!r}: {exc}",
                file=sys.stderr,
            )
            return 1
        if "traces" not in row:
            print(f"error: unexpected response: {row}", file=sys.stderr)
            return 1
        for trace in row["traces"]:
            print(json.dumps(trace))
        return 0
    if args.format == "prometheus":
        labels = socket_labels(sockets)
        expositions = {}
        for sock in sockets:
            try:
                row = _scrape_row(
                    sock, {"op": "stats", "format": "prometheus"},
                    args.timeout,
                )
            except (OSError, ValueError) as exc:
                print(
                    f"error: cannot scrape {sock!r}: {exc}",
                    file=sys.stderr,
                )
                return 1
            if "prometheus" not in row:
                # a version-mismatched worker answering an error row
                # must fail the scrape loudly, never record an empty
                # exposition with exit 0
                print(
                    f"error: unexpected response from {sock!r}: {row}",
                    file=sys.stderr,
                )
                return 1
            expositions[labels[sock]] = row["prometheus"]
        if len(expositions) == 1:
            sys.stdout.write(next(iter(expositions.values())))
        else:
            from licensee_tpu.obs import merge_expositions

            sys.stdout.write(merge_expositions(expositions))
        return 0
    if args.watch is not None or len(sockets) > 1:
        # the fleet operator view: merged table, optionally redrawn
        return _stats_watch(
            sockets, args.watch or 0.0, args.timeout,
            iterations=args.watch_iterations,
        )
    try:
        row = _scrape_row(sockets[0], {"op": "stats"}, args.timeout)
    except (OSError, ValueError) as exc:
        print(
            f"error: cannot scrape {sockets[0]!r}: {exc}", file=sys.stderr
        )
        return 1
    if "stats" in row:
        print(json.dumps(row["stats"]))
        return 0
    print(f"error: unexpected response: {row}", file=sys.stderr)
    return 1


def cmd_traces(args) -> int:
    """The telemetry-plane viewer: ask a fleet front socket for
    ASSEMBLED cross-process trace trees (`{"op": "traces"}` — router
    spans + every worker's serving spans joined by 16-hex trace ID,
    with critical-path self-times) and render them.  "Where did the
    p99 go" is one command: `licensee-tpu traces --socket front.sock
    --slowest 1`."""
    from licensee_tpu.obs.collect import render_tree

    payload: dict = {"op": "traces", "n": args.slowest or args.n}
    if args.id:
        payload["trace_id"] = args.id
    try:
        row = _scrape_row(args.socket, payload, args.timeout)
    except (OSError, ValueError) as exc:
        print(
            f"error: cannot scrape {args.socket!r}: {exc}",
            file=sys.stderr,
        )
        return 1
    trees = row.get("traces")
    if not isinstance(trees, list):
        print(
            f"error: unexpected response: {row} (is {args.socket!r} a "
            "fleet front socket? workers answer {'op': 'trace'} only)",
            file=sys.stderr,
        )
        return 1
    if args.slowest:
        trees = trees[: args.slowest]
    if not trees:
        print("no assembled traces retained", file=sys.stderr)
        return 1
    for i, tree in enumerate(trees):
        if args.json:
            print(json.dumps(tree))
        else:
            if i:
                print()
            print(render_tree(tree))
    return 0


def cmd_slo(args) -> int:
    """The SLO verdict: scrape a worker (or fleet front) socket's
    stats and render the multi-window burn-rate table (obs/slo.py).
    Exit 0 when every objective is inside its burn thresholds, 1 when
    any fast/slow burn alert fires."""
    try:
        row = _scrape_row(args.socket, {"op": "stats"}, args.timeout)
    except (OSError, ValueError) as exc:
        print(
            f"error: cannot scrape {args.socket!r}: {exc}",
            file=sys.stderr,
        )
        return 1
    slo = (row.get("stats") or {}).get("slo")
    if not isinstance(slo, dict):
        print(
            f"error: no slo block in stats from {args.socket!r}",
            file=sys.stderr,
        )
        return 1
    if args.json:
        print(json.dumps(slo))
        return 0 if slo.get("ok") else 1
    from licensee_tpu.obs.slo import WINDOWS

    window_names = [w for w, _secs in WINDOWS]
    rows = [["OBJECTIVE", "TARGET", *[f"BURN_{w}" for w in window_names],
             "VERDICT"]]
    for name, obj in sorted((slo.get("objectives") or {}).items()):
        windows = obj.get("windows") or {}
        verdict = "ok"
        if obj.get("fast_burn_alert"):
            verdict = "PAGE (fast burn)"
        elif obj.get("slow_burn_alert"):
            verdict = "TICKET (slow burn)"
        rows.append([
            name,
            f"{obj.get('target', 0) * 100:g}%",
            *[str(windows.get(w, "-")) for w in window_names],
            verdict,
        ])
    _render_table(rows, sys.stdout)
    print(f"slo: {'ok' if slo.get('ok') else 'BURNING'}")
    return 0 if slo.get("ok") else 1


def cmd_alerts(args) -> int:
    """The anomaly watchdog's ledger: ask a fleet front socket for
    ``{"op": "alerts"}`` (the watchdog snapshot — active alerts,
    fire/clear history, declared rules) and render it.  Exit 0 when
    nothing is firing, 1 when any alert is active."""
    try:
        row = _scrape_row(args.socket, {"op": "alerts"}, args.timeout)
    except (OSError, ValueError) as exc:
        print(
            f"error: cannot scrape {args.socket!r}: {exc}",
            file=sys.stderr,
        )
        return 1
    snap = row.get("alerts")
    if not isinstance(snap, dict):
        # a bare worker answers bad_request: the watchdog lives on the
        # fleet front (the router owns the telemetry store)
        print(
            f"error: no alerts verb at {args.socket!r} (need a fleet "
            f"front socket): {row.get('error', row)}",
            file=sys.stderr,
        )
        return 1
    if args.json:
        print(json.dumps(snap))
        return 0 if not snap.get("active") else 1
    active = snap.get("active") or []
    rows = [["RULE", "KIND", "SERIES", "SINCE_S", "DETAIL"]]
    for alert in active:
        rows.append([
            alert.get("rule", "-"),
            alert.get("kind", "-"),
            alert.get("series", "-"),
            f"{alert.get('since_s', 0):.0f}",
            json.dumps(alert.get("detail") or {}),
        ])
    if active:
        _render_table(rows, sys.stdout)
    else:
        print("no active alerts")
    if args.history:
        history = (snap.get("history") or [])[-args.history:]
        for event in history:
            print(
                f"  {event.get('state', '?'):7s} {event.get('rule', '?')} "
                f"({event.get('series', '?')}) "
                f"{json.dumps(event.get('detail') or {})}"
            )
    print(
        f"alerts: {len(active)} active, "
        f"{snap.get('fired_total', 0)} fired total, "
        f"{len(snap.get('rules') or [])} rules"
    )
    return 0 if not active else 1


_SPARK_CHARS = "▁▂▃▄▅▆▇█"


def _spark(values: list) -> str:
    """A fixed-height unicode sparkline; None gaps render as spaces."""
    numeric = [v for v in values if v is not None]
    if not numeric:
        return ""
    lo, hi = min(numeric), max(numeric)
    span = hi - lo
    out = []
    for v in values:
        if v is None:
            out.append(" ")
        elif span <= 0:
            out.append(_SPARK_CHARS[0])
        else:
            idx = int((v - lo) / span * (len(_SPARK_CHARS) - 1) + 0.5)
            out.append(_SPARK_CHARS[min(idx, len(_SPARK_CHARS) - 1)])
    return "".join(out)


def _counter_rate_points(points: list) -> list:
    """Adjacent-pair rates over stored counter samples ([ts, value]
    rows from a ``fn=raw`` query); a negative step (counter reset)
    yields a None gap instead of a bogus negative rate."""
    out = []
    for (t0, v0), (t1, v1) in zip(points, points[1:]):
        if t1 <= t0:
            continue
        step = v1 - v0
        out.append(step / (t1 - t0) if step >= 0 else None)
    return out


def _top_query(sock: str, params: dict, timeout: float) -> dict | None:
    """One ``{"op": "query"}`` round trip; None on any miss (the
    dashboard renders what it can and dashes the rest)."""
    try:
        row = _scrape_row(sock, {"op": "query", **params}, timeout)
    except (OSError, ValueError):
        return None
    result = row.get("query")
    return result if isinstance(result, dict) else None


def _top_frame(sock: str, timeout: float, window: float) -> list[str]:
    """Render one ``top`` frame from the front socket's stats verb,
    alerts verb, and telemetry-store queries."""
    lines: list[str] = []
    try:
        stats = _scrape_row(
            sock, {"op": "stats"}, timeout
        ).get("stats") or {}
    except (OSError, ValueError) as exc:
        return [f"licensee-tpu top — {sock}: unreachable ({exc})"]
    tsdb = stats.get("tsdb") or {}
    scrape = tsdb.get("scrape") or {}
    alerts_block = stats.get("alerts") or {}
    lines.append(
        f"licensee-tpu top — {sock}   up {stats.get('uptime_s', 0):.0f}s   "
        f"store {tsdb.get('series', 0)} series / "
        f"{tsdb.get('bytes_est', 0)} B   "
        f"scrape rounds {scrape.get('rounds', 0)} "
        f"(lag {scrape.get('last_lag_s', 0):.2f}s)   "
        f"alerts {alerts_block.get('active', 0)} active"
    )
    lines.append("")
    # -- per-worker throughput + p99 from the stored series --
    # worker schedulers count finished work as event="completed"
    # ("ok" is the fleet-level counter, which has no worker label)
    rps = _top_query(
        sock,
        {"series": "serve_requests_total", "fn": "rate",
         "window": window, "labels": {"event": "completed"},
         "by": "worker"},
        timeout,
    )
    p99 = _top_query(
        sock,
        {"series": "serve_stage_seconds", "fn": "quantile", "q": 0.99,
         "window": window, "labels": {"stage": "total"}, "by": "worker"},
        timeout,
    )
    workers = sorted(
        set((rps or {}).get("groups") or {})
        | set((p99 or {}).get("groups") or {})
    )
    rows = [["WORKER", "REQ_S", "P99_MS", f"TREND({window:.0f}s)"]]
    for name in workers:
        rate_row = ((rps or {}).get("groups") or {}).get(name) or {}
        p99_row = ((p99 or {}).get("groups") or {}).get(name) or {}
        raw = _top_query(
            sock,
            {"series": "serve_requests_total", "fn": "raw",
             "window": window, "limit": 24,
             "labels": {"event": "completed", "worker": name}},
            timeout,
        )
        trend = _spark(_counter_rate_points((raw or {}).get("points") or []))
        rate = rate_row.get("value")
        q_value = p99_row.get("value")
        rows.append([
            name or "(unlabeled)",
            "-" if rate is None else f"{rate:.1f}",
            "-" if q_value is None else f"{q_value * 1000:.1f}",
            trend or "-",
        ])
    if workers:
        out = io.StringIO()
        _render_table(rows, out)
        lines.extend(out.getvalue().splitlines())
    else:
        lines.append("(no stored per-worker series yet)")
    # -- per-pool rollup, when a multi-tenant router publishes the
    # pool-labeled series (a single-pool fleet never registers them,
    # so this section simply does not render there) --
    pool_rps = _top_query(
        sock,
        {"series": "fleet_tenant_requests_total", "fn": "rate",
         "window": window, "labels": {"event": "ok"}, "by": "pool"},
        timeout,
    )
    pool_p99 = _top_query(
        sock,
        {"series": "fleet_tenant_request_seconds", "fn": "quantile",
         "q": 0.99, "window": window, "by": "pool"},
        timeout,
    )
    pool_names = sorted(
        set((pool_rps or {}).get("groups") or {})
        | set((pool_p99 or {}).get("groups") or {})
    )
    if pool_names:
        pool_rows = [["POOL", "REQ_S", "P99_MS"]]
        for name in pool_names:
            rate_row = ((pool_rps or {}).get("groups") or {}).get(name) or {}
            p99_row = ((pool_p99 or {}).get("groups") or {}).get(name) or {}
            rate = rate_row.get("value")
            q_value = p99_row.get("value")
            pool_rows.append([
                name or "(unlabeled)",
                "-" if rate is None else f"{rate:.1f}",
                "-" if q_value is None else f"{q_value * 1000:.1f}",
            ])
        lines.append("")
        out = io.StringIO()
        _render_table(pool_rows, out)
        lines.extend(out.getvalue().splitlines())
    # -- SLO burn --
    objectives = (stats.get("slo") or {}).get("objectives") or {}
    if objectives:
        lines.append("")
        for name, obj in sorted(objectives.items()):
            verdict = "ok"
            if obj.get("fast_burn_alert"):
                verdict = "PAGE"
            elif obj.get("slow_burn_alert"):
                verdict = "TICKET"
            sources = obj.get("window_sources") or {}
            stored = sum(1 for s in sources.values() if s == "store")
            lines.append(
                f"slo {name}: max burn {obj.get('max_burn', 0):g} "
                f"[{verdict}] ({stored}/{len(sources) or 0} windows "
                f"store-backed)"
            )
    # -- autoscale state, when a fleet autoscaler publishes it --
    units = _top_query(
        sock, {"series": "autoscale_capacity_units", "fn": "latest"},
        timeout,
    )
    if units is not None and units.get("value") is not None:
        pressure = _top_query(
            sock, {"series": "autoscale_pressure", "fn": "latest"},
            timeout,
        )
        p_value = (pressure or {}).get("value")
        lines.append(
            f"autoscale: {units['value']:.0f} units, pressure "
            + ("-" if p_value is None else f"{p_value:.2f}")
        )
    # -- active alerts --
    try:
        snap = _scrape_row(
            sock, {"op": "alerts"}, timeout
        ).get("alerts") or {}
    except (OSError, ValueError):
        snap = {}
    active = snap.get("active") or []
    if active:
        lines.append("")
        for alert in active:
            lines.append(
                f"ALERT {alert.get('rule', '?')} "
                f"({alert.get('series', '?')}, "
                f"{alert.get('since_s', 0):.0f}s): "
                f"{json.dumps(alert.get('detail') or {})}"
            )
    return lines


def cmd_top(args) -> int:
    """The live fleet dashboard: per-worker req/s + p99 with stored-
    sample sparklines, SLO burn, autoscale state, and active watchdog
    alerts — all read from a fleet front socket's telemetry store
    (``{"op": "stats"}`` / ``{"op": "query"}`` / ``{"op": "alerts"}``),
    redrawn every ``--interval`` seconds."""
    import itertools
    import time as timelib

    # a bare worker serves none of the store verbs: fail loudly once
    # instead of rendering an empty dashboard forever
    try:
        probe = _scrape_row(
            args.socket, {"op": "query", "list": True}, args.timeout
        )
    except (OSError, ValueError) as exc:
        print(
            f"error: cannot scrape {args.socket!r}: {exc}",
            file=sys.stderr,
        )
        return 1
    if "query" not in probe:
        print(
            f"error: no query verb at {args.socket!r} (need a fleet "
            f"front socket): {probe.get('error', probe)}",
            file=sys.stderr,
        )
        return 1
    window = max(30.0, 4.0 * args.interval)
    for i in itertools.count():
        if args.iterations is not None and i >= args.iterations:
            return 0
        lines = _top_frame(args.socket, args.timeout, window)
        if args.interval > 0 and sys.stdout.isatty():
            sys.stdout.write("\x1b[2J\x1b[H")
        sys.stdout.write("\n".join(lines) + "\n")
        sys.stdout.flush()
        if args.interval <= 0:
            return 0
        try:
            timelib.sleep(args.interval)
        except KeyboardInterrupt:
            return 0


def _jobs_option_value(value: str):
    # the jobs spec carries typed option values; the CLI gets strings.
    # Numbers coerce, everything else rides as-is — the edge's
    # validate_spec is the authority and answers 400 with a reason
    for kind in (int, float):
        try:
            return kind(value)
        except ValueError:
            continue
    return value


def cmd_jobs(args) -> int:
    """Client for the durable jobs tier: submit a manifest (or a local
    archive) as an async striped batch-detect job over a fleet's HTTP
    edge (jobs/executor.py behind POST /jobs), then poll its lifecycle,
    fetch the merged results, or cancel it."""
    from licensee_tpu.jobs.client import JobsClient, JobsClientError

    if args.action != "submit" and not args.job_id:
        print(f"error: jobs {args.action} needs a JOB_ID", file=sys.stderr)
        return 1
    try:
        client = JobsClient(
            args.edge, token=args.token, timeout_s=args.timeout
        )
    except OSError as exc:
        print(f"error: cannot reach {args.edge!r}: {exc}", file=sys.stderr)
        return 1
    try:
        if args.action == "submit":
            spec: dict = {}
            if args.manifest:
                try:
                    with open(args.manifest, encoding="utf-8") as fh:
                        entries = [
                            line.strip() for line in fh if line.strip()
                        ]
                except OSError as exc:
                    print(
                        f"error: cannot read manifest: {exc}",
                        file=sys.stderr,
                    )
                    return 1
                spec["manifest"] = entries
            if args.archive:
                import base64

                try:
                    with open(args.archive, "rb") as fh:
                        blob = fh.read()
                except OSError as exc:
                    print(
                        f"error: cannot read archive: {exc}",
                        file=sys.stderr,
                    )
                    return 1
                spec["archive_b64"] = base64.b64encode(blob).decode("ascii")
                spec["archive_name"] = os.path.basename(args.archive)
            if not spec:
                print(
                    "error: jobs submit needs --manifest FILE and/or "
                    "--archive PATH",
                    file=sys.stderr,
                )
                return 1
            if args.stripes is not None:
                spec["stripes"] = args.stripes
            options: dict = {}
            for kv in args.option or ():
                key, sep, value = kv.partition("=")
                if not sep or not key:
                    print(
                        f"error: bad --option {kv!r} (want KEY=VALUE)",
                        file=sys.stderr,
                    )
                    return 1
                options[key] = _jobs_option_value(value)
            if options:
                spec["options"] = options
            if args.idempotency_key:
                spec["idempotency_key"] = args.idempotency_key
            code, row = client.submit(spec)
            if code not in (200, 202):
                print(
                    f"error: submit answered {code}: {row}",
                    file=sys.stderr,
                )
                return 1
            if args.wait:
                row = client.wait(
                    row["job_id"], timeout_s=args.wait_timeout
                )
            print(json.dumps(row))
            return 0 if row.get("state") != "failed" else 1
        if args.action == "status":
            code, row = client.status(args.job_id)
            if code != 200:
                print(
                    f"error: status answered {code}: {row}",
                    file=sys.stderr,
                )
                return 1
            print(json.dumps(row))
            return 0
        if args.action == "wait":
            row = client.wait(args.job_id, timeout_s=args.wait_timeout)
            print(json.dumps(row))
            return 0 if row.get("state") == "completed" else 1
        if args.action in ("results", "containers"):
            fetch = (
                client.results
                if args.action == "results"
                else client.containers
            )
            code, payload = fetch(args.job_id)
            if code != 200:
                print(
                    f"error: {args.action} answered {code}: "
                    f"{payload[:200]!r}",
                    file=sys.stderr,
                )
                return 1
            if args.output:
                with open(args.output, "wb") as fh:
                    fh.write(payload)
            else:
                sys.stdout.buffer.write(payload)
                sys.stdout.buffer.flush()
            return 0
        # cancel
        code, row = client.cancel(args.job_id)
        if code not in (200, 202):
            print(
                f"error: cancel answered {code}: {row}", file=sys.stderr
            )
            return 1
        print(json.dumps(row))
        return 0
    except JobsClientError as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 1
    except OSError as exc:
        print(f"error: edge connection failed: {exc}", file=sys.stderr)
        return 1
    finally:
        client.close()


def cmd_fleet(args) -> int:
    """The fleet tier: supervise N serve worker processes (restart on
    crash/wedge with backoff, drain on rolling restart) behind one
    health-checked, load-balanced, hedging front socket
    (fleet/supervisor.py + fleet/router.py) — optionally fronted by
    the HTTP/1.1 keep-alive edge (--http) and federated across hosts
    over TCP (--federate)."""
    if args.selftest:
        from licensee_tpu.fleet.selftest import selftest

        return selftest(stub=args.stub)
    if args.selftest_reload:
        from licensee_tpu.fleet.selftest import selftest_reload

        return selftest_reload(stub=args.stub)
    if args.selftest_tcp:
        from licensee_tpu.fleet.selftest import selftest_tcp

        return selftest_tcp(stub=args.stub)
    if args.selftest_jobs:
        from licensee_tpu.jobs.selftest import selftest_jobs

        return selftest_jobs(stub=args.stub)
    if args.selftest_tenant:
        from licensee_tpu.fleet.selftest import selftest_tenant

        return selftest_tenant(stub=args.stub)
    if args.jobs_dir and not args.http:
        print(
            "error: --jobs-dir needs --http (jobs are submitted over "
            "the HTTP edge)",
            file=sys.stderr,
        )
        return 1
    if not args.socket and not args.http:
        print("error: need --socket PATH|HOST:PORT (the client-facing "
              "front door) and/or --http HOST:PORT, or --selftest",
              file=sys.stderr)
        return 1
    hedge_ms = args.hedge_ms
    if hedge_ms not in (None, "off", "auto"):
        try:
            hedge_ms = float(hedge_ms)
            if not (hedge_ms >= 0):
                raise ValueError
        except ValueError:
            print(
                f"error: bad --hedge-ms {args.hedge_ms!r} "
                "(want a number, 'auto', or 'off')",
                file=sys.stderr,
            )
            return 1
    if args.tenants and args.federate:
        print(
            "error: --tenants supervises local worker pools and cannot "
            "combine with --federate (put the registry on each member "
            "fleet instead)",
            file=sys.stderr,
        )
        return 1
    import tempfile

    from licensee_tpu.fleet.router import FrontServer, Router
    from licensee_tpu.fleet.supervisor import Supervisor

    supervisor = None
    registry = onboarder = None
    if args.federate:
        # the cross-host FRONT tier: every backend is another fleet's
        # front door (usually host:port); no local workers to spawn
        hosts = {
            f"host{i}": target.strip()
            for i, target in enumerate(args.federate.split(","))
            if target.strip()
        }
        if not hosts:
            print("error: --federate needs at least one target",
                  file=sys.stderr)
            return 1
        router = Router(
            hosts,
            hedge_ms=None if hedge_ms == "off" else hedge_ms,
            probe_interval_s=args.probe_interval_ms / 1000.0,
            pool_per_worker=args.pool_per_worker,
            merge_label="host",
        )
        print(
            f"fleet: federating {len(hosts)} host(s): "
            f"{', '.join(hosts.values())}",
            file=sys.stderr,
        )
    else:
        socket_dir = args.socket_dir or tempfile.mkdtemp(
            prefix="licensee-fleet-"
        )
        os.makedirs(socket_dir, exist_ok=True)
        serve_args: list[str] = []
        for flag, value in (
            ("--mode", args.mode),
            ("--method", args.method),
            ("--max-batch", args.max_batch),
            ("--max-delay-ms", args.max_delay_ms),
            ("--queue-depth", args.queue_depth),
            ("--cache-entries", args.cache_entries),
            ("--cache-bytes", args.cache_bytes),
            ("--trace-sample", args.trace_sample),
        ):
            if value is not None:
                serve_args += [flag, str(value)]
        if args.tenants:
            # the multi-tenant topology: one supervisor per pool (each
            # with its own probe thread, restart backoff, and reload
            # lock), corpus per pool from the registry, the whole set
            # behind one router that routes by resolved corpus tag
            from licensee_tpu.tenancy import (
                CorpusOnboarder, RegistryError, TenantPools, TenantRegistry,
            )

            try:
                registry = TenantRegistry(args.tenants)
            except RegistryError as exc:
                print(f"error: {exc}", file=sys.stderr)
                return 1
            pool_tenants = registry.pools()
            if not pool_tenants:
                print(
                    f"error: {args.tenants!r} defines no tenants",
                    file=sys.stderr,
                )
                registry.close()
                return 1
            tenants = registry.tenants()
            pool_sups = {}
            for pool, names in pool_tenants.items():
                pool_workers = {
                    f"{pool}{i}": os.path.join(
                        socket_dir, f"{pool}{i}.sock"
                    )
                    for i in range(args.workers)
                }
                # co-tenants of one pool share its corpus by
                # definition; the first (sorted) tenant's binding is it
                corpus = tenants[names[0]].corpus
                pool_sups[pool] = Supervisor(
                    pool_workers,
                    chips_per_worker=args.chips_per_worker,
                    serve_args=tuple(serve_args + ["--corpus", corpus]),
                    backoff_base_s=args.restart_backoff_ms / 1000.0,
                    probe_interval_s=args.probe_interval_ms / 1000.0,
                )
            supervisor = pools = TenantPools(
                pool_sups, default_pool=registry.default_pool
            )
            router = Router(
                pools.workers,
                supervisor=pools,
                hedge_ms=None if hedge_ms == "off" else hedge_ms,
                probe_interval_s=args.probe_interval_ms / 1000.0,
                pool_per_worker=args.pool_per_worker,
                pools=pools.worker_pools(),
                default_pool=pools.default_pool,
            )
            onboarder = CorpusOnboarder(
                registry, pools, router,
                staging_dir=os.path.join(socket_dir, "staging"),
                reload_kwargs={
                    "timeout_s": 120.0,
                    "health_timeout_s": args.boot_timeout,
                },
            )
            onboarder.sync_routes()
            print(
                f"fleet: {len(pool_sups)} tenant pool(s) "
                f"({', '.join(sorted(pool_sups))}) x {args.workers} "
                f"worker(s) under {socket_dir}, front door "
                f"{args.socket or args.http}",
                file=sys.stderr,
            )
        else:
            workers = {
                f"w{i}": os.path.join(socket_dir, f"w{i}.sock")
                for i in range(args.workers)
            }
            if args.corpus is not None:
                serve_args += ["--corpus", str(args.corpus)]
            supervisor = Supervisor(
                workers,
                chips_per_worker=args.chips_per_worker,
                serve_args=tuple(serve_args),
                backoff_base_s=args.restart_backoff_ms / 1000.0,
                probe_interval_s=args.probe_interval_ms / 1000.0,
            )
            router = Router(
                workers,
                supervisor=supervisor,
                hedge_ms=None if hedge_ms == "off" else hedge_ms,
                probe_interval_s=args.probe_interval_ms / 1000.0,
                pool_per_worker=args.pool_per_worker,
            )
            print(
                f"fleet: {args.workers} workers under {socket_dir}, "
                f"front door {args.socket or args.http}",
                file=sys.stderr,
            )
    from licensee_tpu.serve.server import SocketInUseError

    if supervisor is not None:
        supervisor.start()
        if not supervisor.wait_healthy(args.boot_timeout):
            print(
                f"error: workers failed to boot: {supervisor.status()}",
                file=sys.stderr,
            )
            supervisor.stop()
            if registry is not None:
                registry.close()
            return 1
    router.start()
    if onboarder is not None:
        # replay rolls a crash interrupted: a journaled roll_start with
        # no terminal record re-validates and re-rolls at boot
        for row in onboarder.recover():
            print(f"fleet: recovered roll {json.dumps(row)}",
                  file=sys.stderr)
    executor = None
    if args.jobs_dir:
        # the durable jobs tier: journal-backed executor sharing the
        # router's metrics registry, its trace tail joined into the
        # collector so edge submit -> executor -> stripe spans
        # assemble under one trace ID
        from licensee_tpu.jobs.executor import JobExecutor

        executor = JobExecutor(
            args.jobs_dir,
            max_concurrent=args.jobs_concurrency,
            registry=router.obs.registry,
        )
        executor.start()
        router.collector.add_source("jobs", executor.trace_tail)
        resumed = executor.resumed_jobs
        print(
            f"fleet: jobs executor over {args.jobs_dir} "
            f"(concurrency {args.jobs_concurrency}"
            + (f", resumed {resumed} job(s)" if resumed else "")
            + ")",
            file=sys.stderr,
        )
    edge_tokens = None
    if registry is not None:
        # the registry's bearer tokens authenticate the edge, and the
        # edge's client label IS the tenant name — that identity is
        # what POST /corpus and per-tenant routing key off
        edge_tokens = dict(registry.tokens())
    if args.edge_token:
        edge_tokens = edge_tokens if edge_tokens is not None else {}
        for spec in args.edge_token:
            name, sep, tok = spec.partition("=")
            if sep and name and tok:
                edge_tokens[tok] = name
            else:
                edge_tokens[spec] = spec
    server = edge = None
    try:
        if args.socket:
            server = FrontServer(args.socket, router)
        if args.http:
            from licensee_tpu.fleet.http_edge import HttpEdgeServer

            edge = HttpEdgeServer(
                args.http, router,
                tokens=edge_tokens,
                rate_per_client=args.edge_rate,
                burst=args.edge_burst,
                jobs=executor,
                tenancy=onboarder,
            )
            print(
                f"fleet: HTTP edge on {args.http}"
                f"{' (port ' + str(edge.bound_port) + ')' if edge.bound_port else ''}",
                file=sys.stderr,
            )
    except (SocketInUseError, OSError) as exc:
        print(f"error: {exc}", file=sys.stderr)
        for srv in (server, edge):
            if srv is not None:
                srv.server_close()
        if executor is not None:
            executor.close()
        router.close()
        if supervisor is not None:
            supervisor.stop()
        if registry is not None:
            registry.close()
        return 1
    # long-lived serving process: the boot-time heap (imports, corpus,
    # supervisor state) never becomes garbage, but untuned gen2 GC
    # re-scans it forever — on the router's event loop that is a
    # ~100 ms stall per pass at saturation, pure tail latency.  Freeze
    # the boot heap out of collection; the saturation bench measures
    # the router under the same setting.
    import gc

    gc.collect()
    gc.freeze()
    import signal as signallib
    import threading

    primary = server if server is not None else edge
    secondary = edge if server is not None else None

    def _term(*_):
        for srv in (primary, secondary):
            if srv is not None:
                threading.Thread(target=srv.shutdown, daemon=True).start()

    try:
        signallib.signal(signallib.SIGTERM, _term)
    except ValueError:
        pass
    secondary_thread = None
    if secondary is not None:
        # both doors share the router's ONE event loop; each facade
        # just parks a waiter thread until shutdown
        secondary_thread = threading.Thread(
            target=secondary.serve_forever,
            kwargs={"poll_interval": 0.2}, daemon=True,
        )
        secondary_thread.start()
    try:
        primary.serve_forever(poll_interval=0.2)
    except KeyboardInterrupt:
        pass
    finally:
        if secondary is not None:
            secondary.shutdown()
            secondary.server_close()
        if secondary_thread is not None:
            secondary_thread.join(timeout=5.0)
        primary.server_close()
        if args.socket and server is not None and server.kind == "unix":
            try:
                os.unlink(args.socket)
            except OSError:
                pass
        if executor is not None:
            # running jobs re-journal as queued and resume on the next
            # --jobs-dir boot; the journal keeps the durable state
            executor.close()
        router.close()
        if supervisor is not None:
            supervisor.stop()
        if registry is not None:
            registry.close()
        if args.stats:
            print(json.dumps(router.stats()), file=sys.stderr)
    return 0


# the one command table: build_parser() wires each entry into argparse
# and cmd_help() prints it — no argparse-private introspection (the
# Thor-style listing of /root/reference/bin/licensee:10-43)
COMMANDS = (
    ("detect", "Detect the license of the given project"),
    ("diff", "Compare license text to a known license"),
    ("license-path", "Path to the project's license file"),
    ("version", "Print the version"),
    ("help", "Describe available commands"),
    ("batch-detect", "Classify a manifest of files on the TPU batch path"),
    ("serve", "Run the online micro-batching classification worker"),
    ("stats", "Scrape serve workers' metrics/traces (obs exporters)"),
    ("traces", "Render assembled cross-process trace trees (fleet)"),
    ("slo", "Evaluate SLO burn rates from a worker/fleet scrape"),
    ("top", "Live fleet dashboard from the retained telemetry store"),
    ("alerts", "Show the anomaly watchdog's active alerts and history"),
    ("fleet", "Supervise N serve workers behind one routed socket"),
    ("corpus-build", "Compile a corpus into a fingerprinted artifact"),
    ("jobs", "Submit and track durable striped jobs over the HTTP edge"),
)
_COMMAND_HELP = dict(COMMANDS)


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="licensee-tpu", description="Detect the license of a project"
    )
    parser.add_argument(
        "--remote",
        action="store_true",
        help="Assume PATH is a GitHub owner/repo path",
    )
    sub = parser.add_subparsers(dest="command")

    def add_common(p):
        p.add_argument("path", nargs="?", default=None)
        p.add_argument("--packages", action=argparse.BooleanOptionalAction, default=True)
        p.add_argument("--readme", action=argparse.BooleanOptionalAction, default=True)
        p.add_argument("--remote", action="store_true")
        p.add_argument("--ref", default=None)

    detect = sub.add_parser("detect", help=_COMMAND_HELP["detect"])
    add_common(detect)
    detect.add_argument("--json", action="store_true")
    detect.add_argument(
        "--confidence", type=float, default=licensee_tpu.CONFIDENCE_THRESHOLD
    )
    detect.add_argument("--license", default=None)
    detect.add_argument("--diff", action="store_true")
    detect.set_defaults(func=cmd_detect)

    diff = sub.add_parser("diff", help=_COMMAND_HELP["diff"])
    add_common(diff)
    diff.add_argument("--license", default=None)
    diff.add_argument(
        "--socket", default=None, metavar="PATH|HOST:PORT",
        help=(
            "Diff over the wire instead of locally: one {\"op\": "
            "\"diff\"} round trip to a live serve worker (closest "
            "template when --license is omitted)"
        ),
    )
    diff.add_argument(
        "--timeout", type=float, default=30.0,
        help="Wire diff round-trip timeout in seconds (default 30)",
    )
    diff.set_defaults(func=cmd_diff)

    lp = sub.add_parser("license-path", help=_COMMAND_HELP["license-path"])
    add_common(lp)
    lp.set_defaults(func=cmd_license_path)

    version = sub.add_parser("version", help=_COMMAND_HELP["version"])
    version.set_defaults(func=cmd_version)

    help_cmd = sub.add_parser("help", help=_COMMAND_HELP["help"])
    help_cmd.add_argument("topic", nargs="?", default=None)
    help_cmd.set_defaults(func=cmd_help, parser=parser)

    batch = sub.add_parser(
        "batch-detect", help=_COMMAND_HELP["batch-detect"]
    )
    batch.add_argument(
        "manifest", nargs="?", default=None,
        help="File with one path per line",
    )
    batch.add_argument(
        "--corpus",
        default="vendored",
        help=(
            "Template pool: 'vendored' (choosealicense, default), 'spdx' "
            "(the vendored SPDX license-list XMLs), or a path to an SPDX "
            "license-list-XML src/ directory (e.g. the full ~600-license set)"
        ),
    )
    batch.add_argument(
        "--output", default=None,
        help="Write JSONL here via the pipelined BatchProject (resumable)",
    )
    batch.add_argument(
        "--no-resume", action="store_true",
        help="Restart from scratch instead of resuming a partial --output",
    )
    batch.add_argument(
        "--method", default="auto",
        choices=["auto", "popcount", "matmul", "pallas", "pallas-mxu"],
        help=(
            "Device scoring path (default auto: popcount at vendored "
            "width, matmul at full-SPDX width — the measured winners; "
            "see the ADR in kernels/dice_pallas.py)"
        ),
    )
    batch.add_argument(
        "--mode", default="license",
        choices=["license", "readme", "package", "auto"],
        help=(
            "Which project-file chain to run per blob: 'license' "
            "(Copyright/Exact/Dice), 'readme' (extract the License "
            "section, then the license chain + Reference fallback), "
            "'package' (filename-dispatched package-manifest matchers), "
            "or 'auto' (route EACH file by its filename through the "
            "reference's score tables — LICENSE-likes to the license "
            "chain, READMEs to the readme chain, package manifests to "
            "their matchers, everything else skipped unread — for "
            "mixed manifests)"
        ),
    )
    batch.add_argument(
        "--mesh", default=None, metavar="DATA[,MODEL]",
        help=(
            "Device mesh for the scorer: DATA chips shard the blob batch, "
            "MODEL chips shard the template matrix vocab-wise (default: "
            "all visible devices data-parallel; 'none' forces one device)"
        ),
    )
    batch.add_argument(
        "--closest", type=int, default=0, metavar="K",
        help=(
            "Attach the top-K closest candidate licenses (key + "
            "confidence) to rows that reach the Dice scorer, like "
            "detect's closest-licenses view (prefiltered exact/"
            "copyright rows skip it)"
        ),
    )
    batch.add_argument(
        "--confidence", type=float, default=None, metavar="N",
        help=(
            "Minimum Dice confidence for a match (default: the global "
            f"threshold, {licensee_tpu.CONFIDENCE_THRESHOLD})"
        ),
    )
    batch.add_argument(
        "--attribution", action="store_true",
        help=(
            "Extract the copyright/attribution line per matched blob "
            "(detect's Attribution row, license_file.rb:71-77): a "
            "post-match host regex, paid only for matched rows — and "
            "with dedupe, once per unique content"
        ),
    )
    batch.add_argument(
        "--no-dedupe", action="store_true",
        help=(
            "Disable the (filename, content-hash) result cache that "
            "short-circuits repeated blobs (real license corpora are "
            "dominated by verbatim copies)"
        ),
    )
    batch.add_argument("--batch-size", type=int, default=4096)
    batch.add_argument("--workers", type=int, default=None,
                       help="Featurization worker threads (default: cpu count)")
    def bounded(kind, lo):
        # fail the typo in argparse, not after a 50M-line manifest loads
        def parse(value):
            v = kind(value)
            if not (v >= lo):  # rejects out-of-range AND NaN
                raise argparse.ArgumentTypeError(
                    f"must be >= {lo}, got {value!r}"
                )
            return v

        # argparse embeds the callable's name in "invalid ... value"
        parse.__name__ = f">={lo} {kind.__name__}"
        return parse

    def nonneg(kind):
        return bounded(kind, 0)

    batch.add_argument(
        "--featurize-procs", type=nonneg(int), default=0, metavar="N",
        help=(
            "Featurize in N worker PROCESSES instead of threads (GIL "
            "insurance for hosts where the native pipeline is absent and "
            "thread scaling disappoints; bit-identical output, resume "
            "unchanged).  Threads win when the native pipeline is up"
        ),
    )
    batch.add_argument(
        "--coalesce-batches", type=bounded(int, 1), default=32, metavar="N",
        help=(
            "How many produced batches may wait while their sparse "
            "device rows (dedupe-heavy manifests) accumulate into full "
            "device chunks — amortizes the per-dispatch round trip; 1 "
            "disables coalescing (default 32)"
        ),
    )
    batch.add_argument(
        "--pipeline-depth", type=bounded(int, 1), default=2, metavar="N",
        help=(
            "How many dispatched device groups may be in flight at "
            "once: 1 = the synchronous dispatch/await/write loop, >= 2 "
            "= the overlap pipeline (featurize chunk N+1 while the "
            "device scores N and the writer drains N-1; output "
            "bit-identical at every depth; default 2)"
        ),
    )

    def lanes_arg(value):
        if value == "auto":
            return value
        return bounded(int, 1)(value)

    lanes_arg.__name__ = "K|auto"
    batch.add_argument(
        "--device-lanes", type=lanes_arg, default=None, metavar="K|auto",
        help=(
            "In-stripe multi-chip scoring: round-robin whole dispatch "
            "chunks across the first K visible chips ('auto' = all), "
            "so one featurize lane feeds K independent device lanes. "
            "Mutually exclusive with an explicit --mesh (which shards "
            "ONE chunk across chips and synchronizes per dispatch)"
        ),
    )
    batch.add_argument(
        "--stripes", default=None, metavar="N|auto|elastic",
        help=(
            "Scale out across N co-located worker processes, each "
            "classifying a contiguous manifest stripe into its own "
            "resume-safe shard under a supervisor (crash restart with "
            "backoff resumes the dead stripe; SIGTERM drains), then "
            "merge shards/stats/metrics deterministically — the merged "
            "output is bit-identical to a 1-process run.  'auto' sizes "
            "from the host core count and the bench scaling model "
            "(BENCH_DETAILS.json).  'elastic' starts at --autoscale-min "
            "and grows/shrinks the stripe count live against each "
            "worker's measured featurize-lane occupancy (scraped from "
            "its --prom-file heartbeat); every scale event is a drain + "
            "resume-safe respawn.  Needs --output"
        ),
    )
    batch.add_argument(
        "--autoscale-min", type=bounded(int, 1), default=1, metavar="N",
        help="With --stripes elastic: lower capacity bound (default 1)",
    )
    batch.add_argument(
        "--autoscale-max", type=bounded(int, 1), default=8, metavar="N",
        help=(
            "With --stripes elastic: upper capacity bound (default 8); "
            "units beyond the host's useful stripe count become "
            "per-stripe --featurize-procs"
        ),
    )
    batch.add_argument(
        "--autoscale-cooldown", type=nonneg(float), default=30.0,
        metavar="SECS",
        help=(
            "With --stripes elastic: minimum seconds between scale "
            "events (default 30) — the new capacity needs time to show "
            "up in the signal it is judged by"
        ),
    )
    batch.add_argument(
        "--chips-per-stripe", type=bounded(int, 1), default=None,
        metavar="K",
        help=(
            "With --stripes: give stripe i chips [i*K, (i+1)*K) via the "
            "LICENSEE_TPU_VISIBLE_CHIPS env contract "
            "(parallel/distributed.py chips_for_worker + "
            "apply_visible_chips over each CHILD's env dict); default: "
            "stripes share default device visibility"
        ),
    )
    # internal: the rank flags the stripe runner spawns workers with
    batch.add_argument(
        "--stripe-index", type=nonneg(int), default=None,
        help=argparse.SUPPRESS,
    )
    batch.add_argument(
        "--stripe-count", type=bounded(int, 1), default=None,
        help=argparse.SUPPRESS,
    )
    batch.add_argument(
        "--stats-file", default=None, metavar="PATH",
        help=(
            "Write the run's stats JSON to PATH (atomic replace) — the "
            "machine-readable twin of --stats; the stripe runner merges "
            "these per shard"
        ),
    )
    batch.add_argument(
        "--prom-file", default=None, metavar="PATH",
        help=(
            "Write a Prometheus text exposition of the run (pipeline "
            "stage seconds, result counters, native featurize profile) "
            "to PATH; the stripe runner merges these stripe-labeled"
        ),
    )
    batch.add_argument(
        "--selftest", action="store_true",
        help=(
            "Run the 2-stripe CPU smoke (real worker subprocesses over "
            "a synthetic corpus; merged output must be bit-identical "
            "to a 1-stripe run) and exit 0/1 — the CI smoke"
        ),
    )
    batch.add_argument(
        "--selftest-autoscale", action="store_true",
        help=(
            "Run the elastic-stripes drill (stub workers under the real "
            "drain/respawn/resume machinery: a saturated featurize lane "
            "must scale up, an idle one back down, and the merged "
            "output must stay bit-identical) and exit 0/1"
        ),
    )
    batch.add_argument(
        "--selftest-remote", action="store_true",
        help=(
            "Run the remote-ingest drill (a loopback HTTP host serves "
            "a tar + zip with one scripted 503-then-recover and one "
            "mid-stream truncation; remote scans and a 2-stripe merge "
            "must be bit-identical to local disk) and exit 0/1"
        ),
    )
    batch.add_argument("--stats", action="store_true",
                       help="Print run stats + per-stage timers to stderr")
    batch.add_argument(
        "--progress", type=nonneg(float), default=0, metavar="SECS",
        help=(
            "With --output: emit a JSON progress line (rows done, "
            "files/sec, dedupe hits) to stderr at most every SECS "
            "seconds — a 50M-file scan should not be a black box"
        ),
    )
    batch.add_argument("--profile", default=None, metavar="DIR",
                       help="Write a jax.profiler trace to DIR")
    batch.set_defaults(func=cmd_batch_detect)

    serve = sub.add_parser("serve", help=_COMMAND_HELP["serve"])
    serve.add_argument(
        "--socket", default=None, metavar="PATH|HOST:PORT",
        help=(
            "Serve on a Unix domain socket — or, as host:port, on TCP "
            "(the cross-host federation tier's worker transport; "
            "TCP_NODELAY on every connection) — one JSONL session per "
            "connection, shared cache; default is one session on "
            "stdin/stdout"
        ),
    )
    serve.add_argument(
        "--mode", default="license",
        choices=["license", "readme", "package", "auto"],
        help=(
            "Which matcher chain requests run (same semantics as "
            "batch-detect; 'auto' routes each request by its filename)"
        ),
    )
    serve.add_argument(
        "--corpus", default="vendored",
        help=(
            "Template pool: 'vendored' (default), 'spdx', or a path to "
            "an SPDX license-list-XML src/ directory"
        ),
    )
    serve.add_argument(
        "--method", default="auto",
        choices=["auto", "popcount", "matmul", "pallas", "pallas-mxu"],
        help="Device scoring path (same as batch-detect)",
    )
    serve.add_argument(
        "--mesh", default=None, metavar="DATA[,MODEL]",
        help=(
            "Device mesh for the scorer ('auto' = all visible devices "
            "data-parallel; default: single device — bucket shapes are "
            "rounded up to the data axis)"
        ),
    )
    serve.add_argument(
        "--max-batch", type=bounded(int, 1), default=256, metavar="N",
        help=(
            "Flush a micro-batch as soon as N Dice-bound requests are "
            "waiting (default 256)"
        ),
    )
    serve.add_argument(
        "--max-delay-ms", type=nonneg(float), default=5.0, metavar="MS",
        help=(
            "Flush a PARTIAL micro-batch once its oldest request has "
            "waited MS milliseconds — the latency bound (default 5)"
        ),
    )
    serve.add_argument(
        "--queue-depth", type=bounded(int, 1), default=1024, metavar="N",
        help=(
            "Bounded admission queue: a request arriving with N "
            "Dice-bound rows already waiting is rejected with "
            "retry_after instead of buffered (default 1024)"
        ),
    )
    serve.add_argument(
        "--pipeline-depth", type=bounded(int, 1), default=2, metavar="N",
        help=(
            "How many submitted device flushes may be in flight before "
            "the scheduler thread blocks on the handoff queue: 1 = "
            "synchronous flush, >= 2 = the overlap pipeline (the "
            "scheduler gathers flush N+1 while the device scores N "
            "and the completion thread answers N-1; default 2)"
        ),
    )
    serve.add_argument(
        "--cache-entries", type=nonneg(int), default=65536, metavar="N",
        help=(
            "Content-hash LRU result cache capacity; 0 disables "
            "(default 65536)"
        ),
    )
    serve.add_argument(
        "--cache-bytes", type=nonneg(int), default=None, metavar="N",
        help=(
            "Bound the result cache by estimated resident BYTES "
            "(LRU eviction, on top of --cache-entries) — the memory "
            "ceiling for week-long fleet workers (default: unbounded)"
        ),
    )
    serve.add_argument(
        "--deadline-ms", type=nonneg(float), default=0.0, metavar="MS",
        help=(
            "Default per-request deadline: a request still queued after "
            "MS milliseconds answers deadline_exceeded instead of "
            "occupying a device slot; 0 = none (per-request "
            "deadline_ms overrides)"
        ),
    )
    serve.add_argument(
        "--buckets", default=None, metavar="N,N,...",
        help=(
            "Padded device batch shapes (ascending); each compiles "
            "once and partial flushes pad to the smallest fitting "
            "bucket (default: a x4 ladder up to --max-batch)"
        ),
    )
    serve.add_argument(
        "--confidence", type=float, default=None, metavar="N",
        help=(
            "Minimum Dice confidence for a match (default: the global "
            f"threshold, {licensee_tpu.CONFIDENCE_THRESHOLD})"
        ),
    )
    serve.add_argument(
        "--stats", action="store_true",
        help="Dump the final stats JSON to stderr at shutdown",
    )
    serve.add_argument(
        "--no-tracing", action="store_true",
        help="Disable request tracing entirely (metrics stay on)",
    )
    serve.add_argument(
        "--trace-sample", type=nonneg(float), default=0.01, metavar="RATE",
        help=(
            "Head-sampling rate in [0,1]: retain every ~1/RATE-th "
            "request's trace (default 0.01; slow requests are always "
            "retained regardless)"
        ),
    )
    serve.add_argument(
        "--trace-slow-ms", type=nonneg(float), default=250.0, metavar="MS",
        help=(
            "Slow-request exemplar threshold: a request slower than MS "
            "is retained (and logged with --trace-log) even when head "
            "sampling skipped it (default 250)"
        ),
    )
    serve.add_argument(
        "--trace-log", default=None, metavar="PATH",
        help=(
            "Append slow-request exemplar traces to this JSONL file "
            "(bounded: one rotation to PATH.1 at ~4 MiB)"
        ),
    )
    serve.add_argument(
        "--selftest", action="store_true",
        help=(
            "Run an in-process end-to-end session (exact prefilter, "
            "Dice micro-batch, cache hit, stats verb, Prometheus "
            "exposition, five-span exemplar trace) and exit 0/1 — "
            "the CI smoke"
        ),
    )
    serve.add_argument(
        "--selftest-reload", action="store_true",
        help=(
            "Run the corpus hot-swap smoke (build an artifact, serve "
            "live traffic, blue/green reload under it, verify the "
            "fingerprint flipped, the cache fenced, and corrupt/"
            "unloadable sources refused) and exit 0/1 — the CI smoke"
        ),
    )
    serve.set_defaults(func=cmd_serve)

    stats = sub.add_parser("stats", help=_COMMAND_HELP["stats"])
    stats.add_argument(
        "--socket", action="append", default=None,
        metavar="PATH|HOST:PORT",
        help=(
            "A serve worker's Unix socket — or host:port for a TCP "
            "worker/front — to scrape; repeat the flag for a fleet — "
            "several targets print ONE merged table (json) or one "
            "worker-labeled merged exposition (prometheus)"
        ),
    )
    stats.add_argument(
        "--format", default="json", choices=["json", "prometheus"],
        help=(
            "Output: 'json' (the stats verb snapshot) or 'prometheus' "
            "(text exposition — pipe into a node_exporter textfile or "
            "curl-style scrape job)"
        ),
    )
    stats.add_argument(
        "--watch", type=nonneg(float), default=None, metavar="SECS",
        help=(
            "Re-scrape and redraw the merged table every SECS seconds "
            "(Ctrl-C stops) — the live operator view of a fleet; REQ_S "
            "reads the target's telemetry store when it serves the "
            "query verb (honest from the first frame), else the "
            "completed-counter delta per second"
        ),
    )
    stats.add_argument(
        "--watch-iterations", type=nonneg(int), default=None,
        help=argparse.SUPPRESS,  # bound the --watch loop (tests/CI)
    )
    stats.add_argument(
        "--trace", type=nonneg(int), default=None, metavar="N",
        help="Print the last N retained traces (JSONL) instead of metrics",
    )
    stats.add_argument(
        "--timeout", type=nonneg(float), default=10.0, metavar="SECS",
        help="Socket connect/read timeout (default 10)",
    )
    stats.add_argument(
        "--selftest", action="store_true",
        help=(
            "Exercise the obs layer in-process (registry, histogram "
            "math, exposition grammar, tracer sampling + slow "
            "exemplars, native-profile delta scrape) and exit 0/1 — "
            "the CI smoke"
        ),
    )
    stats.set_defaults(func=cmd_stats)

    traces = sub.add_parser("traces", help=_COMMAND_HELP["traces"])
    traces.add_argument(
        "--socket", required=True, metavar="PATH|HOST:PORT",
        help=(
            "A fleet FRONT door (licensee-tpu fleet --socket TARGET; "
            "host:port for a TCP front): the router's collector pulls "
            "every worker tail and answers {'op': 'traces'} with "
            "assembled trees"
        ),
    )
    traces.add_argument(
        "--id", default=None, metavar="HEX",
        help="Only traces whose 16-hex ID starts with this prefix",
    )
    traces.add_argument(
        "--slowest", type=bounded(int, 1), default=None, metavar="N",
        help="The N slowest assembled traces (default: 20 slowest)",
    )
    traces.add_argument(
        "--n", type=bounded(int, 1), default=20, metavar="N",
        help="How many trees to fetch without --slowest (default 20)",
    )
    traces.add_argument(
        "--json", action="store_true",
        help="One JSON tree per line instead of the rendered view",
    )
    traces.add_argument(
        "--timeout", type=nonneg(float), default=10.0, metavar="SECS",
        help="Socket connect/read timeout (default 10)",
    )
    traces.set_defaults(func=cmd_traces)

    slo = sub.add_parser("slo", help=_COMMAND_HELP["slo"])
    slo.add_argument(
        "--socket", required=True, metavar="PATH|HOST:PORT",
        help=(
            "A serve worker's socket (its own objectives) or a fleet "
            "front door — host:port for TCP — (the router's "
            "fleet-level objectives)"
        ),
    )
    slo.add_argument(
        "--json", action="store_true",
        help="Print the raw slo stats block instead of the table",
    )
    slo.add_argument(
        "--timeout", type=nonneg(float), default=10.0, metavar="SECS",
        help="Socket connect/read timeout (default 10)",
    )
    slo.set_defaults(func=cmd_slo)

    top = sub.add_parser("top", help=_COMMAND_HELP["top"])
    top.add_argument(
        "--socket", required=True, metavar="PATH|HOST:PORT",
        help=(
            "A fleet FRONT door (its router owns the telemetry store "
            "the dashboard reads); host:port for a TCP front"
        ),
    )
    top.add_argument(
        "--interval", type=nonneg(float), default=2.0, metavar="SECS",
        help=(
            "Redraw cadence (default 2; 0 prints one frame and exits)"
        ),
    )
    top.add_argument(
        "--iterations", type=nonneg(int), default=None,
        help=argparse.SUPPRESS,  # bound the redraw loop (tests/CI)
    )
    top.add_argument(
        "--timeout", type=nonneg(float), default=10.0, metavar="SECS",
        help="Socket connect/read timeout (default 10)",
    )
    top.set_defaults(func=cmd_top)

    alerts = sub.add_parser("alerts", help=_COMMAND_HELP["alerts"])
    alerts.add_argument(
        "--socket", required=True, metavar="PATH|HOST:PORT",
        help=(
            "A fleet FRONT door (the router's watchdog owns the alert "
            "ledger); host:port for a TCP front"
        ),
    )
    alerts.add_argument(
        "--history", type=nonneg(int), default=0, metavar="N",
        help="Also print the last N fire/clear transitions",
    )
    alerts.add_argument(
        "--json", action="store_true",
        help="Print the raw watchdog snapshot instead of the table",
    )
    alerts.add_argument(
        "--timeout", type=nonneg(float), default=10.0, metavar="SECS",
        help="Socket connect/read timeout (default 10)",
    )
    alerts.set_defaults(func=cmd_alerts)

    fleet = sub.add_parser("fleet", help=_COMMAND_HELP["fleet"])
    fleet.add_argument(
        "--socket", default=None, metavar="PATH|HOST:PORT",
        help="The client-facing front socket (JSONL, same protocol "
             "as one worker — clients cannot tell the difference).  "
             "host:port binds the front door on TCP",
    )
    fleet.add_argument(
        "--http", default=None, metavar="HOST:PORT",
        help=(
            "Also serve the HTTP/1.1 keep-alive edge on this TCP "
            "address (POST /classify with a JSON content-row body, "
            "GET /healthz, GET /metrics), on the router's own event "
            "loop — queue_full maps to 429 + Retry-After, router "
            "shutdown to 503"
        ),
    )
    fleet.add_argument(
        "--edge-token", action="append", default=None,
        metavar="NAME=TOKEN",
        help=(
            "HTTP edge bearer token (repeatable): requests must carry "
            "'Authorization: Bearer TOKEN' and are rate-limited and "
            "fair-queued per NAME.  A bare TOKEN names itself.  "
            "Default: auth off (clients keyed by peer address)"
        ),
    )
    fleet.add_argument(
        "--edge-rate", type=bounded(float, 0.001), default=1000.0,
        metavar="RPS",
        help="HTTP edge per-client token-bucket rate (default 1000/s)",
    )
    fleet.add_argument(
        "--edge-burst", type=bounded(float, 1), default=None,
        metavar="N",
        help="HTTP edge per-client burst depth (default: the rate)",
    )
    fleet.add_argument(
        "--federate", default=None, metavar="TARGET,TARGET,...",
        help=(
            "Run as the cross-host FRONT tier: no local workers — "
            "each comma-separated target (host:port or socket path) "
            "is another fleet's front door, dispatched least-loaded "
            "with failover/hedging across hosts and scraped into a "
            "host-labeled merged exposition"
        ),
    )
    fleet.add_argument(
        "--workers", type=bounded(int, 1), default=2, metavar="N",
        help="Worker processes to supervise (default 2)",
    )
    fleet.add_argument(
        "--chips-per-worker", type=bounded(int, 1), default=None,
        metavar="K",
        help=(
            "Give worker i chips [i*K, (i+1)*K) via the "
            "LICENSEE_TPU_VISIBLE_CHIPS env contract "
            "(parallel/distributed.py apply_visible_chips); default: "
            "workers share default device visibility"
        ),
    )
    fleet.add_argument(
        "--socket-dir", default=None, metavar="DIR",
        help="Directory for per-worker sockets (default: a tmpdir)",
    )
    fleet.add_argument(
        "--hedge-ms", default="off", metavar="MS|auto|off",
        help=(
            "Hedged requests: after MS milliseconds without an answer, "
            "duplicate the request to a second worker and take the "
            "first answer ('auto' derives the delay from the live p95; "
            "default off).  A duplicate the twin has cached or in "
            "flight coalesces by content hash; otherwise the extra "
            "device load is bounded by the hedge rate (~5% at auto)"
        ),
    )
    fleet.add_argument(
        "--pool-per-worker", type=bounded(int, 1), default=4,
        metavar="N",
        help=(
            "Pipelined backend connections the router may open per "
            "worker (default 4).  Many requests ride each connection "
            "at once; more connections spread head-of-line blocking, "
            "at the cost of more worker session threads"
        ),
    )
    fleet.add_argument(
        "--probe-interval-ms", type=bounded(float, 1), default=250.0,
        metavar="MS",
        help="Health-probe cadence for supervisor and router "
             "(default 250)",
    )
    fleet.add_argument(
        "--restart-backoff-ms", type=bounded(float, 1), default=250.0,
        metavar="MS",
        help=(
            "Base restart backoff: a crashed worker respawns after "
            "MS * 2^restarts ms, capped at 10s; the counter resets "
            "after 10s of stable health (default 250)"
        ),
    )
    fleet.add_argument(
        "--boot-timeout", type=bounded(float, 1), default=300.0,
        metavar="SECS",
        help="How long to wait for every worker's first health probe "
             "(default 300)",
    )
    # per-worker serve knobs, forwarded verbatim to each worker argv
    fleet.add_argument("--mode", default=None,
                       choices=["license", "readme", "package", "auto"],
                       help="Forwarded to each worker (serve --mode)")
    fleet.add_argument("--corpus", default=None,
                       help="Forwarded to each worker (serve --corpus)")
    fleet.add_argument(
        "--method", default=None,
        choices=["auto", "popcount", "matmul", "pallas", "pallas-mxu"],
        help="Forwarded to each worker (serve --method)",
    )
    fleet.add_argument("--max-batch", type=bounded(int, 1), default=None,
                       help="Forwarded to each worker (serve --max-batch)")
    fleet.add_argument(
        "--max-delay-ms", type=nonneg(float), default=None,
        help="Forwarded to each worker (serve --max-delay-ms)",
    )
    fleet.add_argument(
        "--queue-depth", type=bounded(int, 1), default=None,
        help="Forwarded to each worker (serve --queue-depth)",
    )
    fleet.add_argument(
        "--cache-entries", type=nonneg(int), default=None,
        help="Forwarded to each worker (serve --cache-entries)",
    )
    fleet.add_argument(
        "--cache-bytes", type=nonneg(int), default=None,
        help="Forwarded to each worker (serve --cache-bytes)",
    )
    fleet.add_argument(
        "--trace-sample", type=nonneg(float), default=None,
        help="Forwarded to each worker (serve --trace-sample)",
    )
    fleet.add_argument(
        "--stats", action="store_true",
        help="Dump the router's fleet stats JSON to stderr at shutdown",
    )
    fleet.add_argument(
        "--selftest", action="store_true",
        help=(
            "Boot a 2-worker CPU fleet, SIGKILL one worker under live "
            "client traffic, and assert zero client-visible errors, "
            "restart-within-backoff, trace propagation, merged "
            "exposition, and clean drain; exit 0/1 — the CI smoke"
        ),
    )
    fleet.add_argument(
        "--selftest-reload", action="store_true",
        help=(
            "Run the fault-drilled zero-downtime upgrade selftest: a "
            "live 2-worker fleet under continuous traffic completes "
            ">=3 rolling corpus reloads interleaved with corrupt-"
            "artifact, refused-validation (rollback), and SIGKILL-"
            "mid-swap faults, with zero client-visible errors; "
            "exit 0/1"
        ),
    )
    fleet.add_argument(
        "--selftest-tcp", action="store_true",
        help=(
            "Run the cross-host federation selftest: 2 supervisor "
            "domains over loopback TCP behind one federated front "
            "router + the HTTP edge — an open-loop HTTP burst, then "
            "SIGKILL of one host's worker mid-stream with zero "
            "client-visible errors (cross-host failover), auth/"
            "slowloris drills, and a host+worker-labeled merged "
            "exposition; exit 0/1"
        ),
    )
    fleet.add_argument(
        "--selftest-jobs", action="store_true",
        help=(
            "Run the durable-jobs selftest: a fleet with --jobs-dir "
            "takes a tar-manifest job over POST /jobs, the whole "
            "process tree is SIGKILLed mid-drain, a second boot on "
            "the same jobs dir replays the journal and resumes from "
            "the stripe shards, and the merged results must be "
            "byte-identical to a direct batch-detect --stripes run "
            "with zero client-visible errors and an assembled "
            "edge+executor+stripe trace; exit 0/1"
        ),
    )
    fleet.add_argument(
        "--selftest-tenant", action="store_true",
        help=(
            "Run the multi-tenant serving selftest: two tenants with "
            "disjoint corpora on separate worker pools behind one "
            "router and HTTP edge — tagged corpus routing, an "
            "authenticated POST /corpus upload+roll of tenant A under "
            "tenant B's live traffic (B's latency SLO must hold), "
            "SIGKILL failover confined to one pool, 401/403/400 auth "
            "probes, and journal crash recovery, with ZERO cross-"
            "tenant rows; exit 0/1"
        ),
    )
    fleet.add_argument(
        "--tenants", default=None, metavar="FILE",
        help=(
            "Serve multi-tenant: FILE is the tenant registry JSON "
            "(token -> corpus -> pool); the fleet boots one worker "
            "pool per registry pool (--workers workers EACH, on that "
            "pool's corpus), routes requests by corpus tag / bearer "
            "token, and serves self-serve corpus onboarding on "
            "POST /corpus (needs --http for the authenticated edge)"
        ),
    )
    fleet.add_argument(
        "--jobs-dir", default=None, metavar="DIR",
        help=(
            "Serve the durable jobs tier (POST /jobs on the HTTP "
            "edge): an append-only journal plus per-job stripe shards "
            "under DIR — a SIGKILLed fleet rebooted on the same DIR "
            "replays the journal and resumes interrupted jobs from "
            "their shards.  Needs --http"
        ),
    )
    fleet.add_argument(
        "--jobs-concurrency", type=bounded(int, 1), default=1,
        metavar="N",
        help=(
            "How many jobs may run their stripe trees at once "
            "(default 1; each job already fans out --stripes worker "
            "processes)"
        ),
    )
    fleet.add_argument(
        "--stub", action="store_true",
        help=(
            "With --selftest/--selftest-reload/--selftest-tcp/"
            "--selftest-jobs/--selftest-tenant: use protocol-faithful "
            "stub workers (no device path) — seconds instead of a JAX "
            "boot per worker"
        ),
    )
    fleet.set_defaults(func=cmd_fleet)

    jobs = sub.add_parser("jobs", help=_COMMAND_HELP["jobs"])
    jobs.add_argument(
        "action",
        choices=["submit", "status", "results", "containers", "cancel",
                 "wait"],
        help=(
            "submit a job spec, poll one job's lifecycle status, "
            "fetch its merged results JSONL / container-verdict "
            "sidecar, cancel it, or block until it reaches a "
            "terminal state"
        ),
    )
    jobs.add_argument(
        "job_id", nargs="?", default=None,
        help="The job id every action but submit operates on",
    )
    jobs.add_argument(
        "--edge", required=True, metavar="HOST:PORT",
        help="The fleet's HTTP edge (a `fleet --http --jobs-dir` door)",
    )
    jobs.add_argument(
        "--token", default=None,
        help="Bearer token for an --edge-token protected edge",
    )
    jobs.add_argument(
        "--manifest", default=None, metavar="FILE",
        help=(
            "Submit this manifest (one entry per line; plain paths or "
            "the ingest grammar — tar::MEMBER, zip::MEMBER, "
            "repo.git::REV, * globs)"
        ),
    )
    jobs.add_argument(
        "--archive", default=None, metavar="PATH",
        help=(
            "Upload this local tar/zip with the submit (base64 in the "
            "spec body); without --manifest the job classifies every "
            "member (ARCHIVE::*)"
        ),
    )
    jobs.add_argument(
        "--stripes", type=bounded(int, 1), default=None, metavar="N",
        help="Worker processes the job's batch-detect fans out to",
    )
    jobs.add_argument(
        "--option", action="append", default=None, metavar="KEY=VALUE",
        help=(
            "Forwarded batch-detect knob (repeatable): batch_size, "
            "workers, mesh, mode, corpus, method, confidence"
        ),
    )
    jobs.add_argument(
        "--idempotency-key", default=None, metavar="KEY",
        help=(
            "Duplicate-submit fence: a resubmit carrying the same key "
            "answers the original job id instead of minting a new job"
        ),
    )
    jobs.add_argument(
        "--wait", action="store_true",
        help="With submit: block until the job is terminal",
    )
    jobs.add_argument(
        "--wait-timeout", type=bounded(float, 0.001), default=600.0,
        metavar="SECS",
        help="How long wait/--wait polls before giving up (default 600)",
    )
    jobs.add_argument(
        "--timeout", type=bounded(float, 0.001), default=30.0,
        metavar="SECS",
        help="Per-round-trip edge timeout in seconds (default 30)",
    )
    jobs.add_argument(
        "--output", default=None, metavar="PATH",
        help="Write results/containers bytes here instead of stdout",
    )
    jobs.set_defaults(func=cmd_jobs)

    corpus_build = sub.add_parser(
        "corpus-build", help=_COMMAND_HELP["corpus-build"]
    )
    corpus_build.add_argument(
        "--corpus", default="vendored",
        help=(
            "Source to compile: 'vendored', 'spdx', an SPDX license-"
            "list-XML src/ directory, or an existing artifact "
            "(re-fingerprint/repack)"
        ),
    )
    corpus_build.add_argument(
        "--output", default=None, metavar="PATH",
        help=(
            "Write the artifact bundle here (atomic replace; prints "
            "the fingerprint manifest on success).  Serve it with "
            "--corpus PATH or hot-swap a live worker with the "
            "{\"op\": \"reload\"} verb"
        ),
    )
    corpus_build.add_argument(
        "--inspect", default=None, metavar="PATH",
        help=(
            "Load an artifact, verify its payload against the "
            "fingerprint manifest, and print the manifest"
        ),
    )
    corpus_build.set_defaults(func=cmd_corpus_build)

    # the COMMANDS table and the registered subcommands must not drift:
    # `help` prints from the table, the parser dispatches from argparse
    if set(sub.choices) != {name for name, _ in COMMANDS}:
        raise AssertionError(
            f"COMMANDS out of sync with parser: {sorted(sub.choices)} "
            f"vs {[name for name, _ in COMMANDS]}"
        )
    return parser


def main(argv: list[str] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    parser = build_parser()
    # derived from COMMANDS (not a second hand-kept list) so a new
    # subcommand can never silently fall through to detect-with-a-path
    known_commands = {name for name, _ in COMMANDS} | {"-h", "--help"}
    # default task is detect (bin/licensee:12)
    if not argv or (argv[0] not in known_commands):
        argv = ["detect", *argv]
    args = parser.parse_args(argv)
    return args.func(args)


if __name__ == "__main__":
    sys.exit(main())
