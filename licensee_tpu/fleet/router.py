"""The fleet router: one client-facing socket fronting N serve workers
— least-loaded dispatch, health-checked failover, backpressure-aware
retries, and tail-cutting hedged requests, all carried by ONE
single-threaded non-blocking event loop (serve/eventloop.py).

Dean & Barroso's "The Tail at Scale" is the playbook:

* **least-loaded routing** — each request goes to the healthy,
  non-draining worker with the lowest load score (probed
  ``queue_depth + in_flight`` plus the router's own outstanding count
  for that worker; the local term keeps bursts spread even between
  probe rounds).
* **failover on death** — classification requests are pure functions
  of content (the content-hash cache key IS the idempotency proof), so
  a request whose worker dies mid-flight is simply retried on another
  replica.  The client sees one answer, never a connection reset.
* **backpressure failover** — a worker answering ``queue_full`` sheds
  load; the router tries the next replica and only surfaces
  ``queue_full`` (with the smallest ``retry_after``) when EVERY
  replica is shedding.
* **hedged requests** — optionally, a duplicate is sent to a second
  worker once the first has been out longer than the observed p95
  (``hedge_ms="auto"``) or a fixed delay; the first answer wins.  The
  duplicate costs the twin a device slot only for content it has never
  seen: a blob already cached or in flight there coalesces via the
  content-hash key (ResultCache/MicroBatcher), and otherwise the extra
  load is bounded by the hedge rate (~5% at a p95-derived delay).  The
  loser's late answer is discarded when it eventually arrives.

**The I/O core.**  Every client connection, every backend connection,
every health probe, and every timeout is a callback on one
``selectors`` event loop — no thread is ever parked on a socket, so a
slow or dead backend can never stall an unrelated client.  Requests
are **pipelined** onto a bounded per-worker connection pool
(``pool_per_worker``): a backend connection carries many in-flight
requests at once, correlated back to their clients by FIFO order (the
worker answers in request order by contract) and cross-checked against
the trace ID the wire protocol carries — a response echoing the wrong
trace is a protocol violation that kills the connection and fails its
in-flight requests over rather than ever answering the wrong client.
A connection that dies with requests in flight fails ALL of them over;
a request that times out closes its (head-of-line-blocked) connection,
failing the requests queued behind it over too.

Trace IDs are minted HERE and forwarded on the wire (``"trace"``
field); the worker adopts the ID (obs/tracing.py), so the router tail
shows ``route``/``hedge``/``failover`` spans and the worker tail shows
the serving spans — same 16-hex handle end to end.

Threading contract: the request state machines live on the loop thread
and need no locks.  ``dispatch()`` is the blocking facade (submit via
``call_soon_threadsafe``, wait on an event); ``stats()`` /
``outstanding()`` / ``pick()`` snapshot loop-owned state via
``run_sync``.  Long ops verbs (the fan-out Prometheus scrape, the
rolling fleet reload) run on a small ops executor, never on the loop.
The analyzer's ``blocking-call`` rule walks every loop callback in
this module: a blocking primitive on the loop thread is a CI finding.
"""

from __future__ import annotations

import json
import random
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor

import re

from licensee_tpu.fleet.wire import (
    ConnectionPool,
    WireError,
    json_str_field,
    oneshot,
)
from licensee_tpu.obs import (
    AnomalyWatchdog,
    FlatlineRule,
    Observability,
    QueryError,
    RateJumpRule,
    SaturationRule,
    ScrapeScheduler,
    SLOEngine,
    TraceCollector,
    TsdbStore,
    merge_expositions,
    pool_objectives,
    router_objectives,
)
from licensee_tpu.serve.eventloop import (
    EventLoop,
    LineConn,
    LoopClosedError,
    LoopJsonlServer,
    connect_target,
    drop_close,
    drop_line,
)
from licensee_tpu.serve.stats import LatencyStats

# how long a no-backend request waits between re-pick attempts while
# the whole fleet is down (a restart may bring a worker back before
# the dispatch deadline) — a timer wakeup, never a parked thread
_REPICK_DELAY_S = 0.05

# wire trace IDs are 64-bit, rendered 16-hex — same space the tracer
# mints from (obs/tracing.py); the mint-only fast path masks into it
_WIRE_MASK = 0xFFFFFFFFFFFFFFFF

# error codes a FEDERATED backend (a per-host router fronting its own
# worker domain) answers when ITS domain momentarily has no capacity —
# to the tier above they mean "this host cannot serve this request
# right now", i.e. attempt failure with failover to another host, never
# a client-visible row.  A plain worker mints none of these, so the
# single-host path is untouched.
_FEDERATED_FAILOVER_CODES = frozenset(
    ("no_backend_available", "router_closed", "router_not_started")
)

# an upstream hop's trace ID: 16 lowercase hex (the worker's adoption
# grammar, serve/server.py TRACE_ID_RE)
_TRACE_ID_RE = re.compile(r"\A[0-9a-f]{16}\Z")


def _fp_compatible(got: str, want: str) -> bool:
    """Fingerprint identity across stamp conventions: real workers
    stamp the SHORT (12-hex) fingerprint on response rows while the
    route table may hold the full 64-hex form (and stub workers stamp
    whatever ``--fingerprint`` string they were booted with) — a
    prefix match in either direction is the same corpus, anything else
    is a worker on the wrong fingerprint."""
    return got == want or got.startswith(want) or want.startswith(got)


class _Attempt:
    """One request sent to one backend connection: the FIFO entry that
    a response line (or a connection death, or a timeout) resolves.
    ``deadline`` is the monotonic instant the router's timeout sweep
    declares this attempt head-of-line dead — one periodic sweep over
    the FIFO heads replaces the timer-per-attempt heap churn that was
    measurable at saturation."""

    __slots__ = ("request", "backend", "conn", "is_hedge", "resolved",
                 "deadline")

    def __init__(self, request: "_Request", backend: "Backend",
                 is_hedge: bool):
        self.request = request
        self.backend = backend
        self.conn: "_BackendConn | None" = None
        self.is_hedge = is_hedge
        self.resolved = False
        self.deadline = 0.0


class _Request:
    """One routed client request's event-loop state machine.

    ``msg`` may be None: the front session's fast path skips the
    client-line parse for content rows, and :attr:`rid` then parses the
    wire line lazily — only the rare finishing paths (queue_full,
    deadline, error rows, slow exemplars) ever need the request id."""

    __slots__ = ("msg", "wire_line", "trace", "wire_trace",
                 "tried", "queue_full_rows", "arms", "t0", "deadline",
                 "hedge_timer", "hedge_started", "first_round",
                 "finished", "last_reason", "on_done", "repick_timer",
                 "pool")

    def __init__(self, msg: dict | None, wire_line: str, trace,
                 wire_trace, on_done, pool=None):
        self.msg = msg
        self.pool = pool
        self.wire_line = wire_line
        self.trace = trace
        self.wire_trace = wire_trace
        self.tried: set[str] = set()
        self.queue_full_rows: list[dict] = []
        self.arms: list[_Attempt] = []
        self.t0 = 0.0
        self.deadline = 0.0
        self.hedge_timer = None
        self.hedge_started = False
        self.first_round = True
        self.finished = False
        self.last_reason = "no healthy backend"
        self.on_done = on_done
        self.repick_timer = None

    @property
    def rid(self):
        if self.msg is None:
            try:
                parsed = json.loads(self.wire_line)
                self.msg = parsed if isinstance(parsed, dict) else {}
            except ValueError:
                self.msg = {}
        return self.msg.get("id")


class _BackendConn:
    """One pipelined JSONL connection to a worker: a FIFO of in-flight
    attempts, resolved strictly in order as response lines arrive (the
    worker's in-order session contract), each response cross-checked
    against the trace ID its request carried."""

    __slots__ = ("router", "backend", "state", "fifo", "line_conn",
                 "_pending_lines", "_abort_connect")

    def __init__(self, router: "Router", backend: "Backend"):
        self.router = router
        self.backend = backend
        self.state = "connecting"
        self.fifo: deque[_Attempt] = deque()
        self.line_conn: LineConn | None = None
        self._pending_lines: list[str] = []
        self._abort_connect = connect_target(
            router.loop, backend.socket_path, router.probe_timeout_s,
            self._on_connected, self._on_connect_error,
        )

    def inflight(self) -> int:
        return len(self.fifo)

    def send(self, attempt: _Attempt) -> None:
        attempt.conn = self
        if self.state == "closed":
            # the dial failed synchronously (ECONNREFUSED on a freshly
            # killed worker's stale socket): buffering here would strand
            # the attempt forever — fail it over NOW instead
            self.router._attempt_resolved(
                attempt, "fail",
                f"{self.backend.name}: connection already closed",
            )
            return
        self.fifo.append(attempt)
        line = attempt.request.wire_line
        if self.state == "open":
            try:
                self.line_conn.write_line_on_loop(line)
            except OSError:
                pass  # close already failed the FIFO over
        else:
            self._pending_lines.append(line)

    # -- loop callbacks --

    def _on_connected(self, sock) -> None:
        self.state = "open"
        self.line_conn = LineConn(
            self.router.loop, sock,
            on_line=self._on_line, on_close=self._on_close,
        )
        pending, self._pending_lines = self._pending_lines, []
        for line in pending:
            try:
                self.line_conn.write_line_on_loop(line)
            except OSError:
                return

    def _on_connect_error(self, exc: Exception) -> None:
        self.state = "closed"
        self._fail_over(f"connect failed: {exc}")

    def _on_line(self, text: str) -> None:
        if not self.fifo:
            self.close("response with no request in flight")
            return
        attempt = self.fifo.popleft()
        expected = attempt.request.wire_trace
        # the hot path avoids a json.loads per response: the trace
        # cross-check and the queue_full/error detection run as
        # substring probes on the raw line (a 16-hex trace ID cannot
        # appear by accident), and the full parse happens only on the
        # rare paths — backpressure rows, protocol violations, and the
        # blocking dispatch() facade's caller thread
        if (
            expected is not None
            and '"trace"' in text
            and expected not in text
        ):
            # pipelining's integrity check: the worker's in-order
            # contract says this response belongs to the FIFO head, but
            # the echoed trace disagrees — the stream is out of sync.
            # Never deliver a mis-correlated verdict: fail this attempt
            # over and burn the connection (its position is unknowable).
            try:
                got = json.loads(text).get("trace")
            except (ValueError, AttributeError):
                got = "<unparseable>"
            self.router._attempt_resolved(
                attempt, "fail",
                f"{self.backend.name}: trace mismatch "
                f"(sent {expected}, response echoes {got})",
            )
            self.close("pipelined response trace mismatch")
            return
        if (
            '"error"' in text
            or '"id"' not in text
            or not text.endswith("}")
        ):
            try:
                row = json.loads(text)
                if not isinstance(row, dict):
                    raise ValueError("response must be a JSON object")
            except ValueError as exc:
                # the head attempt is already popped: fail it over with
                # everything behind it — the stream is unreadable
                self.router._attempt_resolved(
                    attempt, "fail",
                    f"{self.backend.name}: bad response line: {exc}",
                )
                self.close(f"bad response line: {exc}")
                return
            err = row.get("error")
            if isinstance(err, str) and (
                err.split(":", 1)[0] in _FEDERATED_FAILOVER_CODES
            ):
                # cross-host federation: a per-host router reporting
                # "my domain has no backend" (worker mid-restart,
                # domain draining) is a failed ATTEMPT at this tier —
                # fail over to another host instead of relaying the
                # error to the client
                self.router._attempt_resolved(
                    attempt, "fail",
                    f"{self.backend.name}: federated backend answered "
                    f"{err}",
                )
                return
            outcome = "queue_full" if err == "queue_full" else "ok"
            self.router._attempt_resolved(attempt, outcome, row, text)
            return
        self.router._attempt_resolved(attempt, "ok", None, text)

    def _on_close(self, reason) -> None:
        self.state = "closed"
        self._fail_over(f"connection lost: {reason}")

    def _fail_over(self, why: str) -> None:
        if self in self.backend.conns:
            self.backend.conns.remove(self)
        pending, self.fifo = list(self.fifo), deque()
        for attempt in pending:
            self.router._attempt_resolved(
                attempt, "fail", f"{self.backend.name}: {why}"
            )

    def close(self, reason: str | None = None) -> None:
        if self.state == "closed":
            return
        if self.state == "connecting":
            self.state = "closed"
            self._abort_connect()
            # abort fires _on_connect_error -> _fail_over, but only for
            # a still-pending dial; a raced completion lands in a
            # "closed" conn whose fifo we still own
            self._fail_over(reason or "closed")
            return
        self.state = "closed"
        conn, self.line_conn = self.line_conn, None
        if conn is not None:
            # LineConn.close fires _on_close exactly once -> _fail_over
            conn.on_close = self._on_close
            conn.close(reason)


class Backend:
    """The router's view of one worker: connection pool, probed load,
    and per-backend counters.  Loop-thread-owned; the metrics collector
    and ``as_dict`` read the plain ints lock-free (GIL-atomic)."""

    def __init__(self, name: str, socket_path: str):
        self.name = name
        self.socket_path = socket_path
        self.pool: str | None = None  # tenant pool (multi-pool fleets)
        self.conns: list[_BackendConn] = []
        self.healthy = False
        self.probed_load = 0
        self.probe_failures = 0
        self.probe_rounds = 0
        self.outstanding = 0  # routed requests in flight right now
        self.dispatched = 0
        self.ok = 0
        self.failed = 0
        self.queue_full = 0
        self.last_stats: dict = {}
        # probe plumbing (loop-owned)
        self.probe_conn: LineConn | None = None
        self.probe_abort = None
        self.probe_inflight = False
        self.probe_deadline = 0.0

    def load(self) -> int:
        return self.probed_load + self.outstanding

    def pool_inflight(self) -> int:
        return sum(c.inflight() for c in list(self.conns))

    def acquire_conn(self, router: "Router") -> _BackendConn:
        """The pipelining pool policy: reuse an idle connection, grow
        the pool while every connection is busy and the bound allows,
        else pipeline onto the least-loaded connection.  One pass, one
        ``len`` per connection — this runs once per request at
        saturation, where the two-comprehension version was
        measurable."""
        least = None
        least_n = 0
        closed_seen = False
        for conn in self.conns:
            if conn.state == "closed":
                # a dial that failed synchronously closes the conn
                # before (or despite) its place in the pool list —
                # prune below, never reuse
                closed_seen = True
                continue
            n = len(conn.fifo)
            if n == 0 and conn.state == "open":
                if closed_seen:
                    self.conns = [
                        c for c in self.conns if c.state != "closed"
                    ]
                return conn
            if least is None or n < least_n:
                least = conn
                least_n = n
        if closed_seen:
            self.conns = [c for c in self.conns if c.state != "closed"]
        if len(self.conns) < router.pool_per_worker:
            conn = _BackendConn(router, self)
            if conn.state != "closed":  # a sync dial failure stays out
                self.conns.append(conn)
            return conn
        return least

    def close_conns(self) -> None:
        for conn in list(self.conns):
            conn.close("router shutdown")
        self.conns.clear()
        if self.probe_conn is not None:
            conn, self.probe_conn = self.probe_conn, None
            conn.close("router shutdown")
        if self.probe_abort is not None:
            abort, self.probe_abort = self.probe_abort, None
            abort()

    def as_dict(self) -> dict:
        row = {
            "socket": self.socket_path,
            "healthy": self.healthy,
            "probed_load": self.probed_load,
            "outstanding": self.outstanding,
            "dispatched": self.dispatched,
            "ok": self.ok,
            "failed": self.failed,
            "queue_full": self.queue_full,
            "pool_conns": len(self.conns),
            "pool_inflight": self.pool_inflight(),
        }
        if self.pool is not None:
            row["pool"] = self.pool
        return row


class Router:
    """Dispatch requests across the worker fleet; serve the front
    socket.

    ``backends`` maps worker name -> socket path.  ``supervisor`` is
    optional: when given, its draining/stopped flags veto dispatch (the
    drain protocol) and the supervisor reads ``outstanding()`` back.
    ``hedge_ms`` is ``None``/"off" (no hedging), a number (fixed delay
    in ms), or "auto" (the p95 of recent request latencies, floored at
    ``hedge_floor_ms``).  ``pool_per_worker`` bounds the pipelined
    connection pool each backend may grow."""

    def __init__(
        self,
        backends: dict[str, str],
        *,
        supervisor=None,
        probe_interval_s: float = 0.25,
        probe_timeout_s: float = 2.0,
        request_timeout_s: float = 30.0,
        dispatch_wait_s: float = 15.0,
        hedge_ms=None,
        hedge_floor_ms: float = 5.0,
        hedge_min_samples: int = 20,
        max_concurrency: int = 1024,
        pool_per_worker: int = 4,
        registry=None,
        tracing: bool = True,
        trace_sample: float = 0.01,
        trace_slow_ms: float = 250.0,
        merge_label: str = "worker",
        scrape_interval_s: float = 5.0,
        store: "TsdbStore | None" = None,
        watchdog_rules=None,
        pools: dict[str, str] | None = None,
        default_pool: str = "default",
    ):
        if not backends:
            raise ValueError("need at least one backend")
        if hedge_ms in ("off", "none"):
            hedge_ms = None
        if hedge_ms is not None and hedge_ms != "auto":
            hedge_ms = float(hedge_ms)
            if not (hedge_ms >= 0):
                raise ValueError(f"hedge_ms must be >= 0, got {hedge_ms!r}")
        if int(pool_per_worker) < 1:
            raise ValueError(
                f"pool_per_worker must be >= 1, got {pool_per_worker!r}"
            )
        self.hedge_ms = hedge_ms
        self.hedge_floor_ms = float(hedge_floor_ms)
        self.hedge_min_samples = int(hedge_min_samples)
        self.supervisor = supervisor
        if supervisor is not None:
            supervisor.router = self
        self.probe_interval_s = float(probe_interval_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.request_timeout_s = float(request_timeout_s)
        self.dispatch_wait_s = float(dispatch_wait_s)
        self.max_concurrency = int(max_concurrency)
        self.pool_per_worker = int(pool_per_worker)
        # the label prometheus() tags each scraped backend's exposition
        # with: "worker" for a single-host fleet, "host" for the
        # federation tier (each backend is then a per-host router whose
        # exposition is already worker-labeled — the merge nests host
        # OUTSIDE worker, obs/export.merge_expositions)
        self.merge_label = str(merge_label)
        self.backends: dict[str, Backend] = {
            name: Backend(name, path)
            for name, path in backends.items()
        }
        # the tenancy plane: ``pools`` maps worker name -> pool name
        # (heterogeneous fleets serving different corpora side by
        # side); dispatch, failover, and hedging are then confined to
        # the request's pool.  ``_corpus_routes`` maps a request's
        # corpus tag (tenant name, pool name, full or short
        # fingerprint) to its pool; ``_pool_fps`` holds each pool's
        # expected fingerprint for response verification.  Both tables
        # are plain dicts written by ops threads (onboarding rolls)
        # and read per-request on the loop — GIL-atomic replace-only
        # updates, same discipline as the loop-owned counters.
        self.default_pool = str(default_pool)
        self.pools_active = bool(pools)
        self._corpus_routes: dict[str, str] = {}
        self._pool_fps: dict[str, str] = {}
        self._pool_counts: dict[tuple[str, str], int] = {}
        if pools:
            unknown = sorted(set(pools) - set(self.backends))
            if unknown:
                raise ValueError(
                    f"pools names unknown workers: {unknown}"
                )
            for name, backend in self.backends.items():
                backend.pool = pools.get(name, self.default_pool)
            if not any(
                b.pool == self.default_pool
                for b in self.backends.values()
            ):
                raise ValueError(
                    f"default pool {self.default_pool!r} has no "
                    "workers (untagged traffic would never dispatch)"
                )
            for pool in set(pools.values()):
                self._corpus_routes.setdefault(pool, pool)
        self.loop = EventLoop(name="fleet-router")
        self._latency = LatencyStats(capacity=1024)
        self._hedge_p95_cache: tuple[float, float] | None = None
        # loop-owned request accounting
        self._counters = {
            "requests": 0,
            "ok": 0,
            "failovers": 0,
            "retries": 0,
            "hedges_started": 0,
            "hedges_won": 0,
            "hedges_lost": 0,
            "queue_full_failovers": 0,
            "queue_full_returned": 0,
            "no_backend": 0,
            "unknown_corpus": 0,
        }
        self._active = 0
        self._admission: deque = deque()
        # every admitted, unfinished request — the shutdown path must
        # be able to answer requests parked on a repick timer, which
        # are reachable from nowhere else once their timer is dropped
        self._inflight: set = set()
        self._draining = False
        self._probe_timer = None
        self._first_probe_round = threading.Event()
        self._started = False
        self._closing = False
        self.obs = Observability(
            registry,
            tracing=tracing,
            trace_sample=trace_sample,
            trace_slow_ms=trace_slow_ms,
            trace_proc="router",
        )
        # the mint-only fast path: with head sampling off the router
        # still needs a wire trace ID per request (pipelining
        # correlation), but nothing else — mint IDs from a loop-owned
        # counter and skip the Trace object, its spans, and the
        # tracer's lock entirely.  Slow exemplars stay honest via
        # Tracer.note_slow from the measured request latency.
        self._mint_only = self.obs.tracer.mint_only
        self._wire_seq = 0
        self._wire_base = random.Random().getrandbits(64)
        self._timeout_sweep_timer = None
        # the ops lane: long front-socket verbs (the fan-out Prometheus
        # scrape, the rolling fleet reload) block BY DESIGN — they run
        # here, never on the loop
        self._ops = ThreadPoolExecutor(
            max_workers=4, thread_name_prefix="fleet-ops"
        )
        self._register_metrics()
        # the retained telemetry plane (obs/tsdb.py): a scrape round
        # every scrape_interval_s pulls each worker's exposition over a
        # parked wire connection plus the router's own registry
        # in-process, all on the ops executor — the store behind the
        # {"op": "query"} verb, /metrics/history, the SLO burn windows,
        # and the anomaly watchdog.  scrape_interval_s <= 0 keeps the
        # store but never starts the cadence thread (benches drive
        # scrape_once() by hand to isolate its cost).
        self.scrape_interval_s = float(scrape_interval_s)
        self.store = store if store is not None else TsdbStore()
        self.store.register_metrics(self.obs.registry)
        self._scrape_pools = {
            name: ConnectionPool(
                b.socket_path, max_idle=1,
                connect_timeout=self.probe_timeout_s,
            )
            for name, b in self.backends.items()
        }
        self.scraper = ScrapeScheduler(
            self.store,
            interval_s=max(self.scrape_interval_s, 0.05),
            label=self.merge_label,
            executor=self._ops,
            on_round=self._watchdog_round,
        )
        self.scraper.register_metrics(self.obs.registry)
        self.scraper.add_target("router", self._own_exposition)
        for name in self.backends:
            self.scraper.add_target(
                name, lambda n=name: self._scrape_backend(n)
            )
        self.watchdog = AnomalyWatchdog(
            self.store,
            (
                watchdog_rules
                if watchdog_rules is not None
                else self._default_watchdog_rules()
            ),
            registry=self.obs.registry,
        )
        # the fleet SLO engine (obs/slo.py): availability + p99 over
        # the router counters, attached AFTER _register_metrics so the
        # collector pass syncs counters before each evaluation.  Burn
        # windows read the telemetry store (the router's own series
        # land there labeled merge_label="router"); the private sample
        # ring stays as the fallback until the store has coverage.
        objectives = router_objectives()
        if self.pools_active:
            # one latency objective per tenant pool over the
            # pool-labeled histogram: B's burn gauge witnesses that
            # rolling A's pool never touched B's tail
            objectives += pool_objectives(
                {b.pool for b in self.backends.values()}
            )
        self.slo = SLOEngine(
            self.obs.registry, objectives,
            store=self.store,
            store_labels={self.merge_label: "router"},
        ).attach()
        # the telemetry-plane collector (obs/collect.py): the router's
        # own tail plus a {"op":"trace"} pull per worker, joined by
        # trace ID into assembled trees.  Pulls are BLOCKING fan-outs —
        # assembled_traces runs on the ops executor / caller threads,
        # never on the event loop (same contract as prometheus()).
        self.collector = TraceCollector(root_proc="router")
        self.collector.add_source(
            "router", lambda: self.obs.tracer.tail(200)
        )
        for name, backend in self.backends.items():
            self.collector.add_source(
                name,
                lambda b=backend: self._pull_worker_tail(b),
            )

    # -- metrics --

    def _register_metrics(self) -> None:
        reg = self.obs.registry
        reg.gauge(
            "fleet_backends_healthy",
            "Workers currently answering health probes",
        ).set_fn(
            lambda: sum(1 for b in self.backends.values() if b.healthy)
        )
        reg.gauge(
            "fleet_backends_total", "Workers configured behind the router"
        ).set(len(self.backends))
        reg.gauge(
            "fleet_outstanding",
            "Routed requests in flight across all workers",
        ).set_fn(
            lambda: sum(b.outstanding for b in self.backends.values())
        )
        reg.gauge(
            "fleet_loop_lag_ms",
            "Smoothed router event-loop lag (heartbeat lateness); a "
            "blocked loop grows this before the tail latencies do",
        ).set_fn(self.loop.lag_ms)
        reg.gauge(
            "fleet_loop_max_lag_ms",
            "Decaying max of the router event-loop lag",
        ).set_fn(self.loop.max_lag_ms)
        events = reg.counter(
            "fleet_requests_total",
            "Router lifecycle events by kind (requests, ok, failovers, "
            "retries, hedges_started, hedges_won, hedges_lost, "
            "queue_full_failovers, queue_full_returned, no_backend, "
            "unknown_corpus)",
            labels=("event",),
        )
        # labeled "backend", not "worker": the fleet scrape merges this
        # registry under an injected worker="router" label, and a
        # sample carrying its own "worker" label would emit a duplicate
        # label name — which a real Prometheus server rejects
        per_worker = reg.counter(
            "fleet_backend_requests_total",
            "Routed requests by backend worker and outcome",
            labels=("backend", "outcome"),
        )
        pool_conns = reg.gauge(
            "fleet_pool_connections",
            "Open pipelined connections per backend worker",
            labels=("backend",),
        )
        pool_inflight = reg.gauge(
            "fleet_pool_inflight",
            "Requests in flight on the pipelined pool per backend",
            labels=("backend",),
        )
        hist = reg.histogram(
            "fleet_request_seconds",
            "Client-visible routed request latency (retries and hedges "
            "included)",
        )
        # the solo child, resolved ONCE: family.observe() walks
        # labels() -> dict lookup per call, which is measurable at
        # per-request rates on the loop thread
        self._latency_hist = hist.labels()
        # the tenancy plane's metrics exist only on multi-pool fleets:
        # a single-corpus fleet's exposition is byte-identical to
        # before the subsystem existed
        self._pool_hists: dict[str, object] = {}
        pool_events = None
        if self.pools_active:
            pool_names = sorted(
                {b.pool for b in self.backends.values() if b.pool}
            )
            pool_hist = reg.histogram(
                "fleet_tenant_request_seconds",
                "Routed request latency by tenant pool (retries and "
                "hedges included)",
                labels=("pool",),
            )
            # children resolved once per pool, same reasoning as the
            # solo fleet_request_seconds child above
            self._pool_hists = {
                p: pool_hist.labels(pool=p) for p in pool_names
            }
            pool_events = reg.counter(
                "fleet_tenant_requests_total",
                "Tenant-pool routing events by pool and kind (ok, "
                "corpus_mismatch, unknown_corpus)",
                labels=("pool", "event"),
            )

        def collect(_reg) -> None:
            # loop-owned ints read lock-free: a torn read is impossible
            # under the GIL, and a scrape tolerates one-event staleness
            for k, v in dict(self._counters).items():
                events.labels(event=k).sync(v)
            for name, b in list(self.backends.items()):
                per_worker.labels(backend=name, outcome="ok").sync(b.ok)
                per_worker.labels(backend=name, outcome="failed").sync(
                    b.failed
                )
                per_worker.labels(backend=name, outcome="queue_full").sync(
                    b.queue_full
                )
                pool_conns.labels(backend=name).set(len(b.conns))
                pool_inflight.labels(backend=name).set(b.pool_inflight())
            if pool_events is not None:
                for (pool, event), v in list(self._pool_counts.items()):
                    pool_events.labels(pool=pool, event=event).sync(v)

        reg.add_collector(collect)

    def _bump_pool(self, pool: str | None, event: str) -> None:
        # loop-owned tenancy counters; the collector pass syncs them
        # into fleet_tenant_requests_total
        key = (pool or self.default_pool, event)
        self._pool_counts[key] = self._pool_counts.get(key, 0) + 1

    # -- telemetry plane --

    def _own_exposition(self) -> str:
        """The router registry's exposition for the scrape scheduler —
        in-process, no socket; lands in the store under
        ``{merge_label: "router"}``."""
        return self.obs.prometheus()

    def _scrape_backend(self, name: str) -> str:
        """One worker's exposition over its parked scrape connection
        (fleet/wire ConnectionPool: the connection survives between
        rounds).  Raises on any failure — the scheduler counts it a
        miss and the worker's stored series go stale, which is exactly
        what the flatline watchdog rule watches."""
        row = self._scrape_pools[name].request(
            {"op": "stats", "format": "prometheus"},
            timeout=self.probe_timeout_s,
        )
        text = row.get("prometheus")
        if not isinstance(text, str):
            raise WireError(f"no prometheus text from {name}: {row}")
        return text

    def _watchdog_round(self) -> None:
        # runs at the end of every scrape round, on the ops executor
        self.watchdog.evaluate()

    def _default_watchdog_rules(self) -> list:
        """The stock fleet rule set: p99 jump on the routed latency
        histogram, scrape flatline per worker, saturation-approach on
        the bounded occupancy gauges.  Rules over series the fleet
        never stores simply never fire."""
        interval = max(self.scrape_interval_s, 0.05)
        rules = [
            RateJumpRule(
                "router_p99_latency_jump",
                "fleet_request_seconds",
                labels={self.merge_label: "router"},
                signal="quantile",
                q=0.99,
                window_s=max(4.0 * interval, 2.0),
                baseline_windows=8,
                min_baseline=4,
                z_threshold=4.5,
                min_value=0.005,
                description="routed p99 jumped vs its trailing baseline",
            ),
            SaturationRule(
                "edge_queue_saturation",
                "edge_queue_depth",
                threshold=64.0,
                description="HTTP edge queue depth approaching overflow",
            ),
            SaturationRule(
                "pipeline_featurize_saturation",
                "pipeline_featurize_busy",
                threshold=0.95,
                description="featurize lane occupancy near saturation",
            ),
        ]
        for name in self.backends:
            rules.append(FlatlineRule(
                f"worker_scrape_flatline_{name}",
                "tsdb_scrape_up",
                labels={self.merge_label: name},
                stale_after_s=max(3.5 * interval, 5.0),
                description=(
                    f"worker {name} stopped answering telemetry scrapes"
                ),
            ))
        return rules

    # -- lifecycle --

    def start(self) -> None:
        """Start the loop and the probe machinery; returns once the
        first probe round has resolved (success or failure) for every
        backend, so ``pick()`` has a health view immediately.
        Idempotent: a second start() (manual start + ``__enter__``)
        must not arm a SECOND self-rescheduling probe/sweep chain."""
        if self._started:
            return
        self._started = True
        self.loop.start()
        self.loop.call_soon_threadsafe(self._probe_tick)
        self.loop.call_soon_threadsafe(self._arm_timeout_sweep)
        if self.scrape_interval_s > 0:
            self.scraper.start()
        self._first_probe_round.wait(self.probe_timeout_s + 2.0)

    def close(self) -> None:
        self.scraper.stop()
        try:
            self.loop.run_sync(self._shutdown_on_loop)
        except (LoopClosedError, TimeoutError):
            pass
        self.loop.stop()
        self._ops.shutdown(wait=False)
        self.collector.close()
        for pool in self._scrape_pools.values():
            pool.close()

    def _shutdown_on_loop(self) -> None:
        self._closing = True
        if self._probe_timer is not None:
            self._probe_timer.cancel()
            self._probe_timer = None
        if self._timeout_sweep_timer is not None:
            self._timeout_sweep_timer.cancel()
            self._timeout_sweep_timer = None
        # answer EVERY waiting client before the loop stops: requests
        # still in the admission queue, and admitted requests parked on
        # a repick timer (no attempt in any FIFO — close_conns would
        # never reach them, and loop.stop() drops their timers)
        while self._admission:
            req = self._admission.popleft()
            row = {"id": req.rid, "error": "router_closed"}
            if req.trace is not None:
                self.obs.tracer.finish(req.trace, "router_closed")
            if req.wire_trace is not None:
                row["trace"] = req.wire_trace
            self._deliver(req, row, admitted=False)
        for req in list(self._inflight):
            self._finish_error(req, "router_closed")
        for backend in self.backends.values():
            backend.close_conns()

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.close()

    # -- health probes (event-loop state machine) --

    def _probe_tick(self) -> None:
        """One probe pass: time out overdue probes, send fresh ones on
        the persistent per-backend probe connections."""
        if self._closing:
            return
        now = time.perf_counter()
        for backend in self.backends.values():
            if backend.probe_inflight:
                if now >= backend.probe_deadline:
                    self._probe_failed(backend, close_conn=True)
            else:
                self._probe_send(backend)
        self._probe_timer = self.loop.call_later(
            self.probe_interval_s, self._probe_tick
        )

    def _probe_send(self, backend: Backend) -> None:
        backend.probe_inflight = True
        backend.probe_deadline = (
            time.perf_counter() + self.probe_timeout_s
        )
        if backend.probe_conn is None:
            if backend.probe_abort is None:
                backend.probe_abort = connect_target(
                    self.loop, backend.socket_path, self.probe_timeout_s,
                    lambda sock, b=backend: self._probe_connected(b, sock),
                    lambda exc, b=backend: self._probe_conn_failed(b),
                )
            return
        try:
            backend.probe_conn.write_line('{"op": "stats"}')
        except OSError:
            self._probe_failed(backend, close_conn=True)

    def _probe_connected(self, backend: Backend, sock) -> None:
        backend.probe_abort = None
        backend.probe_conn = LineConn(
            self.loop, sock,
            on_line=lambda text, b=backend: self._probe_line(b, text),
            on_close=lambda reason, b=backend: self._probe_closed(b),
        )
        if backend.probe_inflight:
            try:
                backend.probe_conn.write_line('{"op": "stats"}')
            except OSError:
                self._probe_failed(backend, close_conn=True)

    def _probe_conn_failed(self, backend: Backend) -> None:
        backend.probe_abort = None
        if backend.probe_inflight:
            self._probe_failed(backend, close_conn=False)

    def _probe_closed(self, backend: Backend) -> None:
        backend.probe_conn = None
        if backend.probe_inflight:
            self._probe_failed(backend, close_conn=False)

    def _probe_line(self, backend: Backend, text: str) -> None:
        try:
            row = json.loads(text)
            stats = row.get("stats") or {}
            sched = stats.get("scheduler") or {}
            load = int(sched.get("queue_depth") or 0) + int(
                sched.get("in_flight") or 0
            )
        except (ValueError, TypeError, AttributeError):
            self._probe_failed(backend, close_conn=True)
            return
        backend.probe_inflight = False
        backend.probe_failures = 0
        backend.healthy = True
        backend.probed_load = load
        backend.last_stats = stats
        self._probe_round_done(backend)

    def _probe_failed(self, backend: Backend, close_conn: bool) -> None:
        backend.probe_inflight = False
        backend.probe_failures += 1
        backend.healthy = False
        if close_conn:
            if backend.probe_conn is not None:
                conn, backend.probe_conn = backend.probe_conn, None
                conn.on_close = drop_close
                conn.close("probe failed")
            if backend.probe_abort is not None:
                abort, backend.probe_abort = backend.probe_abort, None
                abort()
        self._probe_round_done(backend)

    def _probe_round_done(self, backend: Backend) -> None:
        backend.probe_rounds += 1
        if all(b.probe_rounds > 0 for b in self.backends.values()):
            self._first_probe_round.set()

    # -- dispatch decision (loop thread; public facade below) --

    def _pick(self, exclude=frozenset(), pool=None) -> str | None:
        # a single hand-rolled min pass: this runs once per request at
        # saturation, where two list comprehensions plus a keyed min
        # were measurable
        supervisor = self.supervisor
        best_name = None
        best_load = 0
        for name, b in self.backends.items():
            if name in exclude or not b.healthy:
                continue
            if pool is not None and b.pool != pool:
                # tenancy isolation: failover and hedging never leave
                # the request's pool — a worker on another corpus
                # fingerprint is not a replica, whatever its load
                continue
            if supervisor is not None and not supervisor.dispatchable(
                name
            ):
                continue
            load = b.probed_load + b.outstanding
            if (
                best_name is None
                or load < best_load
                or (load == best_load and name < best_name)
            ):
                best_name = name
                best_load = load
        return best_name

    def pick(self, exclude=frozenset(), pool=None) -> str | None:
        """The least-loaded healthy, non-draining worker outside
        ``exclude`` — the dispatch decision: the router's probed health
        view plus the supervisor's drain/stop veto (confined to
        ``pool`` on a multi-pool fleet)."""
        try:
            return self.loop.run_sync(self._pick, exclude, pool)
        except (LoopClosedError, TimeoutError):
            return self._pick(exclude, pool)

    # -- tenancy route table (written by ops threads; read per-request
    #    on the loop — replace-only dict updates, GIL-atomic) --

    def set_corpus_route(self, tag: str, pool: str) -> None:
        """Bind a corpus tag (tenant name, pool name, full or short
        fingerprint) to a pool; tagged rows and tenant-bound HTTP
        traffic route through this table."""
        self._corpus_routes[tag] = pool

    def drop_corpus_route(self, tag: str) -> None:
        self._corpus_routes.pop(tag, None)

    def set_pool_fingerprint(self, pool: str, fp: str | None) -> None:
        """The fingerprint responses from ``pool`` must stamp; a row
        answering with any other fingerprint is failed over inside the
        pool instead of ever reaching the client.  ``None`` disarms
        the fence (a mid-roll pool legitimately serves old AND new
        fingerprints until the roll completes)."""
        if fp is None:
            self._pool_fps.pop(pool, None)
        else:
            self._pool_fps[pool] = fp

    def pool_fingerprints(self) -> dict[str, str]:
        return dict(self._pool_fps)

    def resolve_pool(self, tag: str | None) -> str | None:
        """Corpus tag -> pool, or None for an unroutable tag (the
        default pool when untagged)."""
        if tag is None:
            return self.default_pool if self.pools_active else None
        return self._corpus_routes.get(tag)

    def outstanding(self, name: str | None = None) -> int:
        """Routed requests currently in flight (one worker, or all) —
        the supervisor's drain barrier reads this."""

        def _read() -> int:
            if name is not None:
                backend = self.backends.get(name)
                return backend.outstanding if backend is not None else 0
            return sum(b.outstanding for b in self.backends.values())

        try:
            return self.loop.run_sync(_read)
        except (LoopClosedError, TimeoutError):
            return _read()

    # -- the request state machine (loop thread) --

    def _submit(self, msg: dict | None, raw_line: str, on_done,
                pool: str | None = None) -> None:
        """Loop-thread entry: admit one routed request.  ``msg`` may be
        None (the front session's no-parse fast path); the request id
        is then recovered lazily, only on paths that need it.  ``pool``
        pins the request to one tenant pool (the HTTP edge resolves it
        from the bearer token's tenant binding); JSONL rows resolve
        their own ``"corpus"`` tag below."""
        self._counters["requests"] += 1
        # cross-tier trace ADOPTION: a line that already carries a
        # valid 16-hex trace (a FRONT router federating this one, or
        # any upstream hop) keeps it — this router re-minting would
        # break the upstream tier's pipelining cross-check AND split
        # the assembled telemetry tree at the host boundary.  Same
        # adoption grammar the worker applies (serve/server.py).
        adopted = None
        if '"trace"' in raw_line:
            if msg is None:
                # a line carrying "trace" anywhere must be PARSED: the
                # worker adopts the TOP-LEVEL field, and a textual scan
                # (json_str_field) cannot tell a nested occurrence
                # apart — adopting a value the worker will not echo
                # would burn the pipelined connection on every
                # cross-check.  Only trace-carrying lines pay this
                # parse; plain content rows keep the no-parse path.
                try:
                    parsed = json.loads(raw_line)
                    msg = parsed if isinstance(parsed, dict) else {}
                except ValueError:
                    msg = {}
            tid = msg.get("trace")
            if isinstance(tid, str) and _TRACE_ID_RE.match(tid):
                adopted = tid
        if self._mint_only:
            # head sampling is off: no Trace object can ever be
            # retained at start, so mint the wire-correlation ID from
            # the loop-owned counter and skip the tracer entirely
            trace = None
            if adopted is not None:
                wire_trace = adopted
            else:
                self._wire_seq += 1
                wire_trace = (
                    f"{(self._wire_base + self._wire_seq) & _WIRE_MASK:016x}"
                )
        else:
            if msg is None:
                try:
                    parsed = json.loads(raw_line)
                    msg = parsed if isinstance(parsed, dict) else {}
                except ValueError:
                    msg = {}
            trace = self.obs.tracer.start(msg.get("id"), trace_id=adopted)
            wire_trace = trace.trace_id if trace is not None else None
        if wire_trace is None or adopted is not None:
            # adopted: the line already carries this exact trace —
            # splicing would only duplicate it
            wire_line = raw_line
        else:
            # splice the minted trace into the raw line instead of
            # re-serializing the whole object (a dict copy + dumps per
            # request is measurable at saturation).  A client-supplied
            # "trace" key becomes a duplicate; JSON parsers take the
            # LAST occurrence, so the router's ID still wins — same
            # override {**msg, "trace": ...} used to perform.
            stripped = raw_line.rstrip()
            if stripped.endswith("}") and not stripped.endswith("{}"):
                wire_line = (
                    f'{stripped[:-1]},"trace":"{wire_trace}"}}'
                )
            else:
                wire_line = json.dumps(
                    {**(msg or {}), "trace": wire_trace}
                )
        if self.pools_active:
            if pool is None and '"corpus"' in raw_line:
                # corpus-tagged row: the tag must be PARSED for the
                # same reason a trace-carrying line is (a textual scan
                # cannot tell a nested occurrence apart) — only tagged
                # rows pay this parse, untagged traffic keeps the
                # no-parse fast path and lands on the default pool
                if msg is None:
                    try:
                        parsed = json.loads(raw_line)
                        msg = parsed if isinstance(parsed, dict) else {}
                    except ValueError:
                        msg = {}
                tag = msg.get("corpus")
                if isinstance(tag, str) and tag:
                    pool = self._corpus_routes.get(tag)
                    if pool is None:
                        self._counters["unknown_corpus"] += 1
                        if trace is not None:
                            self.obs.tracer.finish(
                                trace, "unknown_corpus"
                            )
                        req = _Request(
                            msg, wire_line, trace, wire_trace, on_done
                        )
                        self._deliver(req, {
                            "id": req.rid,
                            "error": f"unknown_corpus: no pool serves "
                            f"corpus tag {tag!r}",
                        }, admitted=False)
                        return
            if pool is None:
                pool = self.default_pool
        else:
            pool = None
        req = _Request(msg, wire_line, trace, wire_trace, on_done, pool)
        if self._closing:
            self._deliver(req, {"id": req.rid, "error": "router_closed"},
                          admitted=False)
            return
        if self._active >= self.max_concurrency:
            self._admission.append(req)
            return
        self._begin(req)

    def _begin(self, req: _Request) -> None:
        self._active += 1
        self._inflight.add(req)
        req.t0 = time.perf_counter()
        req.deadline = req.t0 + self.dispatch_wait_s
        self._dispatch_round(req)

    def _dispatch_round(self, req: _Request) -> None:
        req.repick_timer = None
        if req.finished:
            return
        if self._closing:
            self._finish_error(req, "router_closed")
            return
        now = time.perf_counter()
        if now >= req.deadline:
            self._finish_deadline(req)
            return
        name = self._pick(exclude=req.tried, pool=req.pool)
        if name is None:
            if req.queue_full_rows:
                # no untried replica left and at least one answered
                # queue_full: surface the backpressure NOW — the
                # client's retry_after backoff beats burning the
                # dispatch window hammering shedding workers
                self._finish_queue_full(req)
                return
            if req.tried:
                # every current backend failed this request; a restart
                # may bring one back before the deadline
                req.tried.clear()
            req.repick_timer = self.loop.call_later(
                _REPICK_DELAY_S, self._dispatch_round, req
            )
            return
        if not req.first_round:
            self._counters["retries"] += 1
        req.first_round = False
        self._send_arm(req, name, is_hedge=False)

    def _send_arm(self, req: _Request, name: str, is_hedge: bool) -> None:
        req.tried.add(name)
        backend = self.backends[name]
        if req.trace is not None:
            req.trace.add_span(
                "hedge" if is_hedge else "route", 0.0,
                note=f"to={name} load={backend.load()}",
            )
        attempt = _Attempt(req, backend, is_hedge)
        req.arms.append(attempt)
        backend.outstanding += 1
        backend.dispatched += 1
        # deadline BEFORE send: a synchronously-failing send resolves
        # the attempt re-entrantly; the periodic sweep only ever sees
        # unresolved FIFO entries, each already stamped
        attempt.deadline = time.perf_counter() + self.request_timeout_s
        backend.acquire_conn(self).send(attempt)
        if attempt.resolved or req.finished:
            return
        if not is_hedge and not req.hedge_started:
            delay = self._hedge_delay_s()
            if delay is not None:
                req.hedge_timer = self.loop.call_later(
                    delay, self._hedge_fire, req
                )

    def _arm_timeout_sweep(self) -> None:
        """(Re)arm the attempt-timeout sweep.  One periodic timer for
        the whole router replaces a ``call_later`` + ``cancel`` per
        request — at saturation that heap churn was one of the largest
        single per-request costs.  FIFO order makes the sweep O(pool):
        attempts on one connection share a timeout, so only each FIFO
        HEAD can be the oldest — precision is the sweep period (at most
        ``request_timeout_s/8``), fine for a seconds-scale backstop."""
        if self._closing:
            return
        period = max(0.05, min(self.request_timeout_s / 8.0, 0.5))
        self._timeout_sweep_timer = self.loop.call_later(
            period, self._timeout_sweep
        )

    def _timeout_sweep(self) -> None:
        now = time.perf_counter()
        for backend in self.backends.values():
            for conn in list(backend.conns):
                fifo = conn.fifo
                if fifo and fifo[0].deadline <= now:
                    self._attempt_timeout(fifo[0])
        self._arm_timeout_sweep()

    def _attempt_timeout(self, attempt: _Attempt) -> None:
        if attempt.resolved:
            return
        conn = attempt.conn
        if conn is not None and conn.state != "closed":
            # the connection is head-of-line blocked on this request:
            # closing it fails this attempt AND everything queued
            # behind it over to other replicas
            conn.close(
                f"request timeout after {self.request_timeout_s}s"
            )
        if not attempt.resolved:
            # belt and braces: an attempt must NEVER outlive its
            # deadline unresolved (a stranded request would hang its
            # client)
            self._attempt_resolved(
                attempt, "fail",
                f"{attempt.backend.name}: request timeout after "
                f"{self.request_timeout_s}s",
            )

    def _hedge_fire(self, req: _Request) -> None:
        req.hedge_timer = None
        if req.finished or self._closing:
            return
        second = self._pick(exclude=req.tried, pool=req.pool)
        if second is None:
            return
        self._counters["hedges_started"] += 1
        req.hedge_started = True
        self._send_arm(req, second, is_hedge=True)

    def _hedge_delay_s(self) -> float | None:
        """Seconds to wait before hedging, or None (hedging off / not
        enough samples yet for the auto p95)."""
        if self.hedge_ms is None or len(self.backends) < 2:
            return None
        if self.hedge_ms != "auto":
            return float(self.hedge_ms) / 1000.0
        # the auto p95 snapshot sorts the latency reservoir — too much
        # per-request work at saturation, so memoize for 50 ms
        now = time.perf_counter()
        cached = self._hedge_p95_cache
        if cached is not None and now - cached[0] < 0.05:
            return cached[1]
        snap = self._latency.snapshot()
        if (snap["count"] or 0) < self.hedge_min_samples:
            delay = None
        else:
            delay = max(snap["p95_ms"], self.hedge_floor_ms) / 1000.0
        self._hedge_p95_cache = (now, delay)
        return delay

    def _attempt_resolved(
        self, attempt: _Attempt, outcome: str, payload, text=None
    ) -> None:
        """One arm came back: a response row ("ok"/"queue_full") or a
        death ("fail", payload is the reason string).  ``text`` is the
        raw response line when one exists — the serialization fast path
        for front sessions."""
        if attempt.resolved:
            return
        attempt.resolved = True
        backend = attempt.backend
        backend.outstanding -= 1
        # tenancy's last line of defense: a row answering with the
        # wrong corpus fingerprint (a worker mid-roll, a stale pool)
        # must NEVER reach the client — fail it over inside the pool
        # like a dead backend.  The fence is read LIVE at completion
        # time (not captured at submit): an onboarding roll disarms/
        # re-arms it mid-flight, and a request admitted before the
        # roll must be judged against what the pool serves NOW, not
        # what it served when the request was queued.
        want_fp = (
            self._pool_fps.get(attempt.request.pool)
            if attempt.request.pool is not None else None
        )
        if (
            outcome == "ok"
            and want_fp is not None
            and not attempt.request.finished
        ):
            # fast path (payload is None): extract the system-minted
            # stamp textually, exactly like the HTTP edge's X-Corpus
            # echo
            got = (
                payload.get("corpus") if payload is not None
                else json_str_field(text, "corpus") if text is not None
                else None
            )
            if (
                isinstance(got, str) and got
                and not _fp_compatible(got, want_fp)
            ):
                self._bump_pool(attempt.request.pool, "corpus_mismatch")
                outcome = "fail"
                payload = (
                    f"corpus fingerprint mismatch (want "
                    f"{want_fp[:12]}, row stamps {got[:12]})"
                )
                text = None
        if outcome == "ok":
            backend.ok += 1
        elif outcome == "queue_full":
            backend.queue_full += 1
        else:
            backend.failed += 1
            backend.healthy = False  # fail fast until a probe clears it
        req = attempt.request
        if req.finished:
            return  # a hedge loser's late answer: discarded
        if outcome == "ok":
            self._finish_ok(req, payload, attempt, text)
            return
        if outcome == "queue_full":
            req.queue_full_rows.append(payload)
            self._counters["queue_full_failovers"] += 1
            if req.trace is not None:
                req.trace.add_span(
                    "failover", 0.0,
                    note=f"queue_full from {backend.name}",
                )
        else:
            req.last_reason = str(payload)
            self._counters["failovers"] += 1
            if req.trace is not None:
                req.trace.add_span(
                    "failover", 0.0,
                    note=f"{backend.name}: {req.last_reason[:120]}",
                )
        if any(not a.resolved for a in req.arms):
            return  # a twin is still racing: let it finish
        if req.hedge_timer is not None:
            req.hedge_timer.cancel()
            req.hedge_timer = None
        # every arm is dead: the next retry round starts from scratch
        # and may arm a FRESH hedge (the per-round racing semantics of
        # the old inline core) — the post-failover straggler window is
        # exactly where tail-cutting pays
        req.hedge_started = False
        self._dispatch_round(req)

    # -- finishing --

    def _finish_ok(self, req: _Request, payload: dict,
                   attempt: _Attempt, text=None) -> None:
        if req.hedge_started:
            self._counters[
                "hedges_won" if attempt.is_hedge else "hedges_lost"
            ] += 1
        dt = time.perf_counter() - req.t0
        self._latency.record(dt)
        # the wire trace ID rides as the histogram bucket's exemplar:
        # the exposition's slowest-bucket `# {trace_id="..."}` then
        # resolves via `traces --id` to this request's assembled tree
        self._latency_hist.observe(dt, exemplar=req.wire_trace)
        if req.pool is not None:
            pool_hist = self._pool_hists.get(req.pool)
            if pool_hist is not None:
                pool_hist.observe(dt)
            self._bump_pool(req.pool, "ok")
        self._counters["ok"] += 1
        if req.trace is not None:
            self.obs.tracer.finish(req.trace, "ok")
        elif self._mint_only and dt * 1000.0 >= self.obs.tracer.slow_ms:
            # no Trace object on the mint-only path — retain the slow
            # exemplar (span-less) from the measured latency instead
            self.obs.tracer.note_slow(
                req.wire_trace, req.rid, req.t0, dt
            )
        # the serialization fast path: splice "worker" into the raw
        # response line instead of parsing + re-dumping the row — front
        # sessions write this text verbatim, and the blocking
        # dispatch() facade parses it on ITS thread, never the loop.
        # ``payload is None`` marks the fast path (_on_line verified
        # the line carries id + matching trace and no error field).
        if payload is None:
            out_text = (
                f'{text[:-1]},"worker":"{attempt.backend.name}"}}'
            )
            self._deliver(req, None, out_text)
            return
        payload.setdefault("id", req.rid)
        payload["worker"] = attempt.backend.name
        out_text = None
        if (
            text is not None
            and text.endswith("}")
            and '"id"' in text
        ):
            out_text = (
                f'{text[:-1]},"worker":"{attempt.backend.name}"}}'
            )
        self._deliver(req, payload, out_text)

    def _finish_queue_full(self, req: _Request) -> None:
        self._counters["queue_full_returned"] += 1
        if req.trace is not None:
            self.obs.tracer.finish(req.trace, "queue_full")
        row = min(
            req.queue_full_rows,
            key=lambda r: r.get("retry_after") or float("inf"),
        )
        row.setdefault("id", req.rid)
        self._deliver(req, row)

    def _finish_deadline(self, req: _Request) -> None:
        if req.queue_full_rows:
            self._finish_queue_full(req)
            return
        self._counters["no_backend"] += 1
        if req.trace is not None:
            self.obs.tracer.finish(req.trace, "no_backend")
        row = {
            "id": req.rid,
            "error": f"no_backend_available: {req.last_reason}",
        }
        if req.wire_trace is not None:
            row["trace"] = req.wire_trace
        self._deliver(req, row)

    def _finish_error(self, req: _Request, error: str) -> None:
        row = {"id": req.rid, "error": error}
        if req.trace is not None:
            self.obs.tracer.finish(req.trace, error)
        if req.wire_trace is not None:
            row["trace"] = req.wire_trace
        self._deliver(req, row)

    def _deliver(self, req: _Request, row: dict, text=None,
                 admitted: bool = True) -> None:
        if req.finished:
            return
        req.finished = True
        for timer in (req.hedge_timer, req.repick_timer):
            if timer is not None:
                timer.cancel()
        req.hedge_timer = req.repick_timer = None
        if admitted:
            self._active -= 1
            self._inflight.discard(req)
            if not self._draining:
                # a synchronously-finishing _begin (shutdown, instant
                # error) re-enters _deliver; the guard leaves the drain
                # to the OUTERMOST frame so a deep admission backlog
                # cannot grow the stack
                self._draining = True
                try:
                    while (
                        self._admission
                        and self._active < self.max_concurrency
                    ):
                        self._begin(self._admission.popleft())
                finally:
                    self._draining = False
        try:
            req.on_done(row, text)
        except Exception:  # noqa: BLE001 — a dead client must not kill the loop
            pass

    # -- blocking facade (any thread) --

    def dispatch(self, msg: dict) -> dict:
        """Route one classification request and block for its row —
        the cross-thread facade over the event-loop state machine.
        Always returns a response row for the client."""
        if not self._started:
            # no loop thread exists to run the state machine — fail
            # fast instead of stalling out the dispatch budget
            return {"id": msg.get("id"), "error": "router_not_started"}
        done = threading.Event()
        box: dict = {}

        def on_done(row, text=None) -> None:
            # fast-path deliveries carry only the spliced line; the
            # parse happens HERE, on the caller's thread, not the loop
            box["row"] = row
            box["text"] = text
            done.set()

        raw_line = json.dumps(msg)
        if not self.loop.call_soon_threadsafe(
            self._submit, msg, raw_line, on_done
        ):
            return {"id": msg.get("id"), "error": "router_closed"}
        # the state machine always answers by dispatch deadline +
        # request timeout; the margin covers admission queueing
        budget = self.dispatch_wait_s + self.request_timeout_s + 60.0
        if not done.wait(budget):
            return {
                "id": msg.get("id"),
                "error": f"internal_error: dispatch stalled > {budget}s",
            }
        row = box["row"]
        if row is None:
            try:
                row = json.loads(box["text"])
            except ValueError:
                # a worker line that slipped the fast-path substring
                # heuristics but is not JSON: an error row, never an
                # exception out of the blocking facade
                row = {"id": msg.get("id"),
                       "error": "internal_error: unparseable worker "
                       "response"}
        return row

    # -- ops surface (front-socket verbs + CLI) --

    def stats(self) -> dict:
        def _snapshot() -> dict:
            return {
                "counters": dict(self._counters),
                "backends": {
                    name: b.as_dict()
                    for name, b in self.backends.items()
                },
                "active": self._active,
                "admission_queued": len(self._admission),
            }

        try:
            snap = self.loop.run_sync(_snapshot)
        except (LoopClosedError, TimeoutError):
            snap = _snapshot()
        backends = snap["backends"]
        host_health = None
        if self.supervisor is not None:
            sup = self.supervisor.status()
            for name, row in backends.items():
                row["supervisor"] = sup.get(name)
            host_health = self.supervisor.host_health()
        # domain load in WORKER-stats shape: a front router federating
        # this router over TCP probes it with the exact depth math it
        # uses on a worker (fleet/router._probe_line reads
        # stats.scheduler.queue_depth + in_flight) — queue_depth is the
        # admission backlog plus every probed/outstanding request in
        # the domain, in_flight is the router's active count
        domain_depth = snap["admission_queued"] + sum(
            (row["probed_load"] + row["outstanding"])
            for row in backends.values()
        )
        result = {
            "uptime_s": self.obs.uptime_s(),
            "scheduler": {
                "queue_depth": domain_depth,
                "in_flight": snap["active"],
                "completed": snap["counters"]["ok"],
            },
            "host": host_health,
            "router": {
                **snap["counters"],
                "latency_ms": self._latency.snapshot(),
                "hedge_ms": self.hedge_ms,
                "active": snap["active"],
                "admission_queued": snap["admission_queued"],
                "loop_lag_ms": self.loop.lag_ms(),
                "loop_max_lag_ms": self.loop.max_lag_ms(),
                "pool_per_worker": self.pool_per_worker,
            },
            "backends": backends,
            "tracing": self.obs.tracer.stats(),
            # the fleet SLO verdict (multi-window burn over the router
            # counters) + the trace collector's accounting
            "slo": self.slo.snapshot(),
            "collector": self.collector.stats(),
            # the retained telemetry plane: store occupancy, scrape
            # cadence health, and the watchdog's active-alert count
            # (full alert detail is the {"op": "alerts"} verb)
            "tsdb": {
                **self.store.stats(),
                "scrape": self.scraper.stats(),
            },
            "alerts": {
                "active": len(self.watchdog.active()),
                "fired_total": self.watchdog.snapshot()["fired_total"],
            },
        }
        if self.pools_active:
            pools: dict[str, dict] = {}
            for name, row in backends.items():
                pool = row.get("pool")
                if pool is None:
                    continue
                entry = pools.setdefault(
                    pool,
                    {"workers": [],
                     "fingerprint": self._pool_fps.get(pool)},
                )
                entry["workers"].append(name)
            result["tenancy"] = {
                "default_pool": self.default_pool,
                "pools": pools,
                "corpus_routes": len(self._corpus_routes),
                "events": {
                    f"{pool}:{event}": v
                    for (pool, event), v in sorted(
                        self._pool_counts.items()
                    )
                },
            }
        return result

    def prometheus(self) -> str:
        """The FLEET exposition: the router's own registry plus a live
        scrape of every backend's exposition, merged with one label per
        source (obs/export.py) — ``worker`` on a single-host fleet,
        ``host`` on the federation tier, where each backend's scrape is
        already worker-labeled and the merge nests host outside
        worker."""
        per_source = {"router": self.obs.prometheus()}
        for name, backend in self.backends.items():
            try:
                # a fleet scrape IS a synchronous fan-out by contract:
                # it runs on the ops executor (front sessions) or the
                # caller's thread (CLI), never on the event loop — the
                # cross-module walk proves no loop callback reaches
                # here (ops-executor thunks are not loop edges)
                row = oneshot(
                    backend.socket_path,
                    {"op": "stats", "format": "prometheus"},
                    self.probe_timeout_s,
                )
            except WireError:
                continue  # a dead worker exports nothing this scrape
            text = row.get("prometheus")
            if isinstance(text, str):
                per_source[name] = text
        return merge_expositions(per_source, label=self.merge_label)

    def trace_tail(self, n: int = 20) -> list[dict]:
        return self.obs.tracer.tail(n)

    def _pull_worker_tail(self, backend: Backend) -> list[dict]:
        """One worker's retained-trace tail for the collector; a dead
        or restarting worker contributes nothing this pull."""
        try:
            row = oneshot(
                backend.socket_path, {"op": "trace", "n": 200},
                self.probe_timeout_s,
            )
        except WireError:
            return []
        tail = row.get("traces")
        return tail if isinstance(tail, list) else []

    def assembled_traces(
        self, n: int = 20, *, trace_id: str | None = None
    ) -> list[dict]:
        """The cross-process telemetry view: pull every tail, join by
        trace ID, return assembled trees (slowest first) with
        critical-path self-times (the ``{"op": "traces"}`` front verb
        and the ``licensee-tpu traces`` CLI).  Blocking fan-out — ops
        executor or a caller thread, never the event loop."""
        self.collector.pull()
        return self.collector.assembled(n, trace_id=trace_id)

    def reload_fleet(self, corpus: str, pool: str | None = None) -> dict:
        """The front-door rolling corpus reload: delegates to the
        attached supervisor's health-gated, rollback-capable
        ``reload_fleet`` (fleet/supervisor.py) — one ops verb swaps the
        whole fleet with zero downtime.  On a multi-pool fleet
        (tenancy/pools.py) ``pool`` confines the roll to one tenant's
        workers; the other pools keep serving untouched."""
        if self.supervisor is None:
            raise RuntimeError(
                "no supervisor attached; reload workers directly"
            )
        if pool is not None:
            return self.supervisor.reload_fleet(corpus, pool=pool)
        return self.supervisor.reload_fleet(corpus)



# front-session inbound flow control: above HIGH queued response slots
# the client socket read pauses (the kernel buffer then pushes back on
# an open-loop client outrunning the fleet), resuming below LOW
_SESSION_HIGH = 1024
_SESSION_LOW = 256


class _FrontSession:
    """One client session on the front socket, entirely on the router's
    event loop: parse lines, dispatch concurrently, answer IN REQUEST
    ORDER (same contract as a worker session, so clients cannot tell a
    router from a worker).

    Each request occupies one slot in an ordered queue; content rows
    dispatch immediately and fill their slot whenever they finish,
    while ops verbs (stats/trace/prometheus/reload) start only when
    their slot reaches the HEAD — so a stats row reports "as of this
    point in the session", exactly like the old writer thread."""

    def __init__(self, router: Router, server: "FrontServer",
                 conn: LineConn):
        self.router = router
        self.server = server
        self.conn = conn
        self.slots: deque[dict] = deque()
        self.paused = False
        conn.on_line = self.handle_line
        conn.on_close = self._on_close

    def _on_close(self, _reason) -> None:
        self.server.forget_connection(self.conn)
        self.slots.clear()  # in-flight fills find no slot: dropped

    def _push(self, kind: str, payload=None, row=None) -> None:
        self.slots.append(
            {"kind": kind, "payload": payload, "row": row,
             "text": None, "started": False}
        )
        if not self.paused and len(self.slots) > _SESSION_HIGH:
            self.paused = True
            self.conn.pause_reading()
        self._flush()

    def _submit_content(self, line: str, msg: dict | None = None) -> None:
        """Queue a content row's slot and dispatch it — unlike _push
        the slot is born started (routing begins now, not at the head)
        and nothing flushes until the dispatch fills it."""
        slot = {"kind": "content", "payload": None, "row": None,
                "text": None, "started": True}
        self.slots.append(slot)
        if not self.paused and len(self.slots) > _SESSION_HIGH:
            self.paused = True
            self.conn.pause_reading()
        self.router._submit(
            msg, line,
            lambda row, text=None, s=slot: self._fill(s, row, text),
        )

    def _fill(self, slot: dict, row: dict, text=None) -> None:
        slot["row"] = row
        slot["text"] = text
        self._flush()

    def _flush(self) -> None:
        """Write every ready head slot; start the head ops verb when it
        surfaces.  Iterative (never recursive): a burst of inline ops
        verbs must not grow the stack."""
        while self.slots:
            head = self.slots[0]
            if head["row"] is None and head["text"] is None:
                if not head["started"]:
                    self._start_op(head)  # inline ops fill head now
                if head["row"] is None and head["text"] is None:
                    return  # waiting on a dispatch or a deferred op
            self.slots.popleft()
            try:
                # the router spliced a ready-to-write line for routed
                # content rows; ops verbs and error rows serialize here
                self.conn.write_line_on_loop(
                    head["text"] or json.dumps(head["row"])
                )
            except OSError:
                return  # client went away; _on_close drops the rest
            if self.paused and len(self.slots) < _SESSION_LOW:
                self.paused = False
                self.conn.resume_reading()

    def handle_line(self, line: str) -> None:
        line = line.strip()
        if not line:
            return
        if (
            '"op"' not in line
            and line.startswith("{")
            and line.endswith("}")
        ):
            # content-row fast path: without the '"op"' substring the
            # line cannot carry an ops verb, so skip the parse entirely
            # — the WORKER validates the payload anyway (one validator,
            # serve/server.py), including lines that turn out to be
            # malformed JSON.  At saturation the per-request
            # ``json.loads`` here was the single largest loop cost.
            self._submit_content(line)
            return
        try:
            msg = json.loads(line)
            if not isinstance(msg, dict):
                raise ValueError("request must be a JSON object")
        except ValueError as exc:
            self._push("raw",
                       row={"id": None, "error": f"bad_request: {exc}"})
            return
        rid = msg.get("id")
        op = msg.get("op")
        if op is None:
            # content row: the WORKER validates the payload (one
            # validator, serve/server.py) — the router only owns routing
            self._submit_content(line, msg)
            return
        if op == "diff":
            # the word-diff verb is stateless, idempotent, and
            # answered by any worker from its serving corpus — relay
            # it exactly like a content row (the WORKER validates the
            # payload and echoes the spliced trace; failover/hedging
            # semantics apply unchanged)
            self._submit_content(line, msg)
            return
        if op == "stats":
            fmt = msg.get("format")
            if fmt not in (None, "json", "prometheus"):
                self._push("raw", row={
                    "id": rid,
                    "error": f"bad_request: unknown stats format {fmt!r}",
                })
            else:
                self._push(
                    "prometheus" if fmt == "prometheus" else "stats", rid
                )
        elif op == "trace":
            n = msg.get("n", 20)
            if isinstance(n, bool) or not isinstance(n, int) or n < 0:
                self._push("raw", row={
                    "id": rid,
                    "error": "bad_request: n must be a non-negative int",
                })
            else:
                self._push("trace", (rid, n))
        elif op == "traces":
            # the telemetry plane: assembled cross-process trace trees
            # (router tail + every worker tail joined by trace ID)
            n = msg.get("n", 20)
            tid = msg.get("trace_id")
            if isinstance(n, bool) or not isinstance(n, int) or n < 0:
                self._push("raw", row={
                    "id": rid,
                    "error": "bad_request: n must be a non-negative int",
                })
            elif tid is not None and not isinstance(tid, str):
                self._push("raw", row={
                    "id": rid,
                    "error": "bad_request: trace_id must be a hex "
                    "string prefix",
                })
            else:
                self._push("traces", (rid, n, tid))
        elif op == "reload":
            corpus = msg.get("corpus")
            pool = msg.get("pool")
            if not isinstance(corpus, str) or not corpus:
                self._push("raw", row={
                    "id": rid,
                    "error": "bad_request: reload needs a 'corpus' "
                    "source string",
                })
            elif pool is not None and (
                not isinstance(pool, str) or not pool
            ):
                self._push("raw", row={
                    "id": rid,
                    "error": "bad_request: reload 'pool' must be a "
                    "pool name string",
                })
            else:
                self._push("reload", (rid, corpus, pool))
        elif op == "query":
            # the telemetry-store verb: server-side rate/delta/quantile
            # over retained series (obs/tsdb.py) — param validation is
            # the store's (QueryError carries the wire error code)
            params = {
                k: v for k, v in msg.items() if k not in ("op", "id")
            }
            self._push("query", (rid, params))
        elif op == "alerts":
            self._push("alerts", rid)
        else:
            self._push("raw", row={
                "id": rid, "error": f"bad_request: unknown op {op!r}",
            })

    def _start_op(self, slot: dict) -> None:
        """Run the head slot's ops verb — it starts only once every
        earlier response has been written, so a stats row reports "as
        of this point in the session".  Cheap loop-state snapshots
        (trace) run inline; stats (supervisor lock), the fan-out
        scrape, and the rolling reload can block and go to the ops
        executor, filling their slot back via
        ``call_soon_threadsafe``."""
        slot["started"] = True
        kind = slot["kind"]
        if kind == "stats":
            rid = slot["payload"]
            # stats consults the supervisor, whose lock the monitor
            # thread holds ACROSS a worker respawn's fork+exec — a
            # stats verb landing in that window must wait on the ops
            # executor, never on the loop thread
            self._defer(slot, lambda: {
                "id": rid, "stats": self.router.stats()
            })
        elif kind == "trace":
            rid, n = slot["payload"]
            slot["row"] = {
                "id": rid, "traces": self.router.trace_tail(n)
            }
        elif kind == "traces":
            # the assembled-tree verb pulls every worker tail — a
            # blocking fan-out, ops executor only (like the scrape)
            rid, n, tid = slot["payload"]
            self._defer(slot, lambda: {
                "id": rid,
                "traces": self.router.assembled_traces(
                    n, trace_id=tid
                ),
            })
        elif kind == "prometheus":
            rid = slot["payload"]
            self._defer(slot, lambda: {
                "id": rid, "prometheus": self.router.prometheus()
            })
        elif kind == "reload":
            rid, corpus, pool = slot["payload"]

            def run_reload() -> dict:
                try:
                    return {"id": rid,
                            "reload": self.router.reload_fleet(
                                corpus, pool=pool
                            )}
                except Exception as exc:  # noqa: BLE001 — session containment
                    return {"id": rid, "error": f"reload_failed: {exc}"}

            self._defer(slot, run_reload)
        elif kind == "query":
            rid, params = slot["payload"]

            def run_query() -> dict:
                row = {"id": rid}
                try:
                    row["query"] = self.router.store.query(params)
                except QueryError as exc:
                    if exc.code == "unknown_series":
                        row["error"] = f"unknown_series: {exc}"
                    else:
                        row["error"] = f"bad_request: {exc}"
                return row

            self._defer(slot, run_query)
        elif kind == "alerts":
            rid = slot["payload"]

            def run_alerts() -> dict:
                row = {"id": rid}
                row["alerts"] = self.router.watchdog.snapshot()
                return row

            self._defer(slot, run_alerts)

    def _defer(self, slot: dict, fn) -> None:
        loop = self.router.loop

        def run() -> None:
            try:
                row = fn()
            except Exception as exc:  # noqa: BLE001 — session containment
                row = {"id": None, "error": f"internal_error: {exc}"}
            loop.call_soon_threadsafe(self._fill, slot, row)

        self.router._ops.submit(run)


class FrontServer(LoopJsonlServer):
    """The client-facing Unix socket: one JSONL session per connection,
    all sharing one router AND its event loop — accepts, reads, writes,
    dispatch, and slowloris reaping are all callbacks on the router's
    single loop thread."""

    def __init__(self, path: str, router: Router,
                 stall_timeout_s: float = 30.0):
        self.router = router
        router.loop.start()  # idempotent; the loop must carry accepts
        super().__init__(
            path, loop=router.loop, stall_timeout_s=stall_timeout_s
        )

    def handle_connection(self, sock) -> None:
        conn = LineConn(
            self.loop, sock, on_line=drop_line, on_close=drop_close
        )
        self.track_connection(conn)
        _FrontSession(self.router, self, conn)

