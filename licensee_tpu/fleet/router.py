"""The fleet router: one client-facing socket fronting N serve workers
— least-loaded dispatch, health-checked failover, backpressure-aware
retries, and tail-cutting hedged requests.

Dean & Barroso's "The Tail at Scale" is the playbook:

* **least-loaded routing** — each request goes to the healthy,
  non-draining worker with the lowest load score (probed
  ``queue_depth + in_flight`` plus the router's own outstanding count
  for that worker; the local term keeps bursts spread even between
  probe rounds).
* **failover on death** — classification requests are pure functions
  of content (the content-hash cache key IS the idempotency proof), so
  a request whose worker dies mid-flight is simply retried on another
  replica.  The client sees one answer, never a connection reset.
* **backpressure failover** — a worker answering ``queue_full`` sheds
  load; the router tries the next replica and only surfaces
  ``queue_full`` (with the smallest ``retry_after``) when EVERY
  replica is shedding.
* **hedged requests** — optionally, a duplicate is sent to a second
  worker once the first has been out longer than the observed p95
  (``hedge_ms="auto"``) or a fixed delay; the first answer wins.  The
  duplicate costs the twin a device slot only for content it has never
  seen: a blob already cached or in flight there coalesces via the
  content-hash key (ResultCache/MicroBatcher), and otherwise the extra
  load is bounded by the hedge rate (~5% at a p95-derived delay).  The
  loser's late answer is discarded and its connection recycled.

Trace IDs are minted HERE and forwarded on the wire (``"trace"``
field); the worker adopts the ID (obs/tracing.py), so the router tail
shows ``route``/``hedge``/``failover`` spans and the worker tail shows
the serving spans — same 16-hex handle end to end.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from queue import Empty, SimpleQueue

from licensee_tpu.fleet.wire import ConnectionPool, WireError, oneshot
from licensee_tpu.obs import Observability, merge_expositions
from licensee_tpu.serve.server import JsonlUnixServer
from licensee_tpu.serve.stats import LatencyStats


class Backend:
    """The router's view of one worker: socket, pool, probed load, and
    per-backend counters."""

    def __init__(self, name: str, socket_path: str, probe_timeout_s: float):
        self.name = name
        self.socket_path = socket_path
        self.pool = ConnectionPool(
            socket_path, connect_timeout=probe_timeout_s
        )
        self.healthy = False
        self.probed_load = 0
        self.probe_failures = 0
        self.outstanding = 0  # routed requests in flight right now
        self.dispatched = 0
        self.ok = 0
        self.failed = 0
        self.queue_full = 0
        self.last_stats: dict = {}

    def load(self) -> int:
        return self.probed_load + self.outstanding

    def as_dict(self) -> dict:
        return {
            "socket": self.socket_path,
            "healthy": self.healthy,
            "probed_load": self.probed_load,
            "outstanding": self.outstanding,
            "dispatched": self.dispatched,
            "ok": self.ok,
            "failed": self.failed,
            "queue_full": self.queue_full,
        }


class Router:
    """Dispatch requests across the worker fleet; serve the front
    socket.

    ``backends`` maps worker name -> socket path.  ``supervisor`` is
    optional: when given, its draining/stopped flags veto dispatch (the
    drain protocol) and the supervisor reads ``outstanding()`` back.
    ``hedge_ms`` is ``None``/"off" (no hedging), a number (fixed delay
    in ms), or "auto" (the p95 of recent request latencies, refreshed
    per dispatch, floored at ``hedge_floor_ms``)."""

    def __init__(
        self,
        backends: dict[str, str],
        *,
        supervisor=None,
        probe_interval_s: float = 0.25,
        probe_timeout_s: float = 2.0,
        request_timeout_s: float = 30.0,
        dispatch_wait_s: float = 15.0,
        hedge_ms=None,
        hedge_floor_ms: float = 5.0,
        hedge_min_samples: int = 20,
        max_concurrency: int = 64,
        registry=None,
        tracing: bool = True,
        trace_sample: float = 0.01,
        trace_slow_ms: float = 250.0,
    ):
        if not backends:
            raise ValueError("need at least one backend")
        if hedge_ms in ("off", "none"):
            hedge_ms = None
        if hedge_ms is not None and hedge_ms != "auto":
            hedge_ms = float(hedge_ms)
            if not (hedge_ms >= 0):
                raise ValueError(f"hedge_ms must be >= 0, got {hedge_ms!r}")
        self.hedge_ms = hedge_ms
        self.hedge_floor_ms = float(hedge_floor_ms)
        self.hedge_min_samples = int(hedge_min_samples)
        self.supervisor = supervisor
        if supervisor is not None:
            supervisor.router = self
        self.probe_interval_s = float(probe_interval_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.request_timeout_s = float(request_timeout_s)
        self.dispatch_wait_s = float(dispatch_wait_s)
        self.backends: dict[str, Backend] = {
            name: Backend(name, path, probe_timeout_s)
            for name, path in backends.items()
        }
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._probe_thread: threading.Thread | None = None
        self._latency = LatencyStats(capacity=1024)
        self._counters = {
            "requests": 0,
            "ok": 0,
            "failovers": 0,
            "retries": 0,
            "hedges_started": 0,
            "hedges_won": 0,
            "hedges_lost": 0,
            "queue_full_failovers": 0,
            "queue_full_returned": 0,
            "no_backend": 0,
        }
        self.obs = Observability(
            registry,
            tracing=tracing,
            trace_sample=trace_sample,
            trace_slow_ms=trace_slow_ms,
        )
        self._executor = ThreadPoolExecutor(
            max_workers=int(max_concurrency),
            thread_name_prefix="fleet-dispatch",
        )
        self._register_metrics()

    # -- metrics --

    def _register_metrics(self) -> None:
        reg = self.obs.registry
        reg.gauge(
            "fleet_backends_healthy",
            "Workers currently answering health probes",
        ).set_fn(
            lambda: sum(1 for b in self.backends.values() if b.healthy)
        )
        reg.gauge(
            "fleet_backends_total", "Workers configured behind the router"
        ).set(len(self.backends))
        reg.gauge(
            "fleet_outstanding",
            "Routed requests in flight across all workers",
        ).set_fn(
            lambda: sum(b.outstanding for b in self.backends.values())
        )
        events = reg.counter(
            "fleet_requests_total",
            "Router lifecycle events by kind (requests, ok, failovers, "
            "retries, hedges_started, hedges_won, hedges_lost, "
            "queue_full_failovers, queue_full_returned, no_backend)",
            labels=("event",),
        )
        # labeled "backend", not "worker": the fleet scrape merges this
        # registry under an injected worker="router" label, and a
        # sample carrying its own "worker" label would emit a duplicate
        # label name — which a real Prometheus server rejects
        per_worker = reg.counter(
            "fleet_backend_requests_total",
            "Routed requests by backend worker and outcome",
            labels=("backend", "outcome"),
        )
        hist = reg.histogram(
            "fleet_request_seconds",
            "Client-visible routed request latency (retries and hedges "
            "included)",
        )
        self._latency_hist = hist

        def collect(_reg) -> None:
            with self._lock:
                counters = dict(self._counters)
                rows = [
                    (b.name, b.ok, b.failed, b.queue_full)
                    for b in self.backends.values()
                ]
            for k, v in counters.items():
                events.labels(event=k).sync(v)
            for name, ok, failed, qf in rows:
                per_worker.labels(backend=name, outcome="ok").sync(ok)
                per_worker.labels(backend=name, outcome="failed").sync(
                    failed
                )
                per_worker.labels(backend=name, outcome="queue_full").sync(
                    qf
                )

        reg.add_collector(collect)

    # -- lifecycle --

    def start(self) -> None:
        self.probe_all()  # synchronous first round: pick() works now
        if self._probe_thread is None:
            self._probe_thread = threading.Thread(
                target=self._probe_loop, name="fleet-prober", daemon=True
            )
            self._probe_thread.start()

    def close(self) -> None:
        self._stop.set()
        if self._probe_thread is not None:
            self._probe_thread.join()
            self._probe_thread = None
        self._executor.shutdown(wait=False)
        for backend in self.backends.values():
            backend.pool.close()

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.close()

    # -- health probes --

    def _probe_loop(self) -> None:
        while not self._stop.wait(self.probe_interval_s):
            self.probe_all()

    def probe_all(self) -> None:
        for backend in self.backends.values():
            self._probe(backend)

    def _probe(self, backend: Backend) -> None:
        try:
            # the probe performs its blocking round trip BY DESIGN, on
            # the dedicated prober thread — never on a session/dispatch
            # thread; the handler-path walk reaches it only through
            # coarse name-based call matching
            # analysis: disable=blocking-call
            row = oneshot(
                backend.socket_path, {"op": "stats"}, self.probe_timeout_s
            )
            stats = row.get("stats") or {}
            sched = stats.get("scheduler") or {}
            load = int(sched.get("queue_depth") or 0) + int(
                sched.get("in_flight") or 0
            )
        except (WireError, TypeError, ValueError):
            with self._lock:
                backend.probe_failures += 1
                backend.healthy = False
            return
        with self._lock:
            backend.probe_failures = 0
            backend.healthy = True
            backend.probed_load = load
            backend.last_stats = stats

    # -- dispatch --

    def pick(self, exclude=frozenset()) -> str | None:
        """The least-loaded healthy, non-draining worker outside
        ``exclude`` — the dispatch decision: the router's probed health
        view (read under the lock) plus the supervisor's drain/stop
        veto."""
        with self._lock:
            candidates = [
                b
                for name, b in self.backends.items()
                if name not in exclude and b.healthy
            ]
        # health was just read under the lock; only the supervisor's
        # drain/stop veto remains (dispatchable() would re-take the
        # lock per candidate to re-read the same flag)
        supervisor = self.supervisor
        if supervisor is not None:
            candidates = [
                b for b in candidates if supervisor.dispatchable(b.name)
            ]
        if not candidates:
            return None
        return min(candidates, key=lambda b: (b.load(), b.name)).name

    def outstanding(self, name: str | None = None) -> int:
        """Routed requests currently in flight (one worker, or all) —
        the supervisor's drain barrier reads this."""
        with self._lock:
            if name is not None:
                backend = self.backends.get(name)
                return backend.outstanding if backend is not None else 0
            return sum(b.outstanding for b in self.backends.values())

    def _attempt(self, backend: Backend, line: str):
        """One request/response round trip against one worker.
        Returns ("ok" | "queue_full" | "fail", row_or_reason, dt_s)."""
        t0 = time.perf_counter()
        with self._lock:
            backend.outstanding += 1
            backend.dispatched += 1
        try:
            conn = backend.pool.checkout()
            try:
                row = conn.request(line, self.request_timeout_s)
            except WireError:
                backend.pool.discard(conn)
                raise
            backend.pool.checkin(conn)
        except WireError as exc:
            with self._lock:
                backend.outstanding -= 1
                backend.failed += 1
                backend.healthy = False  # fail fast until a probe clears it
            return ("fail", str(exc), time.perf_counter() - t0)
        dt = time.perf_counter() - t0
        with self._lock:
            backend.outstanding -= 1
            if row.get("error") == "queue_full":
                backend.queue_full += 1
                return ("queue_full", row, dt)
            backend.ok += 1
        return ("ok", row, dt)

    def _hedge_delay_s(self) -> float | None:
        """Seconds to wait before hedging, or None (hedging off / not
        enough samples yet for the auto p95)."""
        if self.hedge_ms is None or len(self.backends) < 2:
            return None
        if self.hedge_ms != "auto":
            return float(self.hedge_ms) / 1000.0
        snap = self._latency.snapshot()
        if (snap["count"] or 0) < self.hedge_min_samples:
            return None
        return max(snap["p95_ms"], self.hedge_floor_ms) / 1000.0

    def dispatch(self, msg: dict) -> dict:
        """Route one classification request: pick, attempt (maybe
        hedged), fail over on death/backpressure.  Always returns a
        response row for the client."""
        t0 = time.perf_counter()
        rid = msg.get("id")
        trace = self.obs.tracer.start(rid)
        wire_msg = dict(msg)
        if trace is not None:
            wire_msg["trace"] = trace.trace_id
        line = json.dumps(wire_msg)
        with self._lock:
            self._counters["requests"] += 1
        tried: set[str] = set()
        queue_full_rows: list[dict] = []
        last_reason = "no healthy backend"
        deadline = t0 + self.dispatch_wait_s
        first_round = True
        while time.perf_counter() < deadline:
            name = self.pick(exclude=tried)
            if name is None:
                if queue_full_rows:
                    # no untried replica left and at least one answered
                    # queue_full: surface the backpressure NOW — the
                    # client's retry_after backoff beats burning the
                    # dispatch window hammering shedding workers
                    break
                if tried:
                    # every current backend failed this request; a
                    # restart may bring one back before the deadline
                    tried = set()
                # bounded 50 ms poll while the whole fleet is down —
                # the asyncio router core replaces this parked thread
                # with a timer wakeup (ROADMAP: async I/O core)
                # analysis: disable=blocking-call
                time.sleep(0.05)
                continue
            if not first_round:
                with self._lock:
                    self._counters["retries"] += 1
            first_round = False
            outcome, payload, winner = self._race(name, line, trace, tried)
            if outcome == "ok":
                dt = time.perf_counter() - t0
                self._latency.record(dt)
                self._latency_hist.observe(dt)
                with self._lock:
                    self._counters["ok"] += 1
                if trace is not None:
                    self.obs.tracer.finish(trace, "ok")
                payload.setdefault("id", rid)
                payload["worker"] = winner
                return payload
            if outcome == "queue_full":
                queue_full_rows.append(payload)
                with self._lock:
                    self._counters["queue_full_failovers"] += 1
                if trace is not None:
                    trace.add_span(
                        "failover", 0.0, note=f"queue_full from {winner}"
                    )
                continue
            # death/timeout: retry elsewhere — content requests are
            # idempotent by construction (pure function of content)
            last_reason = str(payload)
            with self._lock:
                self._counters["failovers"] += 1
            if trace is not None:
                trace.add_span(
                    "failover", 0.0, note=f"{winner}: {last_reason[:120]}"
                )
        if queue_full_rows:
            with self._lock:
                self._counters["queue_full_returned"] += 1
            if trace is not None:
                self.obs.tracer.finish(trace, "queue_full")
            row = min(
                queue_full_rows,
                key=lambda r: r.get("retry_after") or float("inf"),
            )
            row.setdefault("id", rid)
            return row
        with self._lock:
            self._counters["no_backend"] += 1
        if trace is not None:
            self.obs.tracer.finish(trace, "no_backend")
        row = {"id": rid, "error": f"no_backend_available: {last_reason}"}
        if trace is not None:
            row["trace"] = trace.trace_id
        return row

    def _race(self, first: str, line: str, trace, tried: set):
        """One dispatch round: the primary attempt plus, after the
        hedge delay, an optional duplicate on a second worker.  First
        answer wins; a failed arm waits for its twin before the round
        reports failure.  Returns (outcome, payload, worker_name)."""
        tried.add(first)
        if trace is not None:
            trace.add_span(
                "route", 0.0,
                note=f"to={first} load={self.backends[first].load()}",
            )
        hedge_delay = self._hedge_delay_s()
        if hedge_delay is None:
            # no hedge possible this round: run the attempt on the
            # caller's thread — a thread spawn + queue handoff per
            # request is pure overhead when nothing races
            outcome, payload, _dt = self._attempt(
                self.backends[first], line
            )
            return (outcome, payload, first)
        results: SimpleQueue = SimpleQueue()

        # arms run on fresh daemon threads, deliberately NOT on
        # self._executor: an arm can block up to request_timeout_s on a
        # wedged worker, and a bounded shared pool would let a few
        # stuck arms head-of-line-block every new session dispatch —
        # the per-spawn cost is paid only on hedge-capable rounds
        def run(name: str) -> None:
            results.put((name, self._attempt(self.backends[name], line)))

        threading.Thread(
            target=run, args=(first,), daemon=True,
            name=f"fleet-attempt-{first}",
        ).start()
        arms = [first]
        start = time.perf_counter()
        hedge_at = start + hedge_delay
        deadline = start + self.request_timeout_s + 1.0
        seen: dict[str, tuple] = {}
        while time.perf_counter() < deadline:
            now = time.perf_counter()
            # clamp: the clock may cross `deadline` between the loop
            # check and here, and a negative timeout raises ValueError
            wait = max(deadline - now, 0.0)
            if hedge_at is not None:
                wait = min(wait, max(hedge_at - now, 0.0) + 1e-4)
            try:
                name, res = results.get(timeout=wait)
            except Empty:
                name = None
            if name is None:
                if hedge_at is not None and time.perf_counter() >= hedge_at:
                    hedge_at = None
                    second = self.pick(exclude=tried)
                    if second is not None:
                        tried.add(second)
                        arms.append(second)
                        with self._lock:
                            self._counters["hedges_started"] += 1
                        if trace is not None:
                            trace.add_span(
                                "hedge", 0.0, note=f"to={second}"
                            )
                        threading.Thread(
                            target=run, args=(second,), daemon=True,
                            name=f"fleet-hedge-{second}",
                        ).start()
                continue
            outcome, payload, _dt = res
            seen[name] = res
            if outcome == "ok":
                if len(arms) == 2:
                    won_by_hedge = name == arms[1]
                    with self._lock:
                        self._counters[
                            "hedges_won" if won_by_hedge else "hedges_lost"
                        ] += 1
                return ("ok", payload, name)
            if len(seen) < len(arms):
                continue  # a twin is still racing: let it finish
            # every arm answered without a verdict: report the least
            # severe outcome (queue_full beats a dead connection — the
            # client can at least back off)
            for arm_name, (arm_outcome, arm_payload, _d) in seen.items():
                if arm_outcome == "queue_full":
                    return ("queue_full", arm_payload, arm_name)
            return (outcome, payload, name)
        return ("fail", f"race timeout after {self.request_timeout_s}s",
                first)

    # -- ops surface (front-socket verbs + CLI) --

    def stats(self) -> dict:
        with self._lock:
            counters = dict(self._counters)
            backends = {
                name: b.as_dict() for name, b in self.backends.items()
            }
        if self.supervisor is not None:
            sup = self.supervisor.status()
            for name, row in backends.items():
                row["supervisor"] = sup.get(name)
        return {
            "uptime_s": self.obs.uptime_s(),
            "router": {
                **counters,
                "latency_ms": self._latency.snapshot(),
                "hedge_ms": self.hedge_ms,
            },
            "backends": backends,
            "tracing": self.obs.tracer.stats(),
        }

    def prometheus(self) -> str:
        """The FLEET exposition: the router's own registry plus a live
        scrape of every healthy worker's exposition, merged with a
        ``worker`` label per source (obs/export.py)."""
        per_source = {"router": self.obs.prometheus()}
        for name, backend in self.backends.items():
            try:
                # a fleet scrape IS a synchronous fan-out by contract:
                # it runs on the stats verb's session writer thread and
                # tolerates probe_timeout_s per worker; the async core
                # will pipeline these round trips
                # analysis: disable=blocking-call
                row = oneshot(
                    backend.socket_path,
                    {"op": "stats", "format": "prometheus"},
                    self.probe_timeout_s,
                )
            except WireError:
                continue  # a dead worker exports nothing this scrape
            text = row.get("prometheus")
            if isinstance(text, str):
                per_source[name] = text
        return merge_expositions(per_source)

    def trace_tail(self, n: int = 20) -> list[dict]:
        return self.obs.tracer.tail(n)

    def reload_fleet(self, corpus: str) -> dict:
        """The front-door rolling corpus reload: delegates to the
        attached supervisor's health-gated, rollback-capable
        ``reload_fleet`` (fleet/supervisor.py) — one ops verb swaps the
        whole fleet with zero downtime."""
        if self.supervisor is None:
            raise RuntimeError(
                "no supervisor attached; reload workers directly"
            )
        return self.supervisor.reload_fleet(corpus)


class _RouterSession:
    """One client session on the front socket: parse lines, dispatch
    concurrently, answer IN REQUEST ORDER (same contract as a worker
    session, so clients cannot tell a router from a worker)."""

    def __init__(self, router: Router, write_line):
        self.router = router
        self._write_line = write_line
        self._pending: deque = deque()  # ("fut", Future) | ("op", ...)
        self._cond = threading.Condition()
        self._closed = False
        self.requests = 0
        self.responses = 0
        self._writer = threading.Thread(
            target=self._drain, name="fleet-writer", daemon=True
        )
        self._writer.start()

    def _emit(self, kind, payload) -> None:
        with self._cond:
            self._pending.append((kind, payload))
            self._cond.notify_all()

    def _drain(self) -> None:
        while True:
            with self._cond:
                while not self._pending and not self._closed:
                    self._cond.wait()
                if not self._pending and self._closed:
                    return
                kind, payload = self._pending.popleft()
            if kind == "fut":
                try:
                    row = payload.result()
                except Exception as exc:  # noqa: BLE001 — session containment
                    row = {"id": None, "error": f"internal_error: {exc}"}
            elif kind == "stats":
                rid, fmt = payload
                if fmt == "prometheus":
                    row = {"id": rid,
                           "prometheus": self.router.prometheus()}
                else:
                    row = {"id": rid, "stats": self.router.stats()}
            elif kind == "trace":
                rid, n = payload
                row = {"id": rid, "traces": self.router.trace_tail(n)}
            elif kind == "reload":
                rid, corpus = payload
                try:
                    # a fleet reload IS a long synchronous ops verb by
                    # contract: it runs on this session's writer thread
                    # (same as the prometheus fan-out scrape) and holds
                    # only this session's response stream, never the
                    # dispatch path
                    row = {"id": rid,
                           "reload": self.router.reload_fleet(corpus)}
                except Exception as exc:  # noqa: BLE001 — session containment
                    row = {"id": rid, "error": f"reload_failed: {exc}"}
            else:
                row = payload
            try:
                self._write_line(json.dumps(row))
            except (OSError, ValueError):
                return
            self.responses += 1

    def handle_line(self, line: str) -> None:
        line = line.strip()
        if not line:
            return
        self.requests += 1
        try:
            msg = json.loads(line)
            if not isinstance(msg, dict):
                raise ValueError("request must be a JSON object")
        except ValueError as exc:
            self._emit("raw", {"id": None, "error": f"bad_request: {exc}"})
            return
        rid = msg.get("id")
        op = msg.get("op")
        if op == "stats":
            fmt = msg.get("format")
            if fmt not in (None, "json", "prometheus"):
                self._emit(
                    "raw",
                    {"id": rid,
                     "error": f"bad_request: unknown stats format {fmt!r}"},
                )
                return
            self._emit("stats", (rid, fmt))
            return
        if op == "trace":
            n = msg.get("n", 20)
            if isinstance(n, bool) or not isinstance(n, int) or n < 0:
                self._emit(
                    "raw",
                    {"id": rid,
                     "error": "bad_request: n must be a non-negative int"},
                )
                return
            self._emit("trace", (rid, n))
            return
        if op == "reload":
            corpus = msg.get("corpus")
            if not isinstance(corpus, str) or not corpus:
                self._emit(
                    "raw",
                    {"id": rid,
                     "error": "bad_request: reload needs a 'corpus' "
                     "source string"},
                )
                return
            self._emit("reload", (rid, corpus))
            return
        if op is not None:
            self._emit(
                "raw", {"id": rid, "error": f"bad_request: unknown op {op!r}"}
            )
            return
        # content rows: the WORKER validates the payload (one
        # validator, serve/server.py) — the router only owns routing
        self._emit("fut", self.router._executor.submit(
            self.router.dispatch, msg
        ))

    def finish(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._writer.join()


def route_session(router: Router, lines, write_line) -> dict:
    """Run one front-socket session over an iterable of lines."""
    session = _RouterSession(router, write_line)
    try:
        for line in lines:
            session.handle_line(line)
    finally:
        session.finish()
    return {"requests": session.requests, "responses": session.responses}


class FrontServer(JsonlUnixServer):
    """The client-facing Unix socket: one JSONL session per
    connection, all sharing one router (same transport class as a
    worker — serve/server.py)."""

    def __init__(self, path: str, router: Router):
        self.router = router
        super().__init__(path)

    def run_session(self, lines, write_line) -> None:
        route_session(self.router, lines, write_line)
