"""Borg-style worker supervision for the serving fleet: spawn N serve
workers, health-check them, restart crashes/wedges with exponential
backoff, and drain gracefully for rolling restarts.

Each worker is ONE ``licensee-tpu serve --socket`` process — its own
failure domain, its own device pipeline, and (with ``chips_per_worker``)
its own chip subset exported through the SAME env contract the offline
multi-host path uses: the supervisor sets ``LICENSEE_TPU_VISIBLE_CHIPS``
in the child environment and runs ``apply_visible_chips`` over that
dict, so the PJRT visibility vars are derived identically to a
``batch-detect`` co-located launch (parallel/distributed.py).

Failure handling, in escalation order:

* **crash** — ``proc.poll()`` shows an exit: respawn after the current
  backoff delay (``backoff_base_s * 2^restarts`` capped at
  ``backoff_max_s``; the restart counter resets once a worker stays
  healthy ``stable_after_s``, so a week-old worker's first crash
  restarts fast).
* **wedge** — the process is alive but ``{"op": "stats"}`` probes fail
  ``wedged_after`` consecutive times (a hung compile, a stopped
  process): SIGKILL, then the crash path above.  A freshly spawned
  worker gets ``startup_grace_s`` before probe failures count — JAX
  import and corpus load legitimately take seconds.
* **drain** — the rolling-restart verb: mark the worker draining (the
  router stops dispatching to it), wait until the worker reports zero
  queued/in-flight work AND the router reports zero outstanding routed
  requests, then SIGTERM (the serve loop shuts down cleanly and
  unlinks its socket), escalating to SIGKILL only on a stuck exit.

Upgrades ride :meth:`Supervisor.reload_fleet`: a rolling, health-gated
corpus reload (one worker mid-swap at a time, failure budget, automatic
rollback, respawn-argv patching) — zero-downtime corpus rollout with
``rolling_restart()`` as the fallback path.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time

from licensee_tpu.fleet.wire import ConnectionPool, WireError, oneshot
from licensee_tpu.obs.flight import (
    HARVEST_TAIL,
    flight_path_for_socket,
    load_flight_dump,
)
from licensee_tpu.parallel.distributed import (
    apply_visible_chips,
    chips_for_worker,
)

# worker lifecycle states (status()/metrics surface)
STARTING = "starting"
HEALTHY = "healthy"
UNHEALTHY = "unhealthy"
DOWN = "down"
DRAINING = "draining"
STOPPED = "stopped"


class BackoffPolicy:
    """Capped exponential restart backoff — the spawn/backoff core
    shared by this serving supervisor and the offline stripe runner
    (parallel/stripes.py): restart number ``r + 1`` waits
    ``base_s * 2^r`` seconds, capped at ``max_s``; a worker that stays
    healthy ``stable_after_s`` earns its restart counter back so a
    week-old process's first crash restarts fast."""

    def __init__(
        self,
        base_s: float = 0.25,
        max_s: float = 10.0,
        stable_after_s: float = 10.0,
    ):
        self.base_s = float(base_s)
        self.max_s = float(max_s)
        self.stable_after_s = float(stable_after_s)

    def delay_s(self, restarts: int) -> float:
        """The delay before restart number ``restarts + 1``."""
        return min(self.base_s * (2 ** restarts), self.max_s)


def terminate_process(
    proc: subprocess.Popen | None, sigterm_timeout_s: float = 5.0
) -> None:
    """SIGTERM, escalate to SIGKILL after ``sigterm_timeout_s`` — the
    one graceful-stop primitive (shared with the stripe runner)."""
    if proc is None or proc.poll() is not None:
        return
    try:
        proc.terminate()
        proc.wait(timeout=sigterm_timeout_s)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait(timeout=5.0)
    except OSError:
        pass


def default_worker_argv(
    socket_path: str, serve_args: tuple[str, ...] = ()
) -> list[str]:
    """The production worker command: the existing serve loop on its
    own Unix socket."""
    return [
        sys.executable, "-m", "licensee_tpu.cli.main", "serve",
        "--socket", socket_path, *serve_args,
    ]


def worker_env(
    base_env: dict | None, chips: list[str] | None
) -> dict[str, str]:
    """The child environment for one worker.

    With ``chips``, exports ``LICENSEE_TPU_VISIBLE_CHIPS`` and derives
    the runtime visibility vars through ``apply_visible_chips`` on the
    CHILD's env dict — the same translation, validation, and CPU
    rehearsal the offline co-located launch gets, without touching this
    process's environment.  Also pins PYTHONPATH to the package root so
    ``-m licensee_tpu...`` resolves regardless of the child's cwd."""
    env = dict(os.environ if base_env is None else base_env)
    pkg_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    existing = env.get("PYTHONPATH")
    env["PYTHONPATH"] = (
        pkg_root if not existing else pkg_root + os.pathsep + existing
    )
    if chips:
        env["LICENSEE_TPU_VISIBLE_CHIPS"] = ",".join(chips)
        apply_visible_chips(env)
    return env


class WorkerHandle:
    """One supervised worker: its spec, its live process, and the
    restart/health bookkeeping.  Mutated only by the supervisor (under
    its lock); the router reads ``state``/``draining`` lock-free —
    a stale read costs one failed dispatch attempt, which fails over."""

    def __init__(self, name: str, socket_path: str, argv, env):
        self.name = name
        self.socket_path = socket_path
        self.argv = list(argv)
        self.env = dict(env)
        self.proc: subprocess.Popen | None = None
        self.state = STARTING
        self.draining = False
        self.restarts = 0
        self.probe_failures = 0
        self.spawned_at: float | None = None
        self.healthy_since: float | None = None
        self.next_spawn_at: float = 0.0
        self.last_stats: dict = {}
        self.exit_codes: list[int] = []  # recent exits, newest last
        # one entry per scheduled restart: how the worker died (exit
        # code / signal), the backoff armed, and the harvested flight-
        # recorder black box (dump path + last events) — the post-
        # mortem record `fleet --selftest` gates on
        self.restart_log: list[dict] = []

    @property
    def pid(self) -> int | None:
        return self.proc.pid if self.proc is not None else None

    def as_dict(self) -> dict:
        sched = (self.last_stats.get("scheduler") or {})
        return {
            "socket": self.socket_path,
            "pid": self.pid,
            "state": self.state,
            "draining": self.draining,
            "restarts": self.restarts,
            "probe_failures": self.probe_failures,
            "queue_depth": sched.get("queue_depth"),
            "in_flight": sched.get("in_flight"),
            "completed": sched.get("completed"),
            "exit_codes": self.exit_codes[-5:],
            "restart_log": self.restart_log[-3:],
        }


class Supervisor:
    """Spawn + monitor + restart + drain a set of serve workers.

    ``workers`` maps name -> socket path; ``argv_for(name, socket)``
    builds each worker's command (defaults to the serve CLI), so tests
    and the fault harness supervise stub workers through the exact
    production restart machinery."""

    def __init__(
        self,
        workers: dict[str, str],
        *,
        argv_for=None,
        env_for=None,
        chips_per_worker: int | None = None,
        serve_args: tuple[str, ...] = (),
        probe_interval_s: float = 0.5,
        probe_timeout_s: float = 2.0,
        wedged_after: int = 3,
        startup_grace_s: float = 120.0,
        backoff_base_s: float = 0.25,
        backoff_max_s: float = 10.0,
        stable_after_s: float = 10.0,
        base_env: dict | None = None,
    ):
        if not workers:
            raise ValueError("need at least one worker")
        if chips_per_worker is not None and chips_per_worker < 1:
            raise ValueError(
                f"chips_per_worker must be >= 1, got {chips_per_worker!r}"
            )
        self.probe_interval_s = float(probe_interval_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.wedged_after = int(wedged_after)
        self.startup_grace_s = float(startup_grace_s)
        # the shared spawn/backoff core (also driving the offline
        # stripe runner, parallel/stripes.py) — self.backoff is the
        # single source of truth; the read-only properties below keep
        # the long-standing attribute names without a second copy
        self.backoff = BackoffPolicy(
            base_s=backoff_base_s,
            max_s=backoff_max_s,
            stable_after_s=stable_after_s,
        )
        # the router attaches itself here (fleet CLI): drain then also
        # waits for the router's outstanding count to hit zero
        self.router = None
        # spawn ingredients, kept so add_worker can grow the fleet at
        # runtime from the same spec the seed workers got
        self._argv_for = argv_for
        self._env_for = env_for
        self._serve_args = tuple(serve_args)
        self._base_env = base_env
        self._chips_per_worker = chips_per_worker
        self._next_chip_index = len(workers)
        self.workers: dict[str, WorkerHandle] = {}
        for i, (name, sock) in enumerate(workers.items()):
            chips = None
            if chips_per_worker is not None:
                chips = chips_for_worker(i, chips_per_worker)
            env = (
                env_for(name, chips)
                if env_for is not None
                else worker_env(base_env, chips)
            )
            argv = (
                argv_for(name, sock)
                if argv_for is not None
                else default_worker_argv(sock, serve_args)
            )
            self.workers[name] = WorkerHandle(name, sock, argv, env)
        self._lock = threading.Lock()
        # fleet-level reload mutex: one rolling reload at a time.  Two
        # concurrent rolls would interleave worker swaps (the per-worker
        # reload_in_progress guard only catches same-instant overlap on
        # ONE worker), leaving the fleet on mixed fingerprints with
        # clobbered respawn argv — the second roll is refused
        # deterministically instead, mirroring the worker-level verb.
        self._reload_fleet_lock = threading.Lock()
        # one parked connection per worker for the recurring health
        # probe: N workers x a fast probe interval used to dial a fresh
        # socket every round.  The pool's stale-park retry absorbs
        # worker restarts; max_idle=1 because probes are serial per
        # worker (one monitor thread).
        # connect_timeout=probe_timeout_s: the pool's default 2 s dial
        # would outlast a fast probe budget and stall the serial
        # monitor thread on a worker wedged at accept
        self._probe_pools = {
            name: ConnectionPool(
                h.socket_path, max_idle=1,
                connect_timeout=self.probe_timeout_s,
            )
            for name, h in self.workers.items()
        }
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    @property
    def backoff_base_s(self) -> float:
        return self.backoff.base_s

    @property
    def backoff_max_s(self) -> float:
        return self.backoff.max_s

    @property
    def stable_after_s(self) -> float:
        return self.backoff.stable_after_s

    # -- lifecycle --

    def start(self) -> None:
        with self._lock:
            for handle in self.workers.values():
                if handle.proc is None and handle.state != STOPPED:
                    self._spawn(handle)
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._monitor, name="fleet-supervisor", daemon=True
            )
            self._thread.start()

    def stop(self, *, sigterm_timeout_s: float = 5.0) -> None:
        """Stop monitoring and terminate every worker (SIGTERM, then
        SIGKILL after ``sigterm_timeout_s``)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        with self._lock:
            handles = list(self.workers.values())
        for handle in handles:
            self._terminate(handle, sigterm_timeout_s)
            handle.state = STOPPED
        for pool in self._probe_pools.values():
            pool.close()

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()

    # -- elastic membership (the FleetAutoscaler's two levers) --

    def add_worker(self, name: str, socket_path: str) -> WorkerHandle:
        """Grow the fleet by one worker at runtime: build its spec from
        the same ingredients the seed fleet used (``argv_for`` /
        ``env_for`` / chip striping continue where the seed stopped),
        spawn it immediately, and register its probe pool.  The monitor
        thread picks it up on its next pass."""
        with self._lock:
            if name in self.workers:
                raise ValueError(f"worker {name!r} already exists")
            chips = None
            if self._chips_per_worker is not None:
                chips = chips_for_worker(
                    self._next_chip_index, self._chips_per_worker
                )
            self._next_chip_index += 1
            env = (
                self._env_for(name, chips)
                if self._env_for is not None
                else worker_env(self._base_env, chips)
            )
            argv = (
                self._argv_for(name, socket_path)
                if self._argv_for is not None
                else default_worker_argv(socket_path, self._serve_args)
            )
            handle = WorkerHandle(name, socket_path, argv, env)
            self.workers[name] = handle
            self._probe_pools[name] = ConnectionPool(
                socket_path, max_idle=1,
                connect_timeout=self.probe_timeout_s,
            )
            self._spawn(handle)
        return handle

    def remove_worker(
        self,
        name: str,
        *,
        timeout_s: float = 30.0,
        sigterm_timeout_s: float = 5.0,
    ) -> bool:
        """Retire one worker at runtime: drain it (stop dispatch, wait
        for in-flight work, SIGTERM — no respawn), then drop it from
        the fleet and close its probe pool.  Returns the drain's clean
        flag.  The drain marks the handle STOPPED before membership
        changes, so a monitor pass that already snapshotted the handle
        skips it instead of respawning a ghost."""
        if name not in self.workers:
            raise KeyError(f"no worker named {name!r}")
        clean = self.drain(
            name, timeout_s=timeout_s, restart=False,
            sigterm_timeout_s=sigterm_timeout_s,
        )
        with self._lock:
            self.workers.pop(name, None)
        pool = self._probe_pools.pop(name, None)
        if pool is not None:
            pool.close()
        return clean

    # -- spawn / kill primitives (lock held by callers where noted) --

    # every caller (start, poll_once, drain) already holds self._lock
    # across the call; the analyzer now PROVES that contract through
    # the call graph (caller-holds-the-lock), so no pragma is needed
    def _spawn(self, handle: WorkerHandle) -> None:
        """Start (or restart) one worker process.  Lock held.

        The predecessor's flight-recorder box is cleared first: a
        drained worker's clean-shutdown dump (or any leftover) must
        never be harvested as THIS incarnation's crash evidence if it
        dies before writing its own (crash-path harvests already
        consumed their box in _schedule_restart)."""
        try:
            os.unlink(flight_path_for_socket(handle.socket_path))
        except OSError:
            pass
        handle.proc = subprocess.Popen(
            handle.argv,
            env=handle.env,
            stdin=subprocess.DEVNULL,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        handle.spawned_at = time.perf_counter()
        handle.healthy_since = None
        handle.probe_failures = 0
        handle.state = STARTING

    def _terminate(
        self, handle: WorkerHandle, sigterm_timeout_s: float
    ) -> None:
        terminate_process(handle.proc, sigterm_timeout_s)

    # called only from poll_once with self._lock held; the restart
    # bookkeeping rides the caller's critical section (proven by the
    # analyzer's caller-holds-the-lock contract)
    def _schedule_restart(
        self,
        handle: WorkerHandle,
        reason: str = "crash",
        returncode: int | None = None,
    ) -> None:
        """Record the death (exit code/signal + the harvested flight-
        recorder black box) and arm the backoff timer.  Lock held."""
        delay = self.backoff.delay_s(handle.restarts)
        handle.restarts += 1
        handle.next_spawn_at = time.perf_counter() + delay
        handle.state = DOWN
        handle.proc = None
        entry = {
            "reason": reason,
            "exit_code": (
                returncode if returncode is None or returncode >= 0
                else None
            ),
            # a negative Popen returncode IS the killing signal
            "signal": (
                -returncode
                if returncode is not None and returncode < 0
                else None
            ),
            "backoff_s": round(delay, 3),
            "restarts": handle.restarts,
        }
        # harvest the black box NOW, before the respawned incarnation
        # overwrites it: the dump on disk is at most one flush interval
        # older than the death (obs/flight.py's spill contract)
        entry.update(self._harvest_flight(handle))
        handle.restart_log.append(entry)
        del handle.restart_log[:-20]

    @staticmethod
    def _harvest_flight(handle: WorkerHandle) -> dict:
        """Read a dead worker's flight-recorder dump; the last events
        ride the restart-log entry so a SIGKILL post-mortem starts from
        recorded evidence.  The dump is CONSUMED (unlinked) once
        harvested: a crash-looping respawn that dies before its first
        flush must read as "no box" — honest — never replay the
        previous incarnation's events as fresh evidence."""
        path = flight_path_for_socket(handle.socket_path)
        box = load_flight_dump(path)
        if box is None:
            return {
                "flight_dump": path, "flight_harvested": False,
                "flight_events": [],
            }
        try:
            os.unlink(path)
        except OSError:
            pass  # harvested either way; the entry holds the evidence
        events = box.get("events") or []
        return {
            "flight_dump": path,
            "flight_harvested": True,
            "flight_proc": box.get("proc"),
            "flight_recorded": box.get("recorded"),
            "flight_events": events[-HARVEST_TAIL:],
        }

    def backoff_delay_s(self, restarts: int) -> float:
        """The delay before restart number ``restarts + 1`` — exposed
        so tests and the selftest can name the backoff budget."""
        return self.backoff.delay_s(restarts)

    # -- the monitor loop --

    def _monitor(self) -> None:
        while not self._stop.wait(self.probe_interval_s):
            self.poll_once()

    def poll_once(self) -> None:
        """One supervision pass over every worker (public so tests can
        drive supervision deterministically without the timer)."""
        with self._lock:
            handles = list(self.workers.values())
        now = time.perf_counter()
        for handle in handles:
            with self._lock:
                if handle.state == STOPPED or handle.draining:
                    continue
                proc = handle.proc
                if proc is not None and proc.poll() is not None:
                    handle.exit_codes.append(proc.returncode)
                    self._schedule_restart(
                        handle, "crash", proc.returncode
                    )
                    continue
                if proc is None:
                    if now >= handle.next_spawn_at:
                        self._spawn(handle)
                    continue
            # probe OUTSIDE the lock: a 2-second probe timeout must not
            # freeze supervision of every other worker
            stats = self.probe(handle.name)
            with self._lock:
                if stats is not None:
                    handle.last_stats = stats
                    handle.probe_failures = 0
                    if handle.healthy_since is None:
                        handle.healthy_since = time.perf_counter()
                    elif (
                        handle.restarts
                        and time.perf_counter() - handle.healthy_since
                        >= self.stable_after_s
                    ):
                        handle.restarts = 0  # earned a fresh backoff
                    handle.state = HEALTHY
                    continue
                handle.healthy_since = None
                in_grace = (
                    handle.spawned_at is not None
                    and time.perf_counter() - handle.spawned_at
                    < self.startup_grace_s
                )
                if handle.state == STARTING and in_grace:
                    continue  # still booting: not a failure yet
                handle.probe_failures += 1
                if handle.probe_failures >= self.wedged_after:
                    # alive but unresponsive: wedged.  SIGKILL — a
                    # stopped/hung process won't honor SIGTERM
                    proc = handle.proc
                    if proc is not None and proc.poll() is None:
                        try:
                            proc.kill()
                            proc.wait(timeout=5.0)
                        except (OSError, subprocess.TimeoutExpired):
                            pass
                    returncode = None
                    if proc is not None and proc.poll() is not None:
                        returncode = proc.returncode
                        handle.exit_codes.append(returncode)
                    self._schedule_restart(handle, "wedge", returncode)
                else:
                    handle.state = UNHEALTHY

    # -- probes --

    def probe(self, name: str) -> dict | None:
        """One ``{"op": "stats"}`` round trip to a worker; the stats
        dict, or None when the worker cannot answer."""
        with self._lock:
            pool = self._probe_pools.get(name)
            if pool is None:  # dynamically added worker (tests)
                handle = self.workers.get(name)
                if handle is None:
                    # removed concurrently (remove_worker): not an
                    # error — a monitor pass that raced the removal
                    # just moves on
                    return None
                pool = ConnectionPool(
                    handle.socket_path, max_idle=1,
                    connect_timeout=self.probe_timeout_s,
                )
                self._probe_pools[name] = pool
        try:
            row = pool.request({"op": "stats"}, self.probe_timeout_s)
        except WireError:
            return None
        stats = row.get("stats")
        return stats if isinstance(stats, dict) else None

    def wait_healthy(self, timeout_s: float = 120.0) -> bool:
        """Block until every non-stopped worker answers probes (fleet
        boot barrier); False on timeout."""
        deadline = time.perf_counter() + timeout_s
        while time.perf_counter() < deadline:
            pending = [
                h.name
                for h in self.workers.values()
                if h.state != STOPPED and self.probe(h.name) is None
            ]
            if not pending:
                return True
            time.sleep(0.1)
        return False

    def dispatchable(self, name: str) -> bool:
        """May the router send NEW work to this worker?  (The router
        additionally applies its own probe-based health view.)"""
        handle = self.workers.get(name)
        return (
            handle is not None
            and not handle.draining
            and handle.state not in (STOPPED, DOWN)
        )

    # -- drain / rolling restart --

    def drain(
        self,
        name: str,
        *,
        timeout_s: float = 30.0,
        restart: bool = True,
        sigterm_timeout_s: float = 5.0,
    ) -> bool:
        """Gracefully take one worker out of service: stop dispatch,
        wait for in-flight work, SIGTERM, optionally respawn.

        Returns True when the worker drained clean (every in-flight
        request finished before the SIGTERM); False when the timeout
        forced termination with work possibly still in flight."""
        handle = self.workers[name]
        with self._lock:
            handle.draining = True
            handle.state = DRAINING
        clean = False
        try:
            deadline = time.perf_counter() + timeout_s
            while time.perf_counter() < deadline:
                stats = self.probe(name)
                sched = (stats or {}).get("scheduler") or {}
                worker_idle = (
                    stats is not None
                    and sched.get("queue_depth") == 0
                    and sched.get("in_flight") == 0
                )
                router = self.router
                router_idle = (
                    router is None or router.outstanding(name) == 0
                )
                if stats is None and handle.proc is not None and (
                    handle.proc.poll() is not None
                ):
                    # died mid-drain: in-flight work died with it (the
                    # router's retries own it now) — not a clean drain
                    break
                if worker_idle and router_idle:
                    clean = True
                    break
                time.sleep(0.05)
            self._terminate(handle, sigterm_timeout_s)
        finally:
            with self._lock:
                if handle.proc is not None and (
                    handle.proc.poll() is not None
                ):
                    handle.exit_codes.append(handle.proc.returncode)
                handle.proc = None
                if restart:
                    self._spawn(handle)
                else:
                    handle.state = STOPPED
                handle.draining = False
        return clean

    def rolling_restart(self, *, timeout_s: float = 30.0) -> dict:
        """Drain-and-respawn every worker IN SEQUENCE — at most one
        replica out of service at a time, the zero-downtime restart."""
        out = {}
        for name in list(self.workers):
            out[name] = self.drain(name, timeout_s=timeout_s, restart=True)
            # wait for the replacement before touching the next replica
            deadline = time.perf_counter() + max(timeout_s, 60.0)
            while time.perf_counter() < deadline:
                if self.probe(name) is not None:
                    break
                time.sleep(0.1)
        return out

    # -- fleet-wide rolling corpus reload --

    @staticmethod
    def patch_corpus_argv(argv: list[str], corpus: str) -> list[str]:
        """Rewrite a serve worker's argv so a LATER crash-restart boots
        the corpus it was rolled onto — without this, a restart would
        silently roll one replica back to its launch-time corpus.
        Replaces the value after ``--corpus`` (or appends the pair)."""
        out = list(argv)
        for i, arg in enumerate(out[:-1]):
            if arg == "--corpus":
                out[i + 1] = corpus
                return out
        return out + ["--corpus", corpus]

    def _await_fingerprint(
        self, name: str, fingerprint: str | None, timeout_s: float
    ) -> bool:
        """Health-gate one worker after its reload verb answered: it
        must come back on probes AND report the expected fingerprint."""
        deadline = time.perf_counter() + timeout_s
        while time.perf_counter() < deadline:
            stats = self.probe(name)
            if stats is not None:
                got = (stats.get("corpus") or {}).get("fingerprint")
                if fingerprint is None or got == fingerprint:
                    return True
            time.sleep(0.1)
        return False

    def reload_fleet(
        self,
        corpus: str,
        *,
        timeout_s: float = 300.0,
        health_timeout_s: float = 30.0,
        failure_budget: int = 0,
        rollback: bool = True,
        argv_patch=None,
    ) -> dict:
        """Rolling corpus reload: one worker at a time, health-gated,
        with a capped failure budget and automatic rollback.

        Per worker, in sequence: send ``{"op": "reload"}``, wait for
        the validated swap to answer, then gate on a health probe
        reporting the NEW fingerprint before touching the next replica
        — at most one worker is ever mid-swap, so the fleet keeps
        serving throughout.  A worker that refuses the corpus (compile
        error, corrupt artifact, failed validation) or dies mid-swap
        counts against ``failure_budget``; when the budget is exceeded,
        every already-reloaded worker is rolled back to the corpus
        source it reported before the roll, and the fleet is left
        healthy on the OLD fingerprint.  ``rolling_restart()`` remains
        the fallback path when a corpus can only change via argv.

        ``argv_patch(argv, corpus) -> argv`` rewrites a successfully
        reloaded worker's respawn command (default:
        :meth:`patch_corpus_argv`) so later crash-restarts boot the new
        corpus instead of silently rolling back one replica.

        One roll at a time, fleet-wide: a reload_fleet that arrives
        while another is rolling is refused deterministically
        (``error: fleet_reload_in_progress``) — never queued, never
        interleaved — so "at most one worker is ever mid-swap" holds
        across concurrent callers, not just within one roll."""
        if not self._reload_fleet_lock.acquire(blocking=False):
            return {
                "ok": False,
                "corpus": corpus,
                "fingerprint": None,
                "rolled_back": False,
                "error": "fleet_reload_in_progress",
                "workers": {},
            }
        try:
            return self._reload_fleet_locked(
                corpus,
                timeout_s=timeout_s,
                health_timeout_s=health_timeout_s,
                failure_budget=failure_budget,
                rollback=rollback,
                argv_patch=argv_patch,
            )
        finally:
            self._reload_fleet_lock.release()

    def _reload_fleet_locked(
        self,
        corpus: str,
        *,
        timeout_s: float,
        health_timeout_s: float,
        failure_budget: int,
        rollback: bool,
        argv_patch,
    ) -> dict:
        if argv_patch is None:
            argv_patch = self.patch_corpus_argv
        results: dict[str, dict] = {}
        succeeded: list[tuple[str, str | None, list[str]]] = []
        failures = 0
        target_fp: str | None = None
        out = {
            "ok": True,
            "corpus": corpus,
            "fingerprint": None,
            "rolled_back": False,
            "workers": results,
        }
        for name in list(self.workers):
            handle = self.workers[name]
            if handle.state == STOPPED:
                results[name] = {"skipped": "stopped"}
                continue
            before = self.probe(name) or {}
            old_source = (before.get("corpus") or {}).get("source")
            row = None
            error = None
            try:
                row = oneshot(
                    handle.socket_path,
                    {"op": "reload", "corpus": corpus},
                    timeout_s,
                )
            except WireError as exc:
                error = f"reload transport failed: {exc}"
            if row is not None:
                reload_row = row.get("reload")
                if isinstance(reload_row, dict) and reload_row.get("ok"):
                    fp = reload_row.get("fingerprint")
                    target_fp = fp or target_fp
                    if self._await_fingerprint(name, fp, health_timeout_s):
                        results[name] = {"ok": True, "fingerprint": fp}
                        old_argv = list(handle.argv)
                        with self._lock:
                            handle.argv = argv_patch(handle.argv, corpus)
                        succeeded.append((name, old_source, old_argv))
                        continue
                    error = (
                        f"worker unhealthy (or on the wrong fingerprint) "
                        f"{health_timeout_s}s after reload"
                    )
                else:
                    error = str(
                        row.get("error") or f"unexpected response: {row}"
                    )
            failures += 1
            results[name] = {"ok": False, "error": error}
            if failures > failure_budget:
                out["ok"] = False
                if rollback and succeeded:
                    out["rolled_back"] = True
                    self._rollback(succeeded, results, timeout_s)
                break
        out["fingerprint"] = None if out["rolled_back"] else target_fp
        if out["ok"] and failures:
            out["ok"] = False  # within budget, but not a clean roll
        return out

    def _rollback(
        self,
        succeeded: list[tuple[str, str | None, list[str]]],
        results: dict,
        timeout_s: float,
    ) -> None:
        """Return every already-reloaded worker to its pre-roll corpus
        (newest first, mirroring the forward order) and restore its
        respawn argv."""
        for name, old_source, old_argv in reversed(succeeded):
            handle = self.workers.get(name)
            if handle is None:
                continue
            with self._lock:
                handle.argv = old_argv
            entry = results.get(name) or {}
            if not old_source:
                entry["rolled_back"] = False
                entry["rollback_error"] = (
                    "previous corpus source unknown; restart will "
                    "restore it from argv"
                )
                results[name] = entry
                continue
            try:
                row = oneshot(
                    handle.socket_path,
                    {"op": "reload", "corpus": old_source},
                    timeout_s,
                )
                ok = bool(
                    isinstance(row.get("reload"), dict)
                    and row["reload"].get("ok")
                )
                entry["rolled_back"] = ok
                if not ok:
                    entry["rollback_error"] = str(row.get("error") or row)
            except WireError as exc:
                entry["rolled_back"] = False
                entry["rollback_error"] = str(exc)
            results[name] = entry

    # -- introspection --

    def status(self) -> dict:
        with self._lock:
            return {
                name: handle.as_dict()
                for name, handle in self.workers.items()
            }

    def host_health(self) -> dict:
        """The host-level health verdict for the federation tier: this
        supervisor's whole worker domain, summarized the way a FRONT
        router (or an operator) wants it — how many replicas exist,
        how many are answering probes, and whether the domain can take
        new work at all.  Rides the router's ``stats`` verb, so a
        cross-host probe sees domain health in one round trip."""
        with self._lock:
            handles = list(self.workers.values())
            healthy = sum(1 for h in handles if h.state == HEALTHY)
            dispatchable = sum(
                1
                for h in handles
                if not h.draining and h.state not in (STOPPED, DOWN)
            )
            restarts = sum(h.restarts for h in handles)
        return {
            "workers": len(handles),
            "healthy": healthy,
            "dispatchable": dispatchable,
            "restarts": restarts,
            "serving": dispatchable > 0,
        }


def kill_worker(handle: WorkerHandle) -> None:
    """SIGKILL a supervised worker — the crash fault (faults.py rides
    this same path for real processes)."""
    proc = handle.proc
    if proc is not None and proc.poll() is None:
        proc.kill()


def hang_worker(handle: WorkerHandle) -> None:
    """SIGSTOP — the wedge fault: the process stays alive but stops
    answering probes, exercising the supervisor's wedged path."""
    if handle.pid is not None:
        os.kill(handle.pid, signal.SIGSTOP)


def resume_worker(handle: WorkerHandle) -> None:
    if handle.pid is not None:
        os.kill(handle.pid, signal.SIGCONT)
