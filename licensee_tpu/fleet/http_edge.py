"""The HTTP/1.1 network edge: a keep-alive front end for the fleet
router, carried entirely by the router's single-threaded event loop
(serve/eventloop.py) — no threads, no new dependencies, stdlib only.

``POST /classify`` maps one JSON body onto the existing JSONL request
schema (the body IS the content row; the router and workers validate it
exactly as they validate a socket line), pipelined over keep-alive
connections and answered strictly in request order — the same session
contract the JSONL front socket keeps.  The edge owns the three
client-facing policies the wire tier never needed:

* **auth** — per-client bearer tokens (``Authorization: Bearer <t>``);
  with tokens configured, a missing or unknown token answers 401 and
  the client identity for everything below is the token's name.
* **rate limits** — one token bucket per client (``rate_per_client``
  req/s, ``burst`` deep); an over-rate request answers 429 with a
  ``Retry-After`` naming the refill horizon.
* **fair queuing** — admitted requests drain through deficit
  round-robin per client (quantum in BODY BYTES, so a client posting
  fat blobs cannot crowd out one posting small ones), bounded by
  ``max_inflight`` dispatches into the router.

Backpressure translates, it never disconnects: the fleet's
``queue_full`` contract becomes 429 + ``Retry-After`` (the worker's
``retry_after`` hint, rounded up), router shutdown becomes 503.
Responses echo ``X-Trace-Id`` and ``X-Corpus`` headers from the wire
row, so the PR 12 telemetry plane (``licensee-tpu traces``) spans the
edge: the header value IS the 16-hex trace handle the assembled trees
join on.  ``GET /healthz`` (unauthenticated — load-balancer probes)
reports domain health; ``GET /metrics`` serves the merged fleet
Prometheus exposition.

Framing errors answer then burn: an invalid request line, an oversized
body, or a malformed header block gets its status row and THEN the
connection closes — a peer whose framing is broken can never poison
the responses queued behind it.  Header or body dribble is reaped by
the same stall sweep that kills JSONL slowloris clients
(LoopJsonlServer; mid-body counts as mid-line).

The HTTP surface is declared as data (``ROUTES`` / ``STATUS_TEXT``) so
the wire-protocol contract checker (analysis/rules_protocol.py) diffs
it against ``protocol_schema.HTTP_ROUTES`` / ``HTTP_STATUS_CODES`` the
same way it diffs JSONL ops — editing the edge protocol is a two-place
change by design.

Threading contract: every callback here runs on the router's loop
thread and blocks on nothing; the one blocking verb (the fan-out
``/metrics`` scrape) runs on the router's ops executor, exactly like
the JSONL front session's stats verb.  House rules (script/lint):
monotonic clocks only, no print.
"""

from __future__ import annotations

import json
import math
import time
import urllib.parse
from collections import deque

from licensee_tpu.obs.tsdb import QueryError

# the header-echo fast path shares the router's hot-path extractor
from licensee_tpu.fleet.wire import json_str_field as _field_from_line
from licensee_tpu.serve.eventloop import (
    LineConn,
    LoopJsonlServer,
    drop_close,
    drop_line,
)

# the declared HTTP surface: (method, path) -> the wire-level meaning.
# The protocol checker holds this table equal to
# protocol_schema.HTTP_ROUTES, both directions.  ``{id}`` paths are
# templates: runtime matching parses the job id out of the path
# (_job_template) and answers under the template's declared route.
ROUTES: dict[tuple[str, str], str] = {
    ("POST", "/classify"): "content",
    ("GET", "/healthz"): "health",
    ("GET", "/metrics"): "prometheus",
    ("GET", "/metrics/history"): "metrics_history",
    ("POST", "/jobs"): "job_submit",
    ("GET", "/jobs/{id}"): "job_status",
    ("GET", "/jobs/{id}/results"): "job_results",
    ("GET", "/jobs/{id}/containers"): "job_containers",
    ("DELETE", "/jobs/{id}"): "job_cancel",
    ("POST", "/corpus"): "corpus_upload",
}

# every status the edge may mint; _respond looks codes up here, so an
# undeclared code is a KeyError in tests before it is drift in CI.
# Checked equal to protocol_schema.HTTP_STATUS_CODES.
STATUS_TEXT: dict[int, str] = {
    200: "OK",
    202: "Accepted",
    400: "Bad Request",
    401: "Unauthorized",
    403: "Forbidden",
    404: "Not Found",
    405: "Method Not Allowed",
    409: "Conflict",
    413: "Payload Too Large",
    429: "Too Many Requests",
    500: "Internal Server Error",
    503: "Service Unavailable",
}

# the jobs tier's error vocabulary, spelled as module-level dict
# literals so every mint site is one the wire-protocol contract
# checker reads (an f-string body would hide the code)
_JOBS_DISABLED = {
    "error": "jobs_disabled: this edge serves no jobs tier "
             "(start the fleet with --jobs-dir)",
}
_JOB_NOT_FOUND = {"error": "job_not_found: no such job id"}
_JOB_NOT_DONE = {
    "error": "job_not_done: the job has not completed; poll its "
             "status first",
}

# the tenancy tier's error vocabulary, same contract: module-level
# dict literals so every mint site is checker-visible
_TENANCY_DISABLED = {
    "error": "tenancy_disabled: this edge serves no tenant registry "
             "(start the fleet with --tenants)",
}
_UNKNOWN_TENANT = {
    "error": "unknown_tenant: this client is bound to no tenant "
             "(corpus onboarding needs a registry-listed bearer token)",
}

# error-code prefixes (the JSONL "error" field) -> HTTP status classes;
# spelled as explicit branches in _finish_content so every mint site is
# a literal the contract checker can see
_FEDERATION_DOWN_CODES = ("router_closed", "router_not_started",
                          "no_backend_available")

# per-connection pipelining bound: above HIGH un-answered requests the
# client socket read pauses (kernel buffer pushes back), resuming
# below LOW — the JSONL front session's flow control, HTTP-sized
_EDGE_HIGH = 256
_EDGE_LOW = 64

_MAX_HEADERS = 64


def _job_template(path: str) -> tuple[str, str] | None:
    """Parse a ``/jobs/<id>[...]`` path into its declared route
    template + the job id, or None when the shape is not a job path.
    Ids are the executor's lowercase-hex mints; refusing anything
    else keeps arbitrary client bytes out of filesystem joins."""
    if not path.startswith("/jobs/"):
        return None
    rest = path[len("/jobs/"):]
    job_id, _, tail = rest.partition("/")
    if not job_id or not all(
        c in "0123456789abcdef" for c in job_id
    ) or len(job_id) > 32:
        return None
    if tail == "":
        return "/jobs/{id}", job_id
    if tail == "results":
        return "/jobs/{id}/results", job_id
    if tail == "containers":
        return "/jobs/{id}/containers", job_id
    return None


class _TokenBucket:
    """One client's rate limiter: ``take()`` returns 0.0 when a token
    was available, else the seconds until one refills (the Retry-After
    horizon).  Loop-thread owned — no lock."""

    __slots__ = ("rate", "burst", "tokens", "stamp")

    def __init__(self, rate: float, burst: float):
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = float(burst)
        self.stamp = time.perf_counter()

    def take(self) -> float:
        now = time.perf_counter()
        self.tokens = min(
            self.burst, self.tokens + (now - self.stamp) * self.rate
        )
        self.stamp = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return 0.0
        if self.rate <= 0:
            return 60.0
        return (1.0 - self.tokens) / self.rate




class _EdgeRequest:
    """One admitted ``/classify`` request parked in the DRR queue:
    the session slot it answers into, the raw JSON body (the wire
    line), the client identity it queues under, and its fair-queuing
    cost in body bytes."""

    __slots__ = ("session", "slot", "line", "client", "cost", "pool")

    def __init__(self, session: "_EdgeSession", slot: dict, line: str,
                 client: str, pool: str | None = None):
        self.session = session
        self.slot = slot
        self.line = line
        self.client = client
        self.cost = max(1, len(line))
        # the client's tenant pool (bearer-token binding): rides into
        # router._submit so dispatch/failover stay inside the pool
        self.pool = pool


class _EdgeSession:
    """One keep-alive HTTP connection's parser + response writer, as
    loop callbacks on a mixed-framing LineConn: request line, header
    lines, then a Content-Length body blob, then back to line framing.
    Responses go out strictly in request arrival order (the ``slots``
    deque), whatever order the router answers in."""

    def __init__(self, server: "HttpEdgeServer", conn: LineConn,
                 peer: str):
        self.server = server
        self.conn = conn
        self.peer = peer
        self.slots: deque[dict] = deque()
        self.paused = False
        self.burned = False
        self.closed = False
        # per-request parse state
        self._pending_slot: dict | None = None
        self.state = "request"  # "request" | "headers"
        self.method = ""
        self.path = ""
        self.keep_alive = True
        self.headers: dict[str, str] = {}
        self.n_headers = 0
        conn.on_line = self._on_line
        conn.on_blob = self._on_body
        conn.on_close = self._on_close

    # -- teardown --

    def _on_close(self, _reason) -> None:
        self.closed = True
        self.server.forget_connection(self.conn)
        self.slots.clear()  # late router fills find no slot: dropped

    # -- parsing (loop thread) --

    def _on_line(self, line: str) -> None:
        if self.burned or self.closed:
            return
        line = line.rstrip("\r")
        if self.state == "request":
            if not line:
                return  # leading CRLF between pipelined requests: ignore
            self._parse_request_line(line)
            return
        # header block
        if line:
            self._parse_header_line(line)
        else:
            self._end_of_headers()

    def _parse_request_line(self, line: str) -> None:
        parts = line.split(" ")
        if len(parts) != 3 or not parts[2].startswith("HTTP/1."):
            slot = self._new_slot("error")
            self._respond(
                slot, 400,
                _err_body("bad_request", "malformed request line"),
                burn=True,
            )
            return
        self.method, self.path, version = parts
        # keep-alive is the 1.1 default; 1.0 closes unless asked
        self.keep_alive = version != "HTTP/1.0"
        self.headers = {}
        self.n_headers = 0
        self.state = "headers"

    def _parse_header_line(self, line: str) -> None:
        name, sep, value = line.partition(":")
        if not sep or not name.strip():
            slot = self._new_slot("error")
            self._respond(
                slot, 400,
                _err_body("bad_request", "malformed header line"),
                burn=True,
            )
            return
        self.n_headers += 1
        if self.n_headers > _MAX_HEADERS:
            slot = self._new_slot("error")
            self._respond(
                slot, 400,
                _err_body("bad_request",
                          f"more than {_MAX_HEADERS} headers"),
                burn=True,
            )
            return
        self.headers[name.strip().lower()] = value.strip()

    def _end_of_headers(self) -> None:
        self.state = "request"
        headers = self.headers
        conn_opt = headers.get("connection", "").lower()
        if conn_opt == "close":
            self.keep_alive = False
        elif conn_opt == "keep-alive":
            self.keep_alive = True
        raw_len = headers.get("content-length", "0")
        try:
            length = int(raw_len)
            if length < 0:
                raise ValueError
        except ValueError:
            slot = self._new_slot("error")
            self._respond(
                slot, 400,
                _err_body("bad_request",
                          f"bad Content-Length {raw_len!r}"),
                burn=True,
            )
            return
        slot = self._new_slot("content")
        slot["method"] = self.method
        slot["path"] = self.path
        slot["keep_alive"] = self.keep_alive
        # job submissions and corpus uploads carry whole artifacts:
        # they get the fat body budget, every other route keeps the
        # wire-row one
        limit = (
            self.server.max_job_body_bytes
            if (self.method, self.path.partition("?")[0])
            in (("POST", "/jobs"), ("POST", "/corpus"))
            else self.server.max_body_bytes
        )
        if length > limit:
            # refusing to READ the body breaks framing by definition:
            # answer and burn
            self._respond(
                slot, 413,
                _err_body(
                    "bad_request",
                    f"body {length} bytes over the "
                    f"{limit}-byte limit",
                ),
                burn=True,
            )
            return
        if headers.get("expect", "").lower() == "100-continue":
            try:
                self.conn.write_bytes_on_loop(
                    b"HTTP/1.1 100 Continue\r\n\r\n"
                )
            except OSError:
                return
        # the route verdict is computed now but ANSWERED only once the
        # body is drained — keep-alive framing survives a 401/404/429
        slot["verdict"] = self._route_verdict(slot)
        if length:
            self._pending_slot = slot
            self.conn.expect_blob(length)
        else:
            self._finish_request(slot, b"")

    def _on_body(self, blob: bytes) -> None:
        if self.burned or self.closed:
            return
        slot = self._pending_slot
        self._pending_slot = None
        self._finish_request(slot, blob)

    # -- routing --

    def _route_verdict(self, slot: dict) -> tuple:
        """("dispatch"|"health"|"metrics", client), ("jobs", client,
        route, job_id), or ("error", responder args) — decided at
        end-of-headers, delivered at end-of-body."""
        server = self.server
        method, raw_path = slot["method"], slot["path"]
        # the query string is transport detail, not route identity:
        # /metrics/history?series=… routes as /metrics/history, the
        # params ride in the slot for the handler
        path, _, slot["query_string"] = raw_path.partition("?")
        job_id = None
        route = ROUTES.get((method, path))
        if route is None:
            template = _job_template(path)
            if template is not None:
                route = ROUTES.get((method, template[0]))
                job_id = template[1]
            if route is None:
                known_path = any(
                    p == path for _m, p in ROUTES
                ) or template is not None
                if known_path:
                    return ("error", 405,
                            _err_body("bad_request",
                                      f"method {method} not allowed"))
                return ("error", 404,
                        _err_body("bad_request", f"no route {path}"))
        if route == "health":
            return ("health", None)
        client = self.peer
        if server.tokens is not None:
            auth = self.headers.get("authorization", "")
            scheme, _, value = auth.partition(" ")
            client = server.tokens.get(value.strip())
            if scheme.lower() != "bearer" or client is None:
                server.count_throttle("auth")
                return ("error", 401,
                        _err_body("bad_request",
                                  "missing or unknown bearer token"),
                        [("WWW-Authenticate", "Bearer")])
        if route == "prometheus":
            return ("metrics", client)
        if route == "metrics_history":
            return ("metrics_history", client)
        wait = server.bucket_for(client).take()
        if wait > 0.0:
            server.count_throttle("rate_limit")
            return ("error", 429,
                    _err_body("queue_full",
                              "client over its request rate"),
                    [("Retry-After", str(max(1, math.ceil(wait))))])
        if route.startswith("job_"):
            if server.jobs is None:
                return ("error", 503,
                        json.dumps(_JOBS_DISABLED).encode("utf-8"))
            return ("jobs", client, route, job_id)
        if route == "corpus_upload":
            if server.tenancy is None:
                return ("error", 503,
                        json.dumps(_TENANCY_DISABLED).encode("utf-8"))
            if server.tenancy.tenant_for(client) is None:
                # authenticated (or auth-less peer-named) but bound to
                # no tenant: 403, not 401 — the token may be perfectly
                # valid for /classify yet own no corpus
                server.count_throttle("auth")
                return ("error", 403,
                        json.dumps(_UNKNOWN_TENANT).encode("utf-8"))
            return ("corpus", client)
        pool = (
            server.tenancy.pool_for_client(client)
            if server.tenancy is not None else None
        )
        return ("dispatch", client, pool)

    def _finish_request(self, slot: dict, body: bytes) -> None:
        verdict = slot.pop("verdict")
        kind = verdict[0]
        if kind == "error":
            code, payload = verdict[1], verdict[2]
            extra = verdict[3] if len(verdict) > 3 else ()
            self._respond(slot, code, payload, extra_headers=extra)
            return
        if kind == "health":
            self._finish_health(slot)
            return
        if kind == "metrics":
            self._defer_metrics(slot)
            return
        if kind == "metrics_history":
            self._defer_history(slot)
            return
        if kind == "jobs":
            self._defer_job(slot, verdict[2], verdict[3], body)
            return
        if kind == "corpus":
            self._defer_corpus(slot, verdict[1], body)
            return
        line = body.decode("utf-8", errors="replace").strip()
        if not line or "\n" in line:
            # an empty body is not a content row; an embedded newline
            # would smuggle a second JSONL frame through the splice
            self._respond(
                slot, 400,
                _err_body("bad_request",
                          "body must be one JSON content row"),
            )
            return
        self.server.enqueue(
            _EdgeRequest(self, slot, line, verdict[1] or self.peer,
                         pool=verdict[2] if len(verdict) > 2 else None)
        )

    def _finish_health(self, slot: dict) -> None:
        router = self.server.router
        healthy = sum(
            1 for b in router.backends.values() if b.healthy
        )
        ok = healthy > 0 and not router._closing
        payload = json.dumps({
            "ok": ok,
            "backends_healthy": healthy,
            "backends_total": len(router.backends),
        }).encode("utf-8")
        if ok:
            self._respond(slot, 200, payload)
        else:
            self._respond(slot, 503, payload)

    def _defer_metrics(self, slot: dict) -> None:
        """The fan-out Prometheus scrape blocks BY DESIGN — ops
        executor, never the loop (the JSONL front session's contract,
        fleet/router._FrontSession._defer)."""
        server = self.server
        loop = server.router.loop

        def run() -> None:
            try:
                text = server.router.prometheus()
                resp = (200, text.encode("utf-8"), "text/plain")
            except Exception as exc:  # noqa: BLE001 — session containment
                resp = (
                    500,
                    _err_body("internal_error", str(exc)[:200]),
                    "application/json",
                )

            def fill() -> None:
                code, payload, ctype = resp
                if code == 200:
                    self._respond(slot, 200, payload, ctype=ctype)
                else:
                    self._respond(slot, 500, payload)

            loop.call_soon_threadsafe(fill)

        server.router._ops.submit(run)

    def _defer_history(self, slot: dict) -> None:
        """GET /metrics/history: a telemetry-store query.  Store reads
        take the series lock — ops executor, never the loop, same
        contract as the metrics scrape.  Param decoding happens HERE
        (loop thread, pure string work) so a malformed number answers
        400 without burning an ops hop."""
        server = self.server
        loop = server.router.loop
        try:
            params = _history_params(slot.get("query_string", ""))
        except ValueError as exc:
            self._respond(
                slot, 400, _err_body("bad_request", str(exc)[:200])
            )
            return

        def run() -> None:
            try:
                result = server.router.store.query(params)
                resp = (200, json.dumps(result).encode("utf-8"))
            except QueryError as exc:
                if exc.code == "unknown_series":
                    resp = (404,
                            _err_body("unknown_series", str(exc)[:200]))
                else:
                    resp = (400,
                            _err_body("bad_request", str(exc)[:200]))
            except Exception as exc:  # noqa: BLE001 — session containment
                resp = (500, _err_body("internal_error", str(exc)[:200]))

            def fill() -> None:
                code, payload = resp
                self._respond(slot, code, payload)

            loop.call_soon_threadsafe(fill)

        server.router._ops.submit(run)

    def _defer_job(self, slot: dict, route: str, job_id: str | None,
                   body: bytes) -> None:
        """Every jobs verb blocks (journal fsync, manifest/result file
        I/O) — ops executor, never the loop, same contract as the
        metrics scrape."""
        server = self.server
        loop = server.router.loop

        def run() -> None:
            try:
                resp = _job_response(server, route, job_id, body)
            except Exception as exc:  # noqa: BLE001 — session containment
                resp = (
                    500,
                    _err_body("internal_error", str(exc)[:200]),
                    (), "application/json",
                )

            def fill() -> None:
                code, payload, extra, ctype = resp
                self._respond(
                    slot, code, payload, extra_headers=extra, ctype=ctype
                )

            loop.call_soon_threadsafe(fill)

        server.router._ops.submit(run)

    def _defer_corpus(self, slot: dict, client: str | None,
                      body: bytes) -> None:
        """POST /corpus: the whole onboarding pipeline (stage the
        artifact, run the validation gate, roll the tenant's pool)
        blocks for seconds — ops executor, never the loop."""
        server = self.server
        loop = server.router.loop

        def run() -> None:
            try:
                resp = _corpus_upload(server, client, body)
            except Exception as exc:  # noqa: BLE001 — session containment
                resp = (500, _err_body("internal_error", str(exc)[:200]))

            def fill() -> None:
                code, payload = resp
                self._respond(slot, code, payload)

            loop.call_soon_threadsafe(fill)

        server.router._ops.submit(run)

    # -- the router answer path --

    def fill_content(self, slot: dict, row, text) -> None:
        """One routed answer (loop thread): map the wire row onto an
        HTTP status + echo headers.  ``text`` is the router's spliced
        fast-path line (only ever a non-error row)."""
        if text is not None:
            extra = _echo_headers(text)
            self._respond(slot, 200, text.encode("utf-8"), extra_headers=extra)
            return
        err = row.get("error")
        payload = json.dumps(row).encode("utf-8")
        extra = []
        trace = row.get("trace")
        if trace:
            extra.append(("X-Trace-Id", str(trace)))
        corpus = row.get("corpus")
        if corpus:
            extra.append(("X-Corpus", str(corpus)))
        if not isinstance(err, str):
            self._respond(slot, 200, payload, extra_headers=extra)
            return
        code = err.split(":", 1)[0]
        if code == "queue_full":
            # the fleet's backpressure contract, translated: the
            # smallest retry_after the routed replicas offered becomes
            # the HTTP pacing header
            self.server.count_throttle("backpressure")
            retry = row.get("retry_after")
            try:
                after = max(1, math.ceil(float(retry)))
            except (TypeError, ValueError):
                after = 1
            extra.append(("Retry-After", str(after)))
            self._respond(slot, 429, payload, extra_headers=extra)
        elif code == "bad_request":
            self._respond(slot, 400, payload, extra_headers=extra)
        elif code in _FEDERATION_DOWN_CODES:
            # router shutdown / a fleet with no dispatchable backend:
            # the edge stays up and says so honestly
            self._respond(slot, 503, payload, extra_headers=extra)
        else:
            self._respond(slot, 500, payload, extra_headers=extra)

    # -- response writing (loop thread, in arrival order) --

    def _new_slot(self, kind: str) -> dict:
        slot = {"kind": kind, "resp": None, "keep_alive": self.keep_alive}
        self.slots.append(slot)
        if not self.paused and len(self.slots) > _EDGE_HIGH:
            self.paused = True
            self.conn.pause_reading()
        return slot

    def _respond(
        self, slot: dict, code: int, payload: bytes,
        extra_headers=(), ctype: str = "application/json",
        burn: bool = False,
    ) -> None:
        if burn:
            # answer then burn: the framing after this request is
            # unknowable — parse nothing further, close once the
            # queued responses (this one included) have flushed
            self.burned = True
            slot["keep_alive"] = False
        slot["resp"] = (code, payload, tuple(extra_headers), ctype)
        self._flush()

    def _flush(self) -> None:
        while self.slots:
            head = self.slots[0]
            if head["resp"] is None:
                return  # in-order contract: wait for the head answer
            self.slots.popleft()
            code, payload, extra, ctype = head["resp"]
            close_after = not head["keep_alive"]
            parts = [
                f"HTTP/1.1 {code} {STATUS_TEXT[code]}\r\n"
                f"Content-Type: {ctype}\r\n"
                f"Content-Length: {len(payload)}\r\n"
            ]
            for name, value in extra:
                parts.append(f"{name}: {value}\r\n")
            if close_after:
                parts.append("Connection: close\r\n")
            parts.append("\r\n")
            head_bytes = "".join(parts).encode("utf-8")
            self.server.count_response(code)
            try:
                self.conn.write_bytes_on_loop(head_bytes + payload)
            except OSError:
                return  # client went away; _on_close drops the rest
            if close_after:
                self.conn.close_when_drained(5.0)
                return
            if self.paused and len(self.slots) < _EDGE_LOW:
                self.paused = False
                self.conn.resume_reading()


def _err_body(code: str, detail: str) -> bytes:
    return json.dumps({"error": f"{code}: {detail}"}).encode("utf-8")


def _history_params(query_string: str) -> dict:
    """Decode ``?series=…&window=…&fn=…`` into a TsdbStore.query params
    dict.  Labels ride as ``labels=name:value,name:value``; numeric
    fields convert here so the store only ever sees typed params (its
    own validation then covers ranges and vocabulary)."""
    params: dict = {}
    for key, value in urllib.parse.parse_qsl(
        query_string, keep_blank_values=True
    ):
        if key in ("series", "fn", "by", "match"):
            params[key] = value
        elif key in ("window", "q"):
            try:
                params[key] = float(value)
            except ValueError:
                raise ValueError(
                    f"{key} must be a number, got {value!r}"
                ) from None
        elif key == "limit":
            try:
                params[key] = int(value)
            except ValueError:
                raise ValueError(
                    f"limit must be an integer, got {value!r}"
                ) from None
        elif key == "list":
            params[key] = value.lower() not in ("", "0", "false", "no")
        elif key == "labels":
            labels: dict[str, str] = {}
            for pair in value.split(","):
                if not pair:
                    continue
                name, sep, lval = pair.partition(":")
                if not sep or not name:
                    raise ValueError(
                        f"labels pair {pair!r} is not name:value"
                    )
                labels[name] = lval
            params["labels"] = labels
        else:
            raise ValueError(f"unknown query parameter {key!r}")
    return params


def _bad_spec(detail: str) -> tuple:
    return (400, _err_body("bad_request", detail), (), "application/json")


def _job_submit(server: "HttpEdgeServer", body: bytes) -> tuple:
    """POST /jobs on an ops thread: decode the spec, stage an uploaded
    archive into the jobs dir (the manifest then references it through
    the ingest ``::*`` container grammar), validate, submit.  The edge
    records its submit span under the SAME trace id the job adopts, so
    the assembled tree runs edge -> executor -> stripes."""
    import base64
    import binascii

    from licensee_tpu.jobs.executor import validate_spec

    jobs = server.jobs
    try:
        row = json.loads(body.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError):
        return _bad_spec("body must be a JSON job spec")
    if not isinstance(row, dict):
        return _bad_spec("job spec must be a JSON object")
    archive_b64 = row.get("archive_b64")
    if archive_b64 is not None:
        if not isinstance(archive_b64, str):
            return _bad_spec("archive_b64 must be a base64 string")
        try:
            blob = base64.b64decode(archive_b64, validate=True)
        except (binascii.Error, ValueError):
            return _bad_spec("archive_b64 does not decode")
        name = row.get("archive_name")
        if not isinstance(name, str) or not name.strip():
            name = "archive.tar"
        saved = jobs.save_upload(name, blob)
        if "manifest" not in row:
            row = {**row, "manifest": [f"{saved}::*"]}
    spec, problem = validate_spec(row)
    if spec is None:
        return _bad_spec(problem)
    corpus_opt = (spec.get("options") or {}).get("corpus")
    if corpus_opt is not None:
        # fail the bad corpus source at submit time (400), not hours
        # later when a stripe crashes on it
        from licensee_tpu.corpus.artifact import (
            ArtifactError, check_corpus_source,
        )
        try:
            check_corpus_source(corpus_opt)
        except (ArtifactError, OSError) as exc:
            return _bad_spec(f"corpus: {exc}")
    trace_in = row.get("trace")
    tracer = server.router.obs.tracer
    trace = tracer.start(
        None,
        trace_id=(
            trace_in if isinstance(trace_in, str) and trace_in else None
        ),
    )
    try:
        job, created = jobs.submit(spec, trace_id=trace.trace_id)
    except RuntimeError as exc:
        tracer.finish(trace, "error")
        return (
            503, _err_body("jobs_disabled", str(exc)[:200]),
            (), "application/json",
        )
    trace.add_span(
        "edge.job_submit",
        time.perf_counter() - trace.t_start,
        t0=trace.t_start,
    )
    tracer.finish(trace, "ok" if created else "duplicate")
    resp = {
        "job_id": job.job_id,
        "state": job.state,
        "duplicate": not created,
    }
    extra = []
    if job.trace_id:
        resp["trace"] = job.trace_id
        extra.append(("X-Trace-Id", str(job.trace_id)))
    return (
        202 if created else 200,
        json.dumps(resp).encode("utf-8"),
        extra, "application/json",
    )


def _job_response(server: "HttpEdgeServer", route: str,
                  job_id: str | None, body: bytes) -> tuple:
    """One jobs verb on an ops thread -> (code, payload, headers,
    content type).  Unknown ids answer 404; results before completion
    answer 409 (poll the status verb); the merged JSONL and the
    container sidecar serve as raw bytes — the byte-identity contract
    with a direct ``batch-detect --stripes`` run is the whole point."""
    jobs = server.jobs
    if route == "job_submit":
        return _job_submit(server, body)
    status = jobs.status(job_id)
    if status is None:
        return (
            404, json.dumps(_JOB_NOT_FOUND).encode("utf-8"),
            (), "application/json",
        )
    extra = []
    trace = status.get("trace")
    if trace:
        extra.append(("X-Trace-Id", str(trace)))
    if route == "job_status":
        return (
            200, json.dumps(status).encode("utf-8"),
            extra, "application/json",
        )
    if route == "job_cancel":
        row = jobs.cancel(job_id) or status
        return (
            202, json.dumps(row).encode("utf-8"),
            extra, "application/json",
        )
    if status.get("state") != "completed":
        row = dict(_JOB_NOT_DONE)
        row["state"] = status.get("state")
        return (
            409, json.dumps(row).encode("utf-8"),
            extra, "application/json",
        )
    results = jobs.results_path(job_id)
    if route == "job_containers":
        try:
            with open(f"{results}.containers.jsonl", "rb") as f:
                payload = f.read()
        except OSError:
            payload = b""  # loose-file jobs have no container sidecar
        return (200, payload, extra, "application/jsonl")
    try:
        with open(results, "rb") as f:
            payload = f.read()
    except OSError as exc:
        return (
            500, _err_body("internal_error", str(exc)[:200]),
            (), "application/json",
        )
    return (200, payload, extra, "application/jsonl")


def _corpus_upload(server: "HttpEdgeServer", client: str | None,
                   body: bytes) -> tuple:
    """POST /corpus on an ops thread -> (code, payload).  The verdict
    already proved the client maps to a tenant; here the artifact
    bytes decode, the onboarding pipeline runs (stage -> validate ->
    journal -> roll -> persist), and OnboardError codes map onto HTTP
    statuses: invalid artifacts 400, a roll already in flight 409
    (retryable), a failed roll 500."""
    import base64
    import binascii

    from licensee_tpu.tenancy import OnboardError

    try:
        row = json.loads(body.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError):
        return (400, _err_body("bad_request",
                               "body must be a JSON corpus upload"))
    if not isinstance(row, dict):
        return (400, _err_body("bad_request",
                               "corpus upload must be a JSON object"))
    artifact_b64 = row.get("artifact_b64")
    if not isinstance(artifact_b64, str) or not artifact_b64:
        return (400, _err_body(
            "bad_request", "artifact_b64 must be a base64 string"
        ))
    try:
        blob = base64.b64decode(artifact_b64, validate=True)
    except (binascii.Error, ValueError):
        return (400, _err_body("bad_request",
                               "artifact_b64 does not decode"))
    name = row.get("name")
    if name is not None and not isinstance(name, str):
        return (400, _err_body("bad_request", "name must be a string"))
    try:
        result = server.tenancy.upload(client, blob, name)
    except OnboardError as exc:
        if exc.code == "unknown_tenant":
            return (403, json.dumps(_UNKNOWN_TENANT).encode("utf-8"))
        if exc.code == "corpus_invalid":
            return (400, json.dumps(
                {"error": f"corpus_invalid: {exc.detail}"}
            ).encode("utf-8"))
        if exc.code == "fleet_reload_in_progress":
            return (409, _err_body(exc.code, exc.detail[:200]))
        return (500, _err_body("internal_error", str(exc)[:200]))
    return (200, json.dumps({"corpus": result}).encode("utf-8"))


def _echo_headers(text: str) -> list[tuple[str, str]]:
    out = []
    trace = _field_from_line(text, "trace")
    if trace:
        out.append(("X-Trace-Id", trace))
    corpus = _field_from_line(text, "corpus")
    if corpus:
        out.append(("X-Corpus", corpus))
    return out


class HttpEdgeServer(LoopJsonlServer):
    """The network edge listener: usually an AF_INET target
    (``host:port``) on the router's own event loop, every connection an
    :class:`_EdgeSession`.  Owns the cross-session policy state — auth
    tokens, per-client token buckets, and the DRR dispatch queue —
    all loop-thread-only, no locks.

    ``tokens`` maps bearer token -> client name (None disables auth:
    every client is its peer address).  ``rate_per_client``/``burst``
    shape each client's token bucket; ``quantum_bytes`` is the DRR
    quantum; ``max_inflight`` bounds concurrent dispatches into the
    router (admitted-but-waiting requests sit in the fair queue, not
    in the router's admission queue, so one greedy client cannot fill
    the shared funnel)."""

    def __init__(
        self,
        target: str,
        router,
        *,
        tokens: dict[str, str] | None = None,
        rate_per_client: float = 1000.0,
        burst: float | None = None,
        quantum_bytes: int = 8192,
        max_inflight: int = 1024,
        max_body_bytes: int = 1 << 20,
        max_job_body_bytes: int = 32 << 20,
        stall_timeout_s: float = 30.0,
        jobs=None,
        tenancy=None,
    ):
        self.router = router
        # the jobs tier (licensee_tpu.jobs.JobExecutor), or None: the
        # /jobs routes then answer 503 jobs_disabled
        self.jobs = jobs
        # the tenancy tier (licensee_tpu.tenancy.CorpusOnboarder), or
        # None: POST /corpus answers 503 tenancy_disabled and content
        # dispatch carries no client-derived pool
        self.tenancy = tenancy
        router.loop.start()  # idempotent; the loop must carry accepts
        super().__init__(
            target, loop=router.loop, stall_timeout_s=stall_timeout_s
        )
        self.tokens = dict(tokens) if tokens else None
        self.rate_per_client = float(rate_per_client)
        self.burst = float(
            burst if burst is not None else max(1.0, rate_per_client)
        )
        self.quantum_bytes = int(quantum_bytes)
        self.max_inflight = int(max_inflight)
        self.max_body_bytes = int(max_body_bytes)
        self.max_job_body_bytes = int(max_job_body_bytes)
        # DRR state (loop-thread only)
        self._queues: dict[str, deque[_EdgeRequest]] = {}
        self._ring: deque[str] = deque()
        self._deficit: dict[str, float] = {}
        self._buckets: dict[str, _TokenBucket] = {}
        self._inflight = 0
        self._queued = 0
        self._pumping = False
        self._register_metrics()

    # -- metrics --

    def _register_metrics(self) -> None:
        reg = self.router.obs.registry
        requests = reg.counter(
            "edge_http_requests_total",
            "HTTP edge responses by status code",
            labels=("code",),
        )
        # children resolved once per code: family.labels() is a dict
        # build per call, measurable at saturation on the loop thread
        children: dict = {}

        def count_response(code: int) -> None:
            child = children.get(code)
            if child is None:
                child = children[code] = requests.labels(code=str(code))
            child.inc()

        self.count_response = count_response
        throttled = reg.counter(
            "edge_http_throttled_total",
            "HTTP edge throttle events (auth, rate_limit, backpressure)",
            labels=("reason",),
        )
        t_children: dict = {}

        def count_throttle(reason: str) -> None:
            child = t_children.get(reason)
            if child is None:
                child = t_children[reason] = throttled.labels(
                    reason=reason
                )
            child.inc()

        self.count_throttle = count_throttle
        reg.gauge(
            "edge_http_connections",
            "Open HTTP edge connections",
        ).set_fn(self.connection_count)
        reg.gauge(
            "edge_queue_depth",
            "Requests parked in the edge's per-client DRR fair queue",
        ).set_fn(lambda: self._queued)
        reg.gauge(
            "edge_inflight",
            "Edge requests currently dispatched into the router",
        ).set_fn(lambda: self._inflight)

    # -- per-client state (loop thread) --

    def bucket_for(self, client: str) -> _TokenBucket:
        bucket = self._buckets.get(client)
        if bucket is None:
            bucket = self._buckets[client] = _TokenBucket(
                self.rate_per_client, self.burst
            )
        return bucket

    # -- DRR fair queue (loop thread) --

    def enqueue(self, item: _EdgeRequest) -> None:
        client = item.client
        queue = self._queues.get(client)
        if queue is None:
            queue = self._queues[client] = deque()
            self._ring.append(client)
        queue.append(item)
        self._queued += 1
        self._pump()

    def _pump(self) -> None:
        """Drain the fair queue into the router: classic deficit
        round-robin — each ring visit grants the client one quantum of
        body-byte credit, requests dispatch while credit and the
        ``max_inflight`` bound allow, and an emptied client leaves the
        ring with its credit forfeited (the DRR anti-hoarding rule).
        Iterative and re-entrancy-guarded: router answers landing
        synchronously re-enter via their completion callback."""
        if self._pumping:
            return
        self._pumping = True
        try:
            while self._ring and self._inflight < self.max_inflight:
                client = self._ring[0]
                queue = self._queues.get(client)
                if not queue:
                    self._ring.popleft()
                    self._queues.pop(client, None)
                    self._deficit.pop(client, None)
                    continue
                credit = self._deficit.get(client, 0.0) + self.quantum_bytes
                while (
                    queue
                    and credit >= queue[0].cost
                    and self._inflight < self.max_inflight
                ):
                    item = queue.popleft()
                    self._queued -= 1
                    credit -= item.cost
                    self._dispatch(item)
                if queue:
                    self._deficit[client] = credit
                    self._ring.rotate(-1)
                    if self._inflight >= self.max_inflight:
                        return
                else:
                    self._ring.popleft()
                    self._queues.pop(client, None)
                    self._deficit.pop(client, None)
        finally:
            self._pumping = False

    def _dispatch(self, item: _EdgeRequest) -> None:
        session = item.session
        if session.closed:
            return  # the client left while queued: answer nobody
        # a BURNED session still owes every response admitted before
        # the burn: the 400 that closes the connection is queued
        # behind them, and an unfilled earlier slot would strand it
        # (answer-then-burn is the framing contract)
        self._inflight += 1

        def on_done(row, text=None) -> None:
            self._inflight -= 1
            if not session.closed:
                session.fill_content(item.slot, row, text)
            self._pump()

        self.router._submit(None, item.line, on_done, pool=item.pool)

    # -- connections --

    def handle_connection(self, sock) -> None:
        try:
            peer = sock.getpeername()
        except OSError:
            peer = None
        peer_name = (
            peer[0] if isinstance(peer, tuple) and peer else "local"
        )
        conn = LineConn(
            self.loop, sock, on_line=drop_line, on_close=drop_close,
            max_line_bytes=64 << 10,
        )
        self.track_connection(conn)
        _EdgeSession(self, conn, peer_name)
