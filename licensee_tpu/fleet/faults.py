"""Fault injection for the fleet: kill / hang / slow-walk live worker
processes, plus a protocol-faithful STUB WORKER for harness runs that
don't need a device path.

The faults are real OS-level faults against real processes — SIGKILL
(crash), SIGSTOP (wedge: alive but silent), and a SIGSTOP/SIGCONT duty
cycle (slow-walk: the brownout that health checks miss but tail
latency exposes) — plus :class:`Slowloris`, the slow/partial-WRITER
client (dribbled bytes, or a half-close mid-line) that a correct
event-loop server must reap without spending a thread or a pool slot
on it.  The selftest (fleet/selftest.py) drives them under live
traffic and asserts the client never sees an error.

The stub worker (``python -m licensee_tpu.fleet.faults --socket P``)
speaks the serve JSONL contract — content rows, ``stats``/``trace``/
``reload`` verbs, trace-ID adoption, ``queue_full`` shedding, corpus
fingerprints on stats and content rows — with configurable misbehavior
(``--service-ms``, ``--hang-after``, ``--exit-after``, ``--queue-full``,
``--fingerprint``, ``--reload-deny``, and scripted reload values:
``slow:MS:FP`` sleeps mid-swap, ``fail:``/``corrupt:`` refuse,
``hang`` wedges), so router/supervisor tests and the rolling-upgrade
drills exercise real processes, real sockets, and real SIGKILL in
milliseconds instead of paying a JAX import per worker.

House rules (script/lint): monotonic clocks only, no print — the stub
talks through its socket and reports errors on stderr.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import signal
import socket
import socketserver
import sys
import threading
import time
from collections import deque

from licensee_tpu.obs.flight import (
    FlightRecorder,
    flight_path_for_socket,
)
from licensee_tpu.serve.eventloop import parse_target


def kill(pid: int) -> None:
    """The crash fault: SIGKILL, no cleanup, no goodbye — the worker's
    socket file stays behind (the stale-socket fix reclaims it)."""
    os.kill(pid, signal.SIGKILL)


def hang(pid: int) -> None:
    """The wedge fault: SIGSTOP freezes the process mid-whatever; it
    stays alive (poll() sees nothing) but answers no probe."""
    os.kill(pid, signal.SIGSTOP)


def resume(pid: int) -> None:
    os.kill(pid, signal.SIGCONT)


def _dial_stream(
    target: str, timeout_s: float | None = None
) -> socket.socket:
    """Blocking harness-side dial of a parse_target target: the right
    address family, TCP_NODELAY on AF_INET, connected (or OSError).
    The load generators and fault clients all go through here so every
    drill runs unchanged against Unix sockets and TCP endpoints."""
    kind, addr = parse_target(target)
    family = socket.AF_INET if kind == "tcp" else socket.AF_UNIX
    sock = socket.socket(family, socket.SOCK_STREAM)
    try:
        if timeout_s is not None:
            sock.settimeout(timeout_s)
        if kind == "tcp":
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sock.connect(addr if kind == "tcp" else target)
    except OSError:
        sock.close()
        raise
    return sock


class SlowWalker:
    """The brownout fault: duty-cycle SIGSTOP/SIGCONT so the worker
    still answers — eventually.  ``duty`` is the STOPPED fraction of
    each ``period_s``."""

    def __init__(self, pid: int, *, duty: float = 0.8,
                 period_s: float = 0.1):
        if not (0.0 < duty < 1.0):
            raise ValueError(f"duty must be in (0, 1), got {duty!r}")
        self.pid = pid
        self.duty = float(duty)
        self.period_s = float(period_s)
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._walk, name="fleet-slowwalk", daemon=True
        )
        self._thread.start()

    def _walk(self) -> None:
        while not self._stop.is_set():
            try:
                os.kill(self.pid, signal.SIGSTOP)
                if self._stop.wait(self.period_s * self.duty):
                    break
                os.kill(self.pid, signal.SIGCONT)
                if self._stop.wait(self.period_s * (1.0 - self.duty)):
                    break
            except ProcessLookupError:
                return  # the victim died: nothing left to torment
        try:
            os.kill(self.pid, signal.SIGCONT)  # never leave it frozen
        except ProcessLookupError:
            pass

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join()
            self._thread = None


class Slowloris:
    """The slow/partial-writer fault against a JSONL socket server: a
    client that starts a request line and never finishes it.

    ``mode="dribble"`` sends one byte of a request every
    ``byte_interval_s`` — forever mid-line, never a newline.
    ``mode="half_close"`` sends half a line then shuts down its write
    side (the torn client).  Either way a correct event-loop server
    must REAP the connection once the partial line has stalled past its
    ``stall_timeout_s`` — without holding a session, a thread, or a
    backend pool slot meanwhile.

    ``run()`` blocks until the server closes the connection or
    ``give_up_s`` passes, and returns ``{"reaped", "elapsed_s",
    "sent_bytes"}`` — the selftest's gate is ``reaped=True`` while
    normal traffic on OTHER connections kept answering.

    ``path`` is a parse_target target (Unix path or ``host:port``) and
    ``payload`` the never-finished request — the default is a JSONL
    content row; the HTTP edge drill dribbles a header block instead
    (same sweep, same reap)."""

    def __init__(self, path: str, *, mode: str = "dribble",
                 byte_interval_s: float = 0.2, give_up_s: float = 30.0,
                 payload: bytes | None = None):
        if mode not in ("dribble", "half_close"):
            raise ValueError(f"unknown slowloris mode {mode!r}")
        self.path = path
        self.mode = mode
        self.byte_interval_s = float(byte_interval_s)
        self.give_up_s = float(give_up_s)
        self.payload = (
            payload if payload is not None
            else b'{"content": "never finished'
        )

    def run(self) -> dict:
        import socket as socketlib

        payload = self.payload
        sent = 0
        t0 = time.perf_counter()
        sock = None
        try:
            sock = _dial_stream(self.path, timeout_s=self.give_up_s)
            if self.mode == "half_close":
                sock.sendall(payload)
                sent = len(payload)
                sock.shutdown(socketlib.SHUT_WR)
            deadline = t0 + self.give_up_s
            poll_s = (
                self.byte_interval_s if self.mode == "dribble" else 0.2
            )
            while time.perf_counter() < deadline:
                if self.mode == "dribble":
                    try:
                        sock.sendall(payload[sent % len(payload):][:1])
                        sent += 1
                    except OSError:
                        # EPIPE/reset on send: the server dropped us
                        return self._result(True, t0, sent)
                # a read tells us whether the server hung up: EOF (or
                # reset) == reaped; timeout == still tolerated
                sock.settimeout(poll_s)
                try:
                    if sock.recv(4096) == b"":
                        return self._result(True, t0, sent)
                    # any actual bytes would be a protocol violation —
                    # the server must never answer a half request; keep
                    # watching, the gate is the close
                except socketlib.timeout:
                    continue
                except OSError:
                    return self._result(True, t0, sent)
            return self._result(False, t0, sent)
        except OSError:
            return self._result(False, t0, sent)
        finally:
            if sock is not None:
                sock.close()

    def _result(self, reaped: bool, t0: float, sent: int) -> dict:
        return {
            "reaped": reaped,
            "elapsed_s": round(time.perf_counter() - t0, 3),
            "sent_bytes": sent,
        }


def open_loop_client(
    path: str,
    rate: float,
    duration_s: float,
    timeout_s: float = 30.0,
) -> dict:
    """One open-loop JSONL client connection for the saturation bench:
    request lines go out at a fixed TARGET RATE regardless of how the
    server is doing (real-traffic arrival — a struggling server does
    not slow its users down), responses are counted (and latency-
    stamped) from raw chunks.  Runs as a SUBPROCESS (``python -m
    licensee_tpu.fleet.faults --open-loop-client ...``) so the load
    generator never shares the router process's GIL — in-process client
    threads were the measurement fighting the measured.

    Returns ``{"sent", "answered", "stalled", "elapsed_s",
    "send_elapsed_s", "lats_ms"}`` — per-request latencies in
    milliseconds, matched to send stamps by response order (the session
    answers in request order).  ``send_elapsed_s`` covers only the send
    window: ``sent / send_elapsed_s`` is the OFFERED arrival rate,
    while ``elapsed_s`` additionally spans the queue drain after the
    last send."""
    line = (json.dumps({"content": "saturation probe"}) + "\n").encode(
        "utf-8"
    )
    stamps: deque = deque()
    lats: list[float] = []
    state = {"sent": 0, "answered": 0, "stalled": False}
    final: dict = {"n": None}
    t0 = time.perf_counter()
    sock = None
    try:
        try:
            sock = _dial_stream(path, timeout_s=timeout_s)
        except OSError:
            state["stalled"] = True
            return {**state, "elapsed_s": 0.0, "lats_ms": []}

        def read_loop() -> None:
            # responses are ordered per session: counting newlines in
            # raw chunks matches them to send stamps without a readline
            # (or a parse) per row
            while True:
                if final["n"] is not None and state["answered"] >= final["n"]:
                    return
                try:
                    chunk = sock.recv(1 << 16)
                except OSError:  # timeout: a stalled client
                    state["stalled"] = True
                    return
                if not chunk:
                    state["stalled"] = True
                    return
                k = chunk.count(b"\n")
                if k:
                    now = time.perf_counter()
                    for _ in range(k):
                        lats.append((now - stamps.popleft()) * 1000.0)
                    state["answered"] += k

        reader = threading.Thread(target=read_loop, daemon=True)
        reader.start()
        tick_s = 0.01
        per_tick = rate * tick_s
        credit = 0.0
        next_tick = t0
        try:
            while time.perf_counter() - t0 < duration_s:
                credit += per_tick
                n = int(credit)
                credit -= n
                if n:
                    now = time.perf_counter()
                    stamps.extend([now] * n)
                    state["sent"] += n
                    sock.sendall(line * n)  # the tick's burst, one write
                next_tick += tick_s
                delay = next_tick - time.perf_counter()
                if delay > 0:
                    time.sleep(delay)
            # the drain sentinel: sent AFTER the loop, then final["n"]
            # is armed — the reader always has one more response coming
            # and exits exactly when everything (sentinel included)
            # answered
            stamps.append(time.perf_counter())
            sock.sendall(line)
            state["sent"] += 1
        except OSError:
            state["stalled"] = True
        send_elapsed = time.perf_counter() - t0
        final["n"] = state["sent"]
        reader.join(timeout=timeout_s + 5.0)
        if reader.is_alive() or state["answered"] < state["sent"]:
            state["stalled"] = True
        return {
            **state,
            "elapsed_s": round(time.perf_counter() - t0, 4),
            "send_elapsed_s": round(send_elapsed, 4),
            "lats_ms": [round(x, 2) for x in lats],
        }
    finally:
        try:
            if sock is not None:
                sock.close()
        except OSError:
            pass


# an HTTP/1.1 status line's head: how responses are counted without a
# full parse (response bodies are JSON rows — the marker cannot appear
# inside one)
_HTTP_STATUS_RE = re.compile(rb"HTTP/1\.[01] (\d{3})")


def open_loop_http_client(
    path: str,
    rate: float,
    duration_s: float,
    token: str | None = None,
    timeout_s: float = 30.0,
) -> dict:
    """The HTTP twin of :func:`open_loop_client` for the edge
    saturation bench: pipelined keep-alive ``POST /classify`` requests
    at a fixed TARGET RATE on one TCP connection, responses counted
    (and latency-stamped, matched by order — HTTP/1.1 answers in
    request order) from status lines in raw chunks.  Also a
    SUBPROCESS, for the same GIL-isolation reason.  Returns the
    open_loop_client dict plus ``non_200`` (any non-200 status fails
    the rung — the edge contract under saturation is 200 or a paced
    429, and the bench offers under the admission cap)."""
    body = json.dumps({"content": "saturation probe"}).encode("utf-8")
    auth = f"Authorization: Bearer {token}\r\n" if token else ""
    line = (
        f"POST /classify HTTP/1.1\r\n"
        f"Host: edge\r\n{auth}"
        f"Content-Type: application/json\r\n"
        f"Content-Length: {len(body)}\r\n\r\n"
    ).encode("utf-8") + body
    stamps: deque = deque()
    lats: list[float] = []
    state = {"sent": 0, "answered": 0, "non_200": 0, "stalled": False}
    final: dict = {"n": None}
    t0 = time.perf_counter()
    sock = None
    try:
        try:
            sock = _dial_stream(path, timeout_s=timeout_s)
        except OSError:
            state["stalled"] = True
            return {**state, "elapsed_s": 0.0, "lats_ms": []}

        def read_loop() -> None:
            tail = b""
            while True:
                if (
                    final["n"] is not None
                    and state["answered"] >= final["n"]
                ):
                    return
                try:
                    chunk = sock.recv(1 << 16)
                except OSError:  # timeout: a stalled client
                    state["stalled"] = True
                    return
                if not chunk:
                    state["stalled"] = True
                    return
                buf = tail + chunk
                k = 0
                last_end = 0
                for m in _HTTP_STATUS_RE.finditer(buf):
                    k += 1
                    last_end = m.end()
                    if m.group(1) != b"200":
                        state["non_200"] += 1
                # keep only unmatched trailing bytes (a status line
                # split across chunks) — never bytes of a counted match
                tail = buf[max(last_end, len(buf) - 11):]
                if k:
                    now = time.perf_counter()
                    for _ in range(min(k, len(stamps))):
                        lats.append((now - stamps.popleft()) * 1000.0)
                    state["answered"] += k

        reader = threading.Thread(target=read_loop, daemon=True)
        reader.start()
        tick_s = 0.01
        per_tick = rate * tick_s
        credit = 0.0
        next_tick = t0
        try:
            while time.perf_counter() - t0 < duration_s:
                credit += per_tick
                n = int(credit)
                credit -= n
                if n:
                    now = time.perf_counter()
                    stamps.extend([now] * n)
                    state["sent"] += n
                    sock.sendall(line * n)
                next_tick += tick_s
                delay = next_tick - time.perf_counter()
                if delay > 0:
                    time.sleep(delay)
            stamps.append(time.perf_counter())
            sock.sendall(line)  # the drain sentinel
            state["sent"] += 1
        except OSError:
            state["stalled"] = True
        send_elapsed = time.perf_counter() - t0
        final["n"] = state["sent"]
        reader.join(timeout=timeout_s + 5.0)
        if reader.is_alive() or state["answered"] < state["sent"]:
            state["stalled"] = True
        return {
            **state,
            "elapsed_s": round(time.perf_counter() - t0, 4),
            "send_elapsed_s": round(send_elapsed, 4),
            "lats_ms": [round(x, 2) for x in lats],
        }
    finally:
        try:
            if sock is not None:
                sock.close()
        except OSError:
            pass


def _client_main(argv) -> int:
    parser = argparse.ArgumentParser(
        prog="licensee-tpu-open-loop-client",
        description="Open-loop saturation client (bench harness)",
    )
    parser.add_argument("--open-loop-client", metavar="TARGET")
    parser.add_argument("--open-loop-http", metavar="TARGET")
    parser.add_argument("--rate", type=float, required=True)
    parser.add_argument("--duration-s", type=float, required=True)
    parser.add_argument("--timeout-s", type=float, default=30.0)
    parser.add_argument("--token", default=None)
    args = parser.parse_args(argv)
    if args.open_loop_http:
        out = open_loop_http_client(
            args.open_loop_http, args.rate, args.duration_s,
            token=args.token, timeout_s=args.timeout_s,
        )
    else:
        out = open_loop_client(
            args.open_loop_client, args.rate, args.duration_s,
            timeout_s=args.timeout_s,
        )
    sys.stdout.write(json.dumps(out) + "\n")
    return 0


# -- the stub worker ---------------------------------------------------


class _StubState:
    """Shared across stub sessions: counters, the trace ring, and the
    scripted misbehavior."""

    def __init__(self, args):
        self.args = args
        self.name = args.name
        self.t0 = time.perf_counter()
        self.lock = threading.Lock()
        self.completed = 0
        self.in_flight = 0
        self.admitted = 0
        # --slow-span START:COUNT:MS parsed once: (start, count, ms)
        self.slow_span = None
        if getattr(args, "slow_span", None):
            start, count, ms = args.slow_span.split(":")
            self.slow_span = (int(start), int(count), float(ms))
        # 256 deep: the SIGKILL drill assembles a failover trace from
        # this tail AFTER the remaining stream drained onto the
        # surviving worker — 64 evicted the evidence
        self.traces: deque = deque(maxlen=256)
        self.hang_forever = threading.Event()
        # the stub keeps a real flight recorder (obs/flight.py) on the
        # same black-box convention as a serve worker, so the fleet
        # drills exercise the supervisor's SIGKILL harvest in
        # milliseconds without a JAX boot
        self.flight = FlightRecorder(
            flight_path_for_socket(args.socket),
            proc=args.name,
            flush_interval_s=0.05,
        ).start()
        # the corpus-lifecycle twin: a fingerprint/source pair the
        # reload verb swaps, echoed on stats and content rows exactly
        # like a real serve worker — the fleet reload drills ride this
        self.fingerprint = args.fingerprint
        self.corpus_source = args.fingerprint
        self.reloads = 0
        self.reload_lock = threading.Lock()


def _stub_reload(state: _StubState, msg: dict) -> dict | None:
    """The stub's reload verb, protocol-identical to a serve worker's:

    * any value -> swap to that value as the new fingerprint+source;
    * ``slow:<ms>:<value>`` -> sleep mid-swap first (the window the
      SIGKILL-mid-swap drill aims at);
    * ``fail:...`` / ``corrupt:...`` or a value matching
      ``--reload-deny`` -> refuse like a failed validation gate, keep
      the old fingerprint;
    * ``hang`` -> never answer (the wedge);
    * a second concurrent reload -> ``reload_in_progress``."""
    rid = msg.get("id")
    corpus = msg.get("corpus")
    if not isinstance(corpus, str) or not corpus:
        return {"id": rid,
                "error": "bad_request: reload needs a 'corpus' "
                "source string"}
    if not state.reload_lock.acquire(blocking=False):
        return {"id": rid, "error": "reload_in_progress"}
    try:
        if corpus.startswith("slow:"):
            _, ms, corpus = corpus.split(":", 2)
            time.sleep(float(ms) / 1000.0)
        if corpus == "hang":
            return None
        deny = state.args.reload_deny
        if corpus.startswith(("fail:", "corrupt:")) or (
            deny and corpus.startswith(deny)
        ):
            return {
                "id": rid,
                "error": f"reload_failed: injected refusal of {corpus!r}",
                "problems": [f"injected refusal of {corpus!r}"],
            }
        with state.lock:
            previous = state.fingerprint
            state.fingerprint = corpus
            state.corpus_source = corpus
            state.reloads += 1
        state.flight.record(
            "reload_swap", fingerprint=corpus, previous=previous
        )
        return {
            "id": rid,
            "reload": {
                "ok": True,
                "fingerprint": corpus,
                "previous": previous,
                "unchanged": corpus == previous,
                "source": corpus,
            },
        }
    finally:
        state.reload_lock.release()


def _stub_answer(state: _StubState, msg: dict) -> dict | None:
    """One stub response row; None hangs the session (the wedge)."""
    args = state.args
    rid = msg.get("id")
    op = msg.get("op")
    if op == "stats":
        with state.lock:
            completed, in_flight = state.completed, state.in_flight
            fingerprint = state.fingerprint
            source = state.corpus_source
            reloads = state.reloads
        if msg.get("format") == "prometheus":
            text = (
                "# HELP stub_requests_total Stub worker requests.\n"
                "# TYPE stub_requests_total counter\n"
                f"stub_requests_total {completed}\n"
            )
            return {"id": rid, "prometheus": text}
        return {
            "id": rid,
            "stats": {
                "uptime_s": round(time.perf_counter() - state.t0, 3),
                "worker": state.name,
                "corpus": {
                    "fingerprint": fingerprint,
                    "source": source,
                    "reloads": reloads,
                },
                "scheduler": {
                    "queue_depth": args.report_load,
                    "in_flight": in_flight,
                    "completed": completed,
                },
            },
        }
    if op == "reload":
        return _stub_reload(state, msg)
    if op == "trace":
        with state.lock:
            tail = list(state.traces)[-int(msg.get("n", 20)):]
        return {"id": rid, "traces": tail}
    if op == "diff":
        # protocol-faithful, semantically canned (like the stub's
        # verdict rows): the real worker's word-diff verb answers a
        # "diff" object keyed by the comparison target, echoing the
        # router-spliced trace for the pipelining cross-check
        row = {
            "id": rid,
            "diff": {
                "key": "stub-mit",
                "similarity": 0.99,
                "identical": False,
                "diff": "{+stub+}",
            },
        }
        if msg.get("trace"):
            row["trace"] = msg["trace"]
        return row
    if op is not None:
        return {"id": rid, "error": f"bad_request: unknown op {op!r}"}
    # a content row
    if args.queue_full:
        state.flight.record("error", what="queue_full", id=rid)
        return {"id": rid, "error": "queue_full", "retry_after": 0.05}
    state.flight.record("admission", id=rid, trace=msg.get("trace"))
    with state.lock:
        state.in_flight += 1
        state.admitted += 1
        n_admit = state.admitted
    # the scripted latency fault: inside the --slow-span window this
    # row serves at the fault latency, not --service-ms — admission
    # order (not completion order) picks the victims so concurrent
    # rows cannot shrink the span
    delay_ms = args.service_ms
    span = state.slow_span
    if span is not None and span[0] < n_admit <= span[0] + span[1]:
        delay_ms = span[2]
    try:
        if delay_ms:
            time.sleep(delay_ms / 1000.0)
        with state.lock:
            state.completed += 1
            n = state.completed
            trace_id = msg.get("trace")
            if trace_id:
                # the same tail-row shape a real worker's tracer
                # serves: kind/proc tags + a dur so the fleet
                # collector joins and attributes without heuristics
                state.traces.append({
                    "trace": trace_id, "id": rid, "kind": "trace",
                    "proc": state.name, "status": "ok",
                    "dur_ms": float(delay_ms),
                    "spans": [{"name": "stub_serve", "t_ms": 0.0,
                               "dur_ms": float(delay_ms)}],
                })
    finally:
        with state.lock:
            state.in_flight -= 1
    if args.hang_after and n > args.hang_after:
        return None  # N answers delivered; silence from here on (wedge)
    if args.exit_after and n >= args.exit_after:
        # crash AFTER answering: the next request finds a dead socket
        threading.Timer(0.05, os._exit, args=(41,)).start()
    with state.lock:
        fingerprint = state.fingerprint
    row = {
        "id": rid, "key": "stub-mit", "matcher": "stub",
        "confidence": 99.0, "cached": False, "stub_worker": state.name,
        # one fingerprint per answer, like a real worker's corpus field
        "corpus": fingerprint,
    }
    if msg.get("trace"):
        row["trace"] = msg["trace"]
    return row


class _StubServer(socketserver.ThreadingMixIn,
                  socketserver.UnixStreamServer):
    daemon_threads = True
    allow_reuse_address = True


class _StubTcpServer(socketserver.ThreadingMixIn,
                     socketserver.TCPServer):
    """The stub worker on an AF_INET listener (``--socket host:port``)
    — the TCP federation drills supervise stubs over loopback TCP with
    the exact machinery the Unix-socket drills use."""

    daemon_threads = True
    allow_reuse_address = True


class _StubHandler(socketserver.StreamRequestHandler):
    def handle(self) -> None:
        # responses are coalesced per read-batch — one sendall carries
        # every answer the batch produced, exactly like the real
        # worker's event-loop transport (serve/eventloop.py flushes
        # writes once per loop pass).  Per-line flushing made the STUB
        # the syscall bottleneck of the router saturation bench.
        state: _StubState = self.server.state
        sock = self.connection
        if sock.family == socket.AF_INET:
            try:
                # coalesced batch responses must not sit out a Nagle
                # delay against the router's pipelined reads
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            except OSError:
                pass
        buf = bytearray()
        while True:
            try:
                chunk = sock.recv(65536)
            except OSError:
                return
            if not chunk:
                return
            buf += chunk
            if b"\n" not in chunk:
                continue
            *lines, rest = buf.split(b"\n")
            buf = bytearray(rest)
            out = bytearray()
            for raw in lines:
                line = raw.decode("utf-8", errors="replace").strip()
                if not line:
                    continue
                try:
                    msg = json.loads(line)
                except ValueError:
                    msg = {}
                row = _stub_answer(state, msg)
                if row is None:
                    # wedge: answers already produced still flush —
                    # same client view as the per-line writer gave
                    if out:
                        try:
                            sock.sendall(out)
                        except OSError:
                            return
                    state.hang_forever.wait()  # wedged, forever
                    return
                out += json.dumps(row).encode("utf-8") + b"\n"
            if out:
                try:
                    sock.sendall(out)
                except OSError:
                    return


def stub_main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="licensee-tpu-stub-worker",
        description="Protocol-faithful stub serve worker (fault harness)",
    )
    parser.add_argument("--socket", required=True)
    parser.add_argument("--name", default="stub")
    parser.add_argument("--service-ms", type=float, default=0.0)
    parser.add_argument(
        "--report-load", type=int, default=0,
        help="Static queue_depth to report in stats (routing tests)",
    )
    parser.add_argument(
        "--hang-after", type=int, default=0,
        help="After N answers, stop responding (stay alive): the wedge",
    )
    parser.add_argument(
        "--exit-after", type=int, default=0,
        help="After N answers, exit(41): the scripted crash",
    )
    parser.add_argument(
        "--queue-full", action="store_true",
        help="Answer every content row with queue_full backpressure",
    )
    parser.add_argument(
        "--fingerprint", default="stub-fp-0",
        help="The corpus fingerprint/source this stub reports until a "
        "reload verb swaps it (the corpus-lifecycle drills)",
    )
    parser.add_argument(
        "--reload-deny", default=None, metavar="PREFIX",
        help="Refuse reload verbs whose corpus value starts with "
        "PREFIX (the per-worker validation-failure script)",
    )
    parser.add_argument(
        "--slow-span", default=None, metavar="START:COUNT:MS",
        help="Scripted latency fault: after the START-th admitted "
        "content row, the next COUNT rows serve in MS milliseconds "
        "instead of --service-ms (the telemetry-plane p99 drill)",
    )
    args = parser.parse_args(argv)
    kind, addr = parse_target(args.socket)
    try:
        if kind == "tcp":
            server = _StubTcpServer(addr, _StubHandler)
        else:
            if os.path.exists(args.socket):
                os.unlink(args.socket)
            server = _StubServer(args.socket, _StubHandler)
    except OSError as exc:
        sys.stderr.write(f"stub worker: cannot bind: {exc}\n")
        return 1
    server.state = _StubState(args)
    try:
        server.serve_forever(poll_interval=0.1)
    except KeyboardInterrupt:
        pass
    finally:
        server.server_close()
        server.state.flight.stop()  # the clean-shutdown black box
        if kind == "unix":
            try:
                os.unlink(args.socket)
            except OSError:
                pass
    return 0


if __name__ == "__main__":
    if "--open-loop-client" in sys.argv or "--open-loop-http" in sys.argv:
        sys.exit(_client_main(sys.argv[1:]))
    sys.exit(stub_main())
