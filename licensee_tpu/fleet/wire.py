"""Fleet-side JSONL wire helpers: one-shot control requests (health
probes, scrapes) and a per-backend connection pool for the router's
request path.

Every worker speaks the serve transport (serve/server.py): one JSON
object per line in, one per line out, in request order.  The fleet tier
talks to workers over the same contract — a probe is just a session of
one ``{"op": "stats"}`` line, and a routed request is a session of one
classification line.  Pooled connections carry ONE in-flight request at
a time, so the worker's in-order response guarantee is trivially the
router's per-request correctness; a sick connection is closed, never
reused.

House rules (script/lint): monotonic clocks only, no print.
"""

from __future__ import annotations

import json
import socket
import threading
from collections import deque


class WireError(OSError):
    """The backend could not answer: connect/send/recv failed or timed
    out, or the response line was not JSON.  The router treats every
    WireError the same way — the attempt failed, fail over."""


class Connection:
    """One Unix-socket JSONL connection: send a line, read a line."""

    def __init__(self, path: str, timeout: float):
        self.path = path
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            self._sock.settimeout(timeout)
            self._sock.connect(path)
            self._file = self._sock.makefile("rwb")
        except OSError as exc:
            self._sock.close()
            raise WireError(f"connect {path!r}: {exc}") from exc

    def request(self, line: str, timeout: float) -> dict:
        """Send one request line, block for one response row."""
        try:
            self._sock.settimeout(timeout)
            self._file.write(line.encode("utf-8") + b"\n")
            self._file.flush()
            raw = self._file.readline()
        except OSError as exc:
            raise WireError(f"io {self.path!r}: {exc}") from exc
        if not raw:
            raise WireError(f"{self.path!r}: peer closed the connection")
        try:
            row = json.loads(raw.decode("utf-8", errors="replace"))
            if not isinstance(row, dict):
                raise ValueError("response must be a JSON object")
        except ValueError as exc:
            raise WireError(f"{self.path!r}: bad response: {exc}") from exc
        return row

    def close(self) -> None:
        try:
            self._file.close()
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass


class ConnectionPool:
    """Idle-connection stack for one backend socket.

    ``checkout`` reuses the most recently parked connection (warmest
    path through the worker's per-connection session threads) or dials
    a fresh one; ``checkin`` parks a HEALTHY connection back, up to
    ``max_idle``; a connection that saw any error is closed instead —
    its stream position is unknowable, and the next request would read
    the previous one's orphaned response."""

    def __init__(
        self, path: str, *, max_idle: int = 8, connect_timeout: float = 2.0
    ):
        self.path = path
        self.max_idle = int(max_idle)
        self.connect_timeout = float(connect_timeout)
        self._idle: deque[Connection] = deque()
        self._lock = threading.Lock()

    def checkout(self) -> Connection:
        with self._lock:
            if self._idle:
                return self._idle.pop()
        return Connection(self.path, self.connect_timeout)

    def checkin(self, conn: Connection) -> None:
        with self._lock:
            if len(self._idle) < self.max_idle:
                self._idle.append(conn)
                return
        conn.close()

    def discard(self, conn: Connection) -> None:
        conn.close()

    def close(self) -> None:
        with self._lock:
            idle, self._idle = list(self._idle), deque()
        for conn in idle:
            conn.close()

    def request(self, payload: dict, timeout: float) -> dict:
        """Pooled single request/response round trip."""
        conn = self.checkout()
        try:
            row = conn.request(json.dumps(payload), timeout)
        except WireError:
            self.discard(conn)
            raise
        self.checkin(conn)
        return row


def oneshot(path: str, payload: dict, timeout: float = 2.0) -> dict:
    """Un-pooled request/response on a fresh connection — the probe
    primitive (supervisor health checks, stats scrapes).  A fresh
    connection per probe means a probe can never be queued behind a
    stuck request on a shared stream."""
    conn = Connection(path, timeout)
    try:
        return conn.request(json.dumps(payload), timeout)
    finally:
        conn.close()
