"""Fleet-side JSONL wire helpers: one-shot control requests, and the
pooled probe path the supervisor's health checks ride.

Every worker speaks the serve transport (serve/server.py): one JSON
object per line in, one per line out, in request order.  The fleet tier
talks to workers over the same contract — a probe is just a session of
one ``{"op": "stats"}`` line.  Targets go through
``serve.eventloop.parse_target``: a Unix socket path, or ``host:port``
for the TCP federation tier (pooled TCP connections disable Nagle —
TCP_NODELAY — before the first byte).  Pooled connections carry ONE in-flight
request at a time, so the worker's in-order response guarantee is
trivially the caller's per-request correctness; a sick connection is
closed, never reused.  (The ROUTER's request path no longer lives here:
it pipelines over non-blocking per-worker pools on the event loop —
fleet/router.py.)

Probes reuse a parked connection instead of dialing per probe: N
workers × a fast probe interval used to cost a fresh socket (and three
syscalls) every round, and the timeout path could strand the fd.
``ConnectionPool.request`` now guarantees the connection is either
parked healthy or CLOSED — every exception path, timeout included,
releases the fd — and retries ONCE on a fresh dial when a REUSED
connection fails at the connection level (the parked socket had gone
stale across a worker restart; a liveness verdict should not flap for
that).  Timeouts are never retried: a wedged worker's probe must cost
one timeout, not two.

House rules (script/lint): monotonic clocks only, no print.
"""

from __future__ import annotations

import errno
import json
import socket
import threading
import time
from collections import deque

from licensee_tpu.serve.eventloop import parse_target


def json_str_field(text: str, key: str) -> str | None:
    """Pull a string field's value out of a serialized JSON row without
    parsing it — the hot-path extractor the router (inbound-trace
    adoption) and the HTTP edge (X-Trace-Id/X-Corpus echo) share.

    Only sound for fields whose values the SYSTEM mints (16-hex trace
    IDs, 12-hex corpus fingerprints): their values never contain
    escapes, and client-controlled text cannot forge the unescaped
    ``"key":`` byte pattern through json.dumps (its quotes arrive
    backslash-escaped).  Callers validate the extracted value against
    the field's grammar before trusting it."""
    marker = f'"{key}"'
    i = text.rfind(marker)
    if i < 0:
        return None
    i += len(marker)
    n = len(text)
    while i < n and text[i] in " \t":
        i += 1
    if i >= n or text[i] != ":":
        return None
    i += 1
    while i < n and text[i] in " \t":
        i += 1
    if i >= n or text[i] != '"':
        return None
    i += 1
    j = text.find('"', i)
    if j <= i:
        return None
    return text[i:j]


class WireError(OSError):
    """The backend could not answer: connect/send/recv failed or timed
    out, or the response line was not JSON.  ``kind`` says which
    failure class: "connect" (dial failed), "refused" (ECONNREFUSED —
    a provably dead listener; callers fail over rather than retry),
    "timeout" (the peer is there but silent), "closed" (peer hung up),
    or "protocol" (bad response line) — the pool's retry policy keys
    off it."""

    def __init__(self, message: str, kind: str = "io"):
        super().__init__(message)
        self.kind = kind


class Connection:
    """One JSONL control connection: send a line, read a line.

    ``target`` is a :func:`parse_target` target — a Unix socket path,
    or ``host:port`` for TCP (TCP_NODELAY set before the dial: a
    request/response line protocol dies under Nagle + delayed ACK).
    The dial distinguishes the two connect-failure classes that demand
    opposite reactions: EAGAIN means the listener's backlog is full
    and the connect never STARTED — retried inside the dial budget —
    while ECONNREFUSED means a provably dead host (kind "refused",
    never retried here: failing over is the caller's job)."""

    def __init__(self, path: str, timeout: float):
        self.path = path
        kind, addr = parse_target(path)
        family = (
            socket.AF_INET if kind == "tcp" else socket.AF_UNIX
        )
        address = addr if kind == "tcp" else path
        deadline = time.perf_counter() + max(0.05, float(timeout))
        while True:
            self._sock = socket.socket(family, socket.SOCK_STREAM)
            try:
                self._sock.settimeout(timeout)
                if kind == "tcp":
                    self._sock.setsockopt(
                        socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
                    )
                self._sock.connect(address)
                self._file = self._sock.makefile("rwb")
                return
            except OSError as exc:
                self._sock.close()
                if (
                    exc.errno == errno.EAGAIN
                    and time.perf_counter() < deadline
                ):
                    # backlog full: this connect never started — a
                    # short blocking retry inside the budget (this is
                    # the blocking wire layer; the loop-side twin is
                    # eventloop._connect_stream's timer retry)
                    time.sleep(0.02)
                    continue
                raise WireError(
                    f"connect {path!r}: {exc}",
                    kind=(
                        "refused"
                        if exc.errno == errno.ECONNREFUSED
                        else "connect"
                    ),
                ) from exc

    def request(self, line: str, timeout: float) -> dict:
        """Send one request line, block for one response row."""
        try:
            self._sock.settimeout(timeout)
            self._file.write(line.encode("utf-8") + b"\n")
            self._file.flush()
            raw = self._file.readline()
        except socket.timeout as exc:
            raise WireError(
                f"io {self.path!r}: {exc}", kind="timeout"
            ) from exc
        except OSError as exc:
            raise WireError(f"io {self.path!r}: {exc}") from exc
        if not raw:
            raise WireError(
                f"{self.path!r}: peer closed the connection",
                kind="closed",
            )
        try:
            row = json.loads(raw.decode("utf-8", errors="replace"))
            if not isinstance(row, dict):
                raise ValueError("response must be a JSON object")
        except ValueError as exc:
            raise WireError(
                f"{self.path!r}: bad response: {exc}", kind="protocol"
            ) from exc
        return row

    def close(self) -> None:
        try:
            self._file.close()
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass


# WireError kinds where a parked connection's failure says "this socket
# went stale" (worker restarted under us) rather than "the worker is
# sick" — worth one fresh dial before reporting failure.  "refused" is
# deliberately absent: ECONNREFUSED is a provably dead listener (a dead
# HOST, on the TCP federation tier) and the right reaction is failing
# over, not dialing the corpse again; "timeout" stays out so a wedged
# worker costs one probe timeout, not two.
_RETRY_FRESH_KINDS = ("connect", "closed", "io")


class ConnectionPool:
    """Idle-connection stack for one backend socket.

    ``checkout`` reuses the most recently parked connection (warmest
    path through the worker's per-connection session threads) or dials
    a fresh one; ``checkin`` parks a HEALTHY connection back, up to
    ``max_idle``; a connection that saw any error is closed instead —
    its stream position is unknowable, and the next request would read
    the previous one's orphaned response."""

    def __init__(
        self, path: str, *, max_idle: int = 8, connect_timeout: float = 2.0
    ):
        self.path = path
        self.max_idle = int(max_idle)
        self.connect_timeout = float(connect_timeout)
        self._idle: deque[Connection] = deque()
        self._lock = threading.Lock()

    def checkout(self) -> Connection:
        with self._lock:
            if self._idle:
                return self._idle.pop()
        return Connection(self.path, self.connect_timeout)

    def checkin(self, conn: Connection) -> None:
        with self._lock:
            if len(self._idle) < self.max_idle:
                self._idle.append(conn)
                return
        conn.close()

    def discard(self, conn: Connection) -> None:
        conn.close()

    def close(self) -> None:
        with self._lock:
            idle, self._idle = list(self._idle), deque()
        for conn in idle:
            conn.close()

    def request(
        self, payload: dict, timeout: float, *, retry_fresh: bool = True
    ) -> dict:
        """Pooled single request/response round trip — the probe
        primitive (supervisor health checks ride this every interval).

        The connection is either parked back healthy or CLOSED: every
        exception path — the probe-timeout path included — releases the
        fd in ``finally``, so a fast probe cadence can never leak
        sockets.  When a REUSED connection fails at the connection
        level (stale park across a worker restart), one fresh dial
        retries before the failure is reported; a "timeout" is never
        retried — a wedged worker must cost one probe timeout, not
        two."""
        line = json.dumps(payload)
        reused = False
        with self._lock:
            conn = self._idle.pop() if self._idle else None
            reused = conn is not None
        if conn is None:
            conn = Connection(self.path, self.connect_timeout)
        ok = False
        try:
            row = conn.request(line, timeout)
            ok = True
            return row
        except WireError as exc:
            if not (
                reused
                and retry_fresh
                and exc.kind in _RETRY_FRESH_KINDS
            ):
                raise
        finally:
            if ok:
                self.checkin(conn)
            else:
                conn.close()
        # the stale-park retry: one fresh dial, same guarantees
        conn = Connection(self.path, self.connect_timeout)
        ok = False
        try:
            row = conn.request(line, timeout)
            ok = True
            return row
        finally:
            if ok:
                self.checkin(conn)
            else:
                conn.close()


def oneshot(path: str, payload: dict, timeout: float = 2.0) -> dict:
    """Un-pooled request/response on a fresh connection — for one-off
    control verbs (CLI scrapes, reload verbs with their own long
    timeouts).  The socket is closed in ``finally`` on every path.
    Recurring probes should ride ``ConnectionPool.request`` instead:
    a fresh dial per probe interval is measurable churn at fleet
    scale."""
    conn = Connection(path, timeout)
    try:
        return conn.request(json.dumps(payload), timeout)
    finally:
        conn.close()
