"""Fleet selftest — the CI smoke behind ``licensee-tpu fleet
--selftest``.

Boots a REAL fleet on this host: a supervisor spawning 2 serve worker
processes (CPU-pinned), the router fronting them on a Unix socket, and
a live client streaming classification traffic through the front door.
Mid-stream, one worker is SIGKILLed (faults.kill — a real SIGKILL to a
real process).  The gate:

* ZERO client-visible errors: every request answers with the correct
  verdict via retry/failover, connection resets and queue losses
  included;
* the supervisor restarts the dead worker within its backoff budget
  and the worker rejoins the rotation (answers probes again);
* trace IDs propagate: at least one router-minted trace ID (route
  span) appears verbatim in a worker's ``{"op": "trace"}`` tail;
* the merged fleet exposition (router registry + per-worker scrapes,
  ``worker``-labeled) parses clean against the Prometheus grammar;
* a graceful drain completes with zero in-flight work (the rolling-
  restart primitive).

``stub=True`` swaps the workers for the protocol-faithful stub
(faults.py) — same supervisor, router, sockets, and SIGKILL, no JAX
import per worker — the fast path the unit tests ride.
"""

from __future__ import annotations

import json
import os
import re
import socket
import sys
import tempfile
import threading
import time

from licensee_tpu.corpus.artifact import short_fingerprint
from licensee_tpu.fleet import faults
from licensee_tpu.fleet.http_edge import HttpEdgeServer
from licensee_tpu.fleet.router import FrontServer, Router
from licensee_tpu.fleet.supervisor import Supervisor, worker_env
from licensee_tpu.fleet.wire import WireError, oneshot
from licensee_tpu.obs import RateJumpRule, check_exposition


def _stub_argv(name: str, sock: str) -> list[str]:
    return [
        sys.executable, "-m", "licensee_tpu.fleet.faults",
        "--socket", sock, "--name", name, "--service-ms", "10",
    ]


def _serve_argv(name: str, sock: str) -> list[str]:
    return [
        sys.executable, "-m", "licensee_tpu.cli.main", "serve",
        "--socket", sock, "--max-delay-ms", "5",
        "--trace-sample", "1.0",
    ]


def _stub_reload_argv(name: str, sock: str) -> list[str]:
    argv = [
        sys.executable, "-m", "licensee_tpu.fleet.faults",
        "--socket", sock, "--name", name, "--service-ms", "5",
        "--fingerprint", "fp-old",
    ]
    if name == "w1":
        # the per-worker validation-failure script: w1 refuses any
        # corpus starting "deny-", so a fleet roll of one fails AFTER
        # w0 succeeded — the rollback drill
        argv += ["--reload-deny", "deny-"]
    return argv


def _serve_reload_argv(name: str, sock: str) -> list[str]:
    return _serve_argv(name, sock) + ["--corpus", "vendored"]


def _client_blobs(stub: bool, n_unique: int = 8) -> list[str]:
    if stub:
        return [f"stub blob {i}" for i in range(n_unique)]
    from licensee_tpu.corpus.license import License

    body = re.sub(
        r"\[(\w+)\]", "example", License.find("mit").content or ""
    )
    # unique Dice-bound variants: defeat the Exact prefilter so rows
    # cross each worker's device path (the serving path under test)
    return [f"{body}\nzqfleet{i} zqtail{i}\n" for i in range(n_unique)]


def _worker_trace_ids(socket_path: str) -> set[str]:
    try:
        row = oneshot(socket_path, {"op": "trace", "n": 100}, 5.0)
    except WireError:
        return set()
    return {
        t.get("trace") for t in row.get("traces") or [] if t.get("trace")
    }


def _saturation_smoke(
    front_path: str, router, problems: list[str], stub: bool,
) -> dict:
    """The open-loop burst gate: C client connections write EVERY
    request line up front (no request/response lockstep — open-loop
    arrival, the shape that used to stall the thread-per-attempt
    router), while a slowloris dribbles a never-finished line
    alongside.  The gates:

    * every request answers (no stalled client) with zero errors;
    * the router's event-loop lag gauge stayed bounded — a blocked
      loop callback shows up here in seconds, long before p99 does;
    * the slowloris was reaped by the stall sweep, having held no
      session, thread, or backend pool slot meanwhile."""
    n_conns = 4 if stub else 2
    n_per_conn = 100 if stub else 25
    lag_budget_ms = 500.0 if stub else 1500.0
    counts = [0] * n_conns
    failures: list[str] = []

    def client(idx: int) -> None:
        try:
            with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as s:
                s.connect(front_path)
                s.settimeout(120.0)
                f = s.makefile("rwb")
                for i in range(n_per_conn):
                    f.write((json.dumps(
                        {"id": i, "content": f"burst {idx} {i}"}
                    ) + "\n").encode("utf-8"))
                f.flush()  # all lines in flight at once: open-loop
                for _ in range(n_per_conn):
                    row = json.loads(f.readline())
                    if row.get("error"):
                        failures.append(f"burst error: {row}")
                    counts[idx] += 1
        except (OSError, ValueError) as exc:
            failures.append(f"burst client {idx}: {exc}")

    loris = faults.Slowloris(
        front_path, mode="dribble", byte_interval_s=0.25, give_up_s=30.0
    )
    loris_box: dict = {}
    loris_thread = threading.Thread(
        target=lambda: loris_box.update(loris.run()), daemon=True
    )
    loris_thread.start()
    threads = [
        threading.Thread(target=client, args=(i,), daemon=True)
        for i in range(n_conns)
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=180.0)
    elapsed = time.perf_counter() - t0
    loris_thread.join(timeout=40.0)
    answered = sum(counts)
    if answered != n_conns * n_per_conn:
        problems.append(
            f"saturation burst: {answered}/{n_conns * n_per_conn} "
            f"answered — a client stalled"
        )
    if failures:
        problems.append(
            f"saturation burst: {len(failures)} failures, "
            f"e.g. {failures[:3]}"
        )
    lag_ms = router.stats()["router"]["loop_max_lag_ms"]
    if not (lag_ms < lag_budget_ms):
        problems.append(
            f"event-loop lag {lag_ms}ms >= {lag_budget_ms}ms during "
            f"the open-loop burst — something blocked the loop"
        )
    if not loris_box.get("reaped"):
        problems.append(
            f"slowloris was not reaped during the burst: {loris_box}"
        )
    return {
        "requests": answered,
        "rps": round(answered / elapsed, 1) if elapsed > 0 else None,
        "max_lag_ms": lag_ms,
        "slowloris": loris_box,
    }


def selftest(
    verbose: bool = True,
    stub: bool = False,
    n_workers: int = 2,
    n_requests: int = 120,
) -> int:
    problems: list[str] = []
    saturation: dict | None = None
    tmpdir = tempfile.mkdtemp(prefix="licensee-fleet-")
    sockets = {
        f"w{i}": os.path.join(tmpdir, f"w{i}.sock")
        for i in range(n_workers)
    }
    boot_timeout = 20.0 if stub else 240.0
    req_timeout = 10.0 if stub else 120.0
    env = worker_env(None, None)
    env.setdefault("JAX_PLATFORMS", "cpu")  # the CI contract: CPU workers
    supervisor = Supervisor(
        sockets,
        argv_for=(_stub_argv if stub else _serve_argv),
        env_for=lambda name, chips: env,
        probe_interval_s=0.25,
        backoff_base_s=0.25,
        backoff_max_s=2.0,
        startup_grace_s=boot_timeout,
    )
    router = Router(
        sockets,
        supervisor=supervisor,
        probe_interval_s=0.25,
        request_timeout_s=req_timeout,
        dispatch_wait_s=req_timeout + 30.0,
        trace_sample=1.0,
    )
    front_path = os.path.join(tmpdir, "front.sock")
    server = None
    server_thread = None
    try:
        supervisor.start()
        if not supervisor.wait_healthy(boot_timeout):
            problems.append(
                f"workers never became healthy: {supervisor.status()}"
            )
            raise _Abort()
        router.start()
        # stall_timeout_s=2: honest clients write whole lines — only a
        # slowloris sits mid-line for seconds, and the smoke wants its
        # reap to land inside the test budget
        server = FrontServer(front_path, router, stall_timeout_s=2.0)
        server_thread = threading.Thread(
            target=server.serve_forever, kwargs={"poll_interval": 0.05},
            daemon=True,
        )
        server_thread.start()

        # -- open-loop saturation smoke (+ slowloris reap) --
        saturation = _saturation_smoke(front_path, router, problems, stub)

        blobs = _client_blobs(stub)
        rows = _drive_traffic(
            front_path, blobs, n_requests, supervisor, problems,
            read_timeout=req_timeout + 60.0,
        )
        # -- zero client-visible errors, correct verdicts --
        want_key = "stub-mit" if stub else "mit"
        errors = [r for r in rows if r.get("error")]
        if errors:
            problems.append(
                f"{len(errors)} client-visible errors, e.g. {errors[:3]}"
            )
        wrong = [r for r in rows if not r.get("error")
                 and r.get("key") != want_key]
        if wrong:
            problems.append(f"wrong verdicts, e.g. {wrong[:3]}")
        if len(rows) != n_requests:
            problems.append(
                f"response count {len(rows)} != requests {n_requests}"
            )
        # -- the dead worker restarted within the backoff budget --
        handle = supervisor.workers["w0"]
        budget = (
            supervisor.backoff_delay_s(0)
            + supervisor.backoff_delay_s(1)
            + boot_timeout
        )
        deadline = time.perf_counter() + budget
        revived = False
        while time.perf_counter() < deadline:
            if handle.restarts >= 1 and supervisor.probe("w0") is not None:
                revived = True
                break
            time.sleep(0.1)
        if not revived:
            problems.append(
                f"w0 not restarted within {budget:.1f}s budget: "
                f"{supervisor.status()}"
            )
        # -- the router actually failed over (the kill landed mid-stream) --
        rstats = router.stats()["router"]
        if rstats["failovers"] + rstats["retries"] < 1:
            problems.append(
                f"no failover recorded — did the kill land? {rstats}"
            )
        # -- trace propagation router -> worker --
        routed_ids = {
            t["trace"]
            for t in router.trace_tail(200)
            if any(s["name"] == "route" for s in t.get("spans", ()))
        }
        worker_ids = set()
        for sock in sockets.values():
            worker_ids |= _worker_trace_ids(sock)
        if not routed_ids:
            problems.append("router retained no routed traces")
        elif not (routed_ids & worker_ids):
            problems.append(
                f"no router trace ID found in any worker tail "
                f"({len(routed_ids)} routed, {len(worker_ids)} worker-side)"
            )
        # -- merged fleet exposition --
        exposition = router.prometheus()
        grammar = check_exposition(exposition)
        if grammar:
            problems.append(f"merged exposition grammar: {grammar[:3]}")
        if 'worker="w1"' not in exposition:
            problems.append("merged exposition missing worker labels")
        if 'fleet_requests_total{worker="router",event="ok"}' not in (
            exposition
        ):
            problems.append("merged exposition missing router series")
        # -- flight-recorder harvest: the SIGKILL's black box rode the
        #    restart log (exit signal + dump path + last events) --
        _check_flight_harvest(supervisor, problems)
        # -- availability SLO intact across the whole drill: the
        #    SIGKILL cost retries, never error budget --
        _check_slo(router, problems)
        # -- cross-process trace assembly: the failed-over request's
        #    tree joins the router's failover spans with the surviving
        #    worker's serving spans under ONE trace ID, critical-path
        #    self-times within 5% of the recorded e2e --
        _check_assembled_traces(router, front_path, problems)
        # -- graceful drain completes in-flight and stops the worker --
        drained_clean = supervisor.drain(
            "w1", timeout_s=30.0, restart=False
        )
        if not drained_clean:
            problems.append("drain of idle w1 was not clean")
        if supervisor.workers["w1"].state != "stopped":
            problems.append(
                f"drained worker state: {supervisor.workers['w1'].state}"
            )
    except _Abort:
        pass
    except Exception as exc:  # noqa: BLE001 — selftest must report, not die
        problems.append(f"selftest crashed: {type(exc).__name__}: {exc}")
    finally:
        if server is not None:
            server.shutdown()
            server.server_close()
        if server_thread is not None:
            server_thread.join(timeout=5.0)
        router.close()
        supervisor.stop()
    # -- the retained-telemetry acceptance drill: its own mini-fleet
    #    (the scripted fault must not race the kill/drain drills
    #    above); stub-only — --slow-span is a stub fault flag --
    telemetry = _telemetry_drill(problems) if stub else None
    if verbose:
        summary = {
            "fleet_selftest": "ok" if not problems else "FAIL",
            "stub_workers": stub,
            "saturation": saturation,
            "telemetry": telemetry,
            "problems": problems,
        }
        sys.stderr.write(json.dumps(summary) + "\n")
    return 0 if not problems else 1


class _Abort(Exception):
    """Internal early-exit: boot failed, nothing further to assert."""


def _check_flight_harvest(
    supervisor: Supervisor, problems: list[str]
) -> None:
    """The flight-recorder drill gate: the supervisor's restart-log
    entry for the SIGKILLed worker must carry the kill signal, the
    black-box dump path, and a NON-EMPTY harvested event tail — a
    SIGKILL post-mortem starts from recorded evidence (obs/flight.py,
    supervisor._harvest_flight)."""
    from licensee_tpu.obs.flight import flight_path_for_socket

    handle = supervisor.workers["w0"]
    log = handle.restart_log
    if not log:
        problems.append(
            "no restart-log entry for the SIGKILLed worker"
        )
        return
    entry = log[0]
    if entry.get("reason") != "crash" or entry.get("signal") != 9:
        problems.append(
            f"restart log missed the kill (want crash/signal 9): "
            f"{ {k: entry.get(k) for k in ('reason', 'exit_code', 'signal')} }"
        )
    want_dump = flight_path_for_socket(handle.socket_path)
    if entry.get("flight_dump") != want_dump:
        problems.append(
            f"restart log names the wrong black-box path: "
            f"{entry.get('flight_dump')!r} != {want_dump!r}"
        )
    if not entry.get("flight_harvested") or not entry.get(
        "flight_events"
    ):
        problems.append(
            "supervisor failed to harvest a non-empty flight dump: "
            f"harvested={entry.get('flight_harvested')} "
            f"events={len(entry.get('flight_events') or [])}"
        )


def _telemetry_drill(problems: list[str]) -> dict | None:
    """The retained-telemetry acceptance drill: a scripted latency
    fault on a stub worker must (1) appear as a stored p99 series
    windowable via the ``{"op": "query"}`` front verb, (2) carry an
    exemplar whose trace ID resolves through ``{"op": "traces"}`` to an
    assembled tree naming that worker, and (3) raise exactly ONE
    watchdog alert (``router_p99_latency_jump``) that clears once the
    fault ends.

    Runs its own single-worker mini-fleet: with one backend, router
    dispatch order IS the stub's admission order, so the ``--slow-span``
    fault window (rows N_BASE+1 .. N_BASE+N_SLOW) is deterministic —
    no racing against a load balancer.  Scrape cadence is cranked to
    0.25s so the stock p99-jump rule's 2s windows fill in seconds, not
    the production minutes."""
    from licensee_tpu.fleet.wire import Connection

    n_base, n_slow, slow_ms = 400, 14, 250.0
    tmpdir = tempfile.mkdtemp(prefix="licensee-tsdb-drill-")
    sock = os.path.join(tmpdir, "wslow.sock")
    front_path = os.path.join(tmpdir, "front.sock")

    def argv_for(name: str, path: str) -> list[str]:
        return [
            sys.executable, "-m", "licensee_tpu.fleet.faults",
            "--socket", path, "--name", name, "--service-ms", "5",
            "--slow-span", f"{n_base}:{n_slow}:{slow_ms:g}",
        ]

    env = worker_env(None, None)
    env.setdefault("JAX_PLATFORMS", "cpu")
    supervisor = Supervisor(
        {"wslow": sock},
        argv_for=argv_for,
        env_for=lambda name, chips: env,
        probe_interval_s=0.25,
        startup_grace_s=20.0,
    )
    router = Router(
        {"wslow": sock},
        supervisor=supervisor,
        probe_interval_s=0.25,
        request_timeout_s=10.0,
        trace_sample=1.0,
        scrape_interval_s=0.25,
        # the stock rule set with one drill tuning: min_value=0.05
        # keeps cold-start jitter (tens of ms on the first windows)
        # from firing — only the scripted 250ms span can breach
        watchdog_rules=[RateJumpRule(
            "router_p99_latency_jump",
            "fleet_request_seconds",
            labels={"worker": "router"},
            signal="quantile",
            q=0.99,
            window_s=2.0,
            baseline_windows=8,
            min_baseline=4,
            z_threshold=4.5,
            min_value=0.05,
            description="routed p99 jumped vs its trailing baseline",
        )],
    )
    server = None
    server_thread = None
    stop = threading.Event()
    drive_errors: list[str] = []
    out: dict = {}

    def drive() -> None:
        """Paced lockstep traffic over ONE connection: the 25ms pace
        spreads the n_base baseline rows across >10s of wall clock, so
        the p99 rule's trailing 2s windows all see traffic before the
        fault lands; past n_base the stub itself throttles (each slow
        row holds the line slow_ms)."""
        conn = None
        try:
            conn = Connection(front_path, 10.0)
            i = 0
            while not stop.is_set():
                row = conn.request(json.dumps(
                    {"id": i, "content": f"drill {i}"}
                ), 10.0)
                if row.get("error"):
                    drive_errors.append(f"drill row error: {row}")
                    return
                i += 1
                stop.wait(0.025)
        except (WireError, OSError) as exc:
            if not stop.is_set():
                drive_errors.append(f"drill driver died: {exc}")
        finally:
            if conn is not None:
                conn.close()

    driver = threading.Thread(target=drive, daemon=True)
    try:
        supervisor.start()
        if not supervisor.wait_healthy(20.0):
            problems.append(
                f"telemetry drill: worker never healthy: "
                f"{supervisor.status()}"
            )
            raise _Abort()
        router.start()
        server = FrontServer(front_path, router, stall_timeout_s=5.0)
        server_thread = threading.Thread(
            target=server.serve_forever, kwargs={"poll_interval": 0.05},
            daemon=True,
        )
        server_thread.start()
        driver.start()

        # -- (3a) the fault fires the p99-jump rule, and ONLY it --
        fired = None
        deadline = time.perf_counter() + 60.0
        while time.perf_counter() < deadline and not drive_errors:
            row = oneshot(front_path, {"op": "alerts"}, 5.0)
            active = (row.get("alerts") or {}).get("active") or []
            if any(
                a.get("rule") == "router_p99_latency_jump"
                for a in active
            ):
                fired = active
                break
            time.sleep(0.3)
        if fired is None:
            problems.append(
                "telemetry drill: p99-jump alert never fired "
                f"(driver errors: {drive_errors[:2]})"
            )
            raise _Abort()
        extras = [
            a["rule"] for a in fired
            if a.get("rule") != "router_p99_latency_jump"
        ]
        if extras:
            problems.append(
                f"telemetry drill: unexpected co-firing rules: {extras}"
            )
        out["alert"] = fired[0]

        # -- (1) the fault is windowable store history: p99 over a
        #    window covering the fault, served by the query verb.  The
        #    alert fires at the FIRST slow completion (one 250ms row
        #    detonates the z-score against the tight baseline), but the
        #    windowed p99 only crosses once enough of the span has
        #    drained through the stub to outnumber the top percentile —
        #    so poll while the remaining ~3.5s of slow rows land.  20s
        #    window (not the rule's 2s): the 14 slow rows stay >1% of
        #    any 20s window at the ~40/s drill pace, so a scheduling
        #    stall on a loaded single-core VM cannot roll the fault
        #    out from under the assertion --
        q: dict = {}
        value = None
        deadline = time.perf_counter() + 30.0
        while time.perf_counter() < deadline:
            row = oneshot(front_path, {
                "op": "query", "series": "fleet_request_seconds",
                "fn": "quantile", "q": 0.99, "window": 20.0,
                "labels": {"worker": "router"},
            }, 5.0)
            q = row.get("query") or {}
            value = q.get("value")
            if value is not None and value >= 0.05:
                break
            time.sleep(0.3)
        if value is None or not value >= 0.05:
            problems.append(
                f"telemetry drill: stored p99 missed the "
                f"{slow_ms:g}ms fault: {q}"
            )
        out["p99"] = value

        # -- (2) the stored exemplar closes the loop to a trace tree
        #    naming the slow worker --
        ex = q.get("exemplar") or {}
        ex_id = ex.get("trace_id")
        if not ex_id:
            problems.append(
                f"telemetry drill: fault p99 carries no exemplar: {q}"
            )
        else:
            row = oneshot(front_path, {
                "op": "traces", "trace_id": ex_id, "n": 5,
            }, 10.0)
            trees = row.get("traces") or []
            procs = set((trees[0].get("procs") or ())) if trees else set()
            if not trees or "wslow" not in procs:
                problems.append(
                    f"telemetry drill: exemplar {ex_id!r} resolved to "
                    f"no tree naming the slow worker (procs={procs})"
                )
            out["exemplar"] = ex_id

        # -- (3b) recovery traffic clears the alert; exactly one fire
        #    across the whole drill --
        cleared = False
        deadline = time.perf_counter() + 45.0
        while time.perf_counter() < deadline:
            row = oneshot(front_path, {"op": "alerts"}, 5.0)
            snap = row.get("alerts") or {}
            if not snap.get("active"):
                cleared = True
                break
            time.sleep(0.3)
        if not cleared:
            problems.append(
                "telemetry drill: alert never cleared after the fault "
                f"ended: {snap.get('active')}"
            )
        elif snap.get("fired_total") != 1:
            problems.append(
                f"telemetry drill: fired_total "
                f"{snap.get('fired_total')} != 1"
            )
        out["fired_total"] = snap.get("fired_total")
        if drive_errors:
            problems.append(f"telemetry drill: {drive_errors[:3]}")
    except _Abort:
        pass
    except Exception as exc:  # noqa: BLE001 — selftest must report, not die
        problems.append(
            f"telemetry drill crashed: {type(exc).__name__}: {exc}"
        )
    finally:
        stop.set()
        driver.join(timeout=15.0)
        if server is not None:
            server.shutdown()
            server.server_close()
        if server_thread is not None:
            server_thread.join(timeout=5.0)
        router.close()
        supervisor.stop()
    return out or None


def _check_slo(router: Router, problems: list[str]) -> None:
    """The SLO gate: the availability objective must end the drill
    with burn rate < 1.0 on every window — zero client-visible errors
    means zero budget spent, SIGKILL included."""
    slo = router.stats().get("slo") or {}
    avail = (slo.get("objectives") or {}).get("availability") or {}
    if not avail:
        problems.append(f"router stats carries no availability SLO: {slo}")
        return
    if not (avail.get("good") or 0) > 0:
        problems.append(f"availability SLO saw no traffic: {avail}")
    max_burn = avail.get("max_burn")
    if max_burn is None or not (max_burn < 1.0):
        problems.append(
            f"availability SLO burned through the drill: "
            f"max_burn={max_burn} windows={avail.get('windows')}"
        )


def _check_assembled_traces(
    router: Router, front_path: str, problems: list[str]
) -> None:
    """The telemetry-plane gate, both layers: (1) the collector joins
    the failed-over request's router spans with the surviving worker's
    serving spans under one trace ID, with critical-path self-times
    summing to within 5% of the recorded end-to-end latency; (2) the
    ``licensee-tpu traces --slowest 1`` CLI prints one assembled tree
    against the live front socket."""
    import contextlib
    import io

    trees = router.assembled_traces(200)
    if not trees:
        problems.append("collector assembled no traces after the drill")
        return
    joined = None
    worker_procs = set(router.backends)
    for tree in trees:
        root = tree.get("root") or {}
        names = {
            c.get("name") for c in root.get("children") or []
        }
        if "failover" not in names:
            continue
        if set(tree.get("procs") or []) & worker_procs:
            joined = tree
            break
    if joined is None:
        problems.append(
            "no assembled tree joins a router failover with a "
            f"surviving worker's spans ({len(trees)} trees, procs "
            f"{sorted({p for t in trees for p in t.get('procs') or []})})"
        )
    else:
        e2e = joined.get("e2e_ms") or 0.0
        crit = joined.get("critical_ms") or 0.0
        if e2e <= 0.0 or abs(crit - e2e) > 0.05 * e2e:
            problems.append(
                f"critical-path self-times {crit}ms not within 5% of "
                f"the recorded e2e {e2e}ms (trace {joined.get('trace')})"
            )
    # every tree must account its time, failover or not
    bad_sums = [
        t["trace"] for t in trees
        if (t.get("e2e_ms") or 0.0) > 0.0
        and abs(t["critical_ms"] - t["e2e_ms"]) > 0.05 * t["e2e_ms"]
    ]
    if bad_sums:
        problems.append(
            f"{len(bad_sums)} assembled trees double- or under-count "
            f"critical-path time, e.g. {bad_sums[:3]}"
        )
    # the one-command view against the live fleet: --slowest 1 prints
    # one assembled tree; pinned by --id to the joined drill trace so
    # the gate is deterministic under concurrent burst traffic
    from licensee_tpu.cli.main import main as cli_main

    for extra in (
        [],
        ["--id", joined["trace"]] if joined is not None else None,
    ):
        if extra is None:
            continue
        out = io.StringIO()
        with contextlib.redirect_stdout(out):
            rc = cli_main([
                "traces", "--socket", front_path, "--slowest", "1",
                *extra,
            ])
        text = out.getvalue()
        if rc != 0 or "critical path" not in text:
            problems.append(
                f"`licensee-tpu traces --slowest 1` against the live "
                f"fleet failed (rc={rc}): {text[:300]!r}"
            )
        elif extra and ("failover" not in text or not any(
            f"[{p}]" in text for p in worker_procs
        )):
            problems.append(
                "the rendered drill tree misses the failover spans or "
                f"the surviving worker's spans: {text[:400]!r}"
            )


class _ReloadTraffic:
    """Continuous client traffic through the front socket for the
    reload drill: sequential request/response round trips on one
    connection, every row collected, until stopped."""

    def __init__(self, front_path: str, blobs: list[str],
                 timeout_s: float):
        self.front_path = front_path
        self.blobs = blobs
        self.timeout_s = timeout_s
        self.rows: list[dict] = []
        self.errors: list[str] = []
        self.reconnects = 0
        self.stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)

    def start(self) -> None:
        self._thread.start()

    def finish(self) -> None:
        self.stop.set()
        self._thread.join(timeout=self.timeout_s + 10.0)

    def _run(self) -> None:
        from licensee_tpu.fleet.wire import Connection, WireError

        conn = None
        i = 0
        while not self.stop.is_set():
            try:
                if conn is None:
                    conn = Connection(self.front_path, self.timeout_s)
                line = json.dumps({
                    "id": i,
                    "content": self.blobs[i % len(self.blobs)],
                    "filename": "LICENSE",
                })
                self.rows.append(conn.request(line, self.timeout_s))
                i += 1
            except WireError as exc:
                # the front socket must never drop a session during a
                # reload: any reconnect is itself a finding (counted),
                # and a failure on a FRESH connection is a hard error
                if conn is None:
                    self.errors.append(str(exc))
                    self.stop.wait(0.2)
                else:
                    self.reconnects += 1
                    conn.close()
                    conn = None
            time.sleep(0.005)
        if conn is not None:
            conn.close()


def _fingerprints(supervisor: Supervisor) -> dict:
    """name -> reported corpus fingerprint for every probeable worker."""
    out = {}
    for name in supervisor.workers:
        stats = supervisor.probe(name)
        out[name] = ((stats or {}).get("corpus") or {}).get("fingerprint")
    return out


def _await_respawn(
    supervisor: Supervisor, name: str, timeout_s: float
) -> bool:
    deadline = time.perf_counter() + timeout_s
    while time.perf_counter() < deadline:
        if supervisor.probe(name) is not None:
            return True
        time.sleep(0.1)
    return False


def _patch_stub_argv(argv: list[str], corpus: str) -> list[str]:
    """The stub twin of Supervisor.patch_corpus_argv: a respawned stub
    must report the fingerprint its fleet was rolled onto."""
    out = list(argv)
    for i, arg in enumerate(out[:-1]):
        if arg == "--fingerprint":
            out[i + 1] = corpus
            return out
    return out + ["--fingerprint", corpus]


def selftest_reload(
    verbose: bool = True,
    stub: bool = False,
    n_workers: int = 2,
) -> int:
    """The fault-drilled zero-downtime upgrade selftest (``licensee-tpu
    fleet --selftest-reload``): a live 2-worker fleet under continuous
    front-socket traffic completes >=3 rolling corpus reloads
    interleaved with injected failures — a corrupt-artifact reload, a
    refused (validation-failure) reload that triggers automatic
    rollback, and (stub mode) a SIGKILL mid-swap — gating that

    * the client sees ZERO errors across every drill;
    * every response carries exactly one KNOWN corpus fingerprint
      (old or new, never anything else — no half-swapped corpus);
    * failed rolls leave the fleet healthy on the previous fingerprint,
      rollback included;
    * a crash-restarted worker rejoins on the fleet's CURRENT corpus
      (the respawn argv is patched by the roll).

    ``stub=True`` runs protocol-faithful stub workers (real processes,
    sockets, and signals; no JAX) — the fast CI path; ``stub=False``
    drives real serve workers through real corpus artifacts."""
    problems: list[str] = []
    tmpdir = tempfile.mkdtemp(prefix="licensee-reload-fleet-")
    sockets = {
        f"w{i}": os.path.join(tmpdir, f"w{i}.sock")
        for i in range(n_workers)
    }
    boot_timeout = 20.0 if stub else 240.0
    req_timeout = 10.0 if stub else 120.0
    env = worker_env(None, None)
    env.setdefault("JAX_PLATFORMS", "cpu")
    supervisor = Supervisor(
        sockets,
        argv_for=(_stub_reload_argv if stub else _serve_reload_argv),
        env_for=lambda name, chips: env,
        probe_interval_s=0.25,
        backoff_base_s=0.25,
        backoff_max_s=2.0,
        startup_grace_s=boot_timeout,
    )
    router = Router(
        sockets,
        supervisor=supervisor,
        probe_interval_s=0.25,
        request_timeout_s=req_timeout,
        dispatch_wait_s=req_timeout + 30.0,
    )
    front_path = os.path.join(tmpdir, "front.sock")
    server = None
    server_thread = None
    traffic = None
    argv_patch = _patch_stub_argv if stub else None
    want_key = "stub-mit" if stub else "mit"
    allowed_fps: set[str] = set()
    good_rolls = 0
    try:
        supervisor.start()
        if not supervisor.wait_healthy(boot_timeout):
            problems.append(
                f"workers never became healthy: {supervisor.status()}"
            )
            raise _Abort()
        router.start()
        server = FrontServer(front_path, router)
        server_thread = threading.Thread(
            target=server.serve_forever, kwargs={"poll_interval": 0.05},
            daemon=True,
        )
        server_thread.start()
        fp_old = _fingerprints(supervisor)["w0"]
        if not fp_old:
            problems.append("workers report no corpus fingerprint")
            raise _Abort()
        allowed_fps.add(fp_old)

        if stub:
            targets = ["fp-new-1", "fp-new-2", "fp-new-4"]
            bad_source = "corrupt:drill"
            deny_source = "deny-fp"
        else:
            from licensee_tpu.corpus.artifact import write_artifact
            from licensee_tpu.corpus.spdx import spdx_corpus

            artifact = os.path.join(tmpdir, "spdx.corpus.npz")
            write_artifact(artifact, spdx_corpus(None), source="spdx")
            bad_source = os.path.join(tmpdir, "corrupt.corpus.npz")
            with open(bad_source, "wb") as f:
                f.write(b"definitely not a corpus artifact")
            targets = [artifact, "vendored", artifact]
            deny_source = None

        traffic = _ReloadTraffic(
            front_path, _client_blobs(stub), req_timeout
        )
        traffic.start()
        time.sleep(0.5)  # rows in flight before the first roll

        def roll(source: str, expect_ok: bool, label: str):
            nonlocal good_rolls
            out = supervisor.reload_fleet(
                source, timeout_s=req_timeout + 60.0,
                health_timeout_s=30.0, argv_patch=argv_patch,
            )
            if bool(out["ok"]) != expect_ok:
                problems.append(f"{label}: unexpected outcome {out}")
            if out.get("fingerprint"):
                allowed_fps.add(out["fingerprint"])
            if out["ok"]:
                good_rolls += 1
                fps = set(_fingerprints(supervisor).values())
                if fps != {out["fingerprint"]}:
                    problems.append(
                        f"{label}: fleet fingerprints diverged: {fps}"
                    )
            return out

        # -- roll 1: clean fleet-wide reload --
        out1 = roll(targets[0], True, "roll-1")
        fp_roll1 = out1.get("fingerprint")

        # -- crash-restart keeps the ROLLED corpus (argv patch) --
        pid = supervisor.workers["w0"].pid
        if pid:
            faults.kill(pid)
        if not _await_respawn(supervisor, "w0", boot_timeout + 10.0):
            problems.append("w0 never respawned after SIGKILL")
        elif stub:
            # a real serve worker re-compiles the artifact on respawn —
            # same fingerprint; the stub proves the argv patch directly
            got = _fingerprints(supervisor)["w0"]
            if got != fp_roll1:
                problems.append(
                    f"respawned w0 on {got!r}, fleet rolled to "
                    f"{fp_roll1!r} — restart rolled it back"
                )

        # -- corrupt-artifact roll: refused, fleet unmoved --
        before = _fingerprints(supervisor)
        roll(bad_source, False, "roll-corrupt")
        after = _fingerprints(supervisor)
        if before != after:
            problems.append(
                f"corrupt roll moved fingerprints: {before} -> {after}"
            )

        # -- refused-validation roll with automatic rollback (stub:
        #    w1 denies, w0 already swapped -> rolled back) --
        if deny_source is not None:
            # w0 swaps to the denied source before w1 refuses it, so a
            # few rows legitimately carry it until the rollback lands
            allowed_fps.add(deny_source)
            out_deny = roll(deny_source, False, "roll-deny")
            if not out_deny.get("rolled_back"):
                problems.append(f"deny roll did not roll back: {out_deny}")
            fps = set(_fingerprints(supervisor).values())
            if fps != set(before.values()):
                problems.append(
                    f"rollback left fleet on {fps}, wanted "
                    f"{set(before.values())}"
                )

        # -- roll 2 --
        roll(targets[1], True, "roll-2")

        # -- SIGKILL mid-swap (stub: the slow reload window) --
        if stub:
            fps_before_kill = _fingerprints(supervisor)
            allowed_fps.add("fp-mid-3")  # a late kill may land post-swap
            killer_done: list[dict] = []

            def slow_roll() -> None:
                killer_done.append(supervisor.reload_fleet(
                    "slow:1500:fp-mid-3", timeout_s=req_timeout + 60.0,
                    health_timeout_s=30.0, argv_patch=argv_patch,
                ))

            rt = threading.Thread(target=slow_roll, daemon=True)
            rt.start()
            time.sleep(0.4)  # w0 is mid-swap (sleeping in the verb)
            pid = supervisor.workers["w0"].pid
            if pid:
                faults.kill(pid)
            rt.join(timeout=req_timeout + 90.0)
            if not killer_done or killer_done[0].get("ok"):
                problems.append(
                    f"SIGKILL mid-swap roll reported ok: {killer_done}"
                )
            if not _await_respawn(supervisor, "w0", boot_timeout + 10.0):
                problems.append("w0 never respawned after mid-swap kill")
            else:
                fps = _fingerprints(supervisor)
                if set(fps.values()) != set(fps_before_kill.values()):
                    problems.append(
                        f"mid-swap kill left fleet on {fps}, wanted "
                        f"{fps_before_kill}"
                    )

        # -- roll 3 --
        roll(targets[2], True, "roll-3")

        if good_rolls < 3:
            problems.append(f"only {good_rolls} clean rolls (< 3)")

        time.sleep(0.5)  # post-roll traffic on the final corpus
        traffic.finish()
        errors = [r for r in traffic.rows if r.get("error")]
        errors = traffic.errors + [str(e)[:200] for e in errors]
        if errors:
            problems.append(
                f"{len(errors)} client-visible errors, e.g. {errors[:3]}"
            )
        if traffic.reconnects:
            problems.append(
                f"front socket dropped the client session "
                f"{traffic.reconnects} time(s)"
            )
        wrong = [
            r for r in traffic.rows
            if not r.get("error") and r.get("key") != want_key
        ]
        if wrong:
            problems.append(f"wrong verdicts, e.g. {wrong[:3]}")
        if len(traffic.rows) < 50:
            problems.append(
                f"only {len(traffic.rows)} traffic rows — the drill "
                "did not run under load"
            )
        unattributed = [
            r for r in traffic.rows
            if not r.get("error") and not r.get("corpus")
        ]
        if unattributed:
            problems.append(
                f"{len(unattributed)} responses carry no corpus "
                f"fingerprint, e.g. {unattributed[:2]}"
            )
        short_allowed = {
            short_fingerprint(fp) for fp in allowed_fps
        } | allowed_fps
        alien = [
            r for r in traffic.rows
            if r.get("corpus") and r["corpus"] not in short_allowed
        ]
        if alien:
            problems.append(
                f"{len(alien)} responses attributed to an unknown "
                f"corpus, e.g. {alien[:2]} (known: {sorted(short_allowed)})"
            )
    except _Abort:
        pass
    except Exception as exc:  # noqa: BLE001 — selftest must report, not die
        problems.append(f"selftest crashed: {type(exc).__name__}: {exc}")
    finally:
        if traffic is not None and not traffic.stop.is_set():
            traffic.finish()
        if server is not None:
            server.shutdown()
            server.server_close()
        if server_thread is not None:
            server_thread.join(timeout=5.0)
        router.close()
        supervisor.stop()
    if verbose:
        summary = {
            "reload_fleet_selftest": "ok" if not problems else "FAIL",
            "stub_workers": stub,
            "clean_rolls": good_rolls,
            "traffic_rows": len(traffic.rows) if traffic else 0,
            "problems": problems,
        }
        sys.stderr.write(json.dumps(summary) + "\n")
    return 0 if not problems else 1


def _free_port() -> int:
    """Lease one loopback TCP port (bind :0, read, close).  A race
    against another process grabbing the port between close and our
    bind exists in principle; on a CI loopback it is noise-level, and
    the selftest reports a bind failure honestly if it ever loses."""
    sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    try:
        sock.bind(("127.0.0.1", 0))
        return sock.getsockname()[1]
    finally:
        sock.close()


class _HttpClient:
    """A sequential HTTP/1.1 keep-alive client for the federation
    drill: one TCP connection, one POST round trip at a time, real
    status-line + Content-Length parsing (the drill gates on status
    codes, so counting newlines is not enough)."""

    def __init__(self, target: str, token: str | None,
                 timeout_s: float):
        from licensee_tpu.fleet.faults import _dial_stream

        self.sock = _dial_stream(target, timeout_s=timeout_s)
        self.reader = self.sock.makefile("rb")
        self.token = token

    def post(self, path: str, body: bytes) -> tuple[int, dict, bytes]:
        auth = (
            f"Authorization: Bearer {self.token}\r\n" if self.token else ""
        )
        head = (
            f"POST {path} HTTP/1.1\r\n"
            f"Host: edge\r\n{auth}"
            f"Content-Type: application/json\r\n"
            f"Content-Length: {len(body)}\r\n\r\n"
        ).encode("utf-8")
        self.sock.sendall(head + body)
        status_line = self.reader.readline()
        parts = status_line.decode("utf-8", "replace").split(" ", 2)
        if len(parts) < 2 or not parts[1].isdigit():
            raise OSError(f"bad status line {status_line!r}")
        code = int(parts[1])
        headers: dict = {}
        while True:
            line = self.reader.readline()
            if line in (b"\r\n", b"\n", b""):
                break
            name, _, value = line.decode(
                "utf-8", "replace"
            ).partition(":")
            headers[name.strip().lower()] = value.strip()
        length = int(headers.get("content-length", "0"))
        payload = self.reader.read(length) if length else b""
        return code, headers, payload

    def close(self) -> None:
        try:
            self.reader.close()
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass


def _edge_burst(
    edge_target: str, token: str, problems: list[str],
    rate: float = 600.0, duration_s: float = 1.0, n_conns: int = 2,
) -> dict:
    """The HTTP open-loop burst through the real edge: subprocess
    clients write pipelined keep-alive POSTs at a fixed arrival rate.
    Gates: every request answered, all 200s, no stalled client."""
    import subprocess

    procs = []
    for _ in range(n_conns):
        proc = subprocess.Popen(
            [
                sys.executable, "-m", "licensee_tpu.fleet.faults",
                "--open-loop-http", edge_target,
                "--rate", str(rate / n_conns),
                "--duration-s", str(duration_s),
                "--token", token,
            ],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        )
        procs.append(proc)
    results = []
    for p in procs:
        try:
            stdout, _ = p.communicate(timeout=duration_s + 60.0)
            results.append(json.loads(stdout))
        except Exception:  # noqa: BLE001 — a dead client is a finding below
            p.kill()
    sent = sum(r["sent"] for r in results)
    answered = sum(r["answered"] for r in results)
    non_200 = sum(r.get("non_200") or 0 for r in results)
    stalled = any(r["stalled"] for r in results) or (
        len(results) < n_conns
    )
    if stalled or answered != sent:
        problems.append(
            f"HTTP burst stalled: {answered}/{sent} answered "
            f"({len(results)}/{n_conns} clients reported)"
        )
    if non_200:
        problems.append(f"HTTP burst saw {non_200} non-200 responses")
    send_elapsed = max(
        (r.get("send_elapsed_s") or 0.0 for r in results), default=0.0
    )
    return {
        "sent": sent,
        "answered": answered,
        "non_200": non_200,
        "offered_rps": round(sent / send_elapsed, 1)
        if send_elapsed else None,
    }


def selftest_tcp(
    verbose: bool = True,
    stub: bool = True,
    n_domains: int = 2,
    workers_per_domain: int = 1,
    n_requests: int = 120,
) -> int:
    """The cross-host federation selftest (``licensee-tpu fleet
    --selftest-tcp``): ``n_domains`` supervisor domains — each a
    supervisor, its worker(s), a domain router, and a domain front
    server, ALL on loopback TCP — federated behind one front router
    (``merge_label="host"``) and the HTTP/1.1 edge.  The drills:

    * an HTTP open-loop keep-alive burst through the edge: every
      request answers 200, no stalled client;
    * SIGKILL of one domain's worker mid-stream: ZERO client-visible
      errors — the domain answers ``no_backend_available`` fast and
      the FRONT router fails the attempt over to the other host (the
      federated failover path), while the domain's supervisor respawns
      the worker and the host rejoins;
    * auth: a wrong bearer token answers 401 without touching a
      backend;
    * a slowloris dribbling HTTP HEADERS over TCP is reaped by the
      stall sweep while the drill traffic keeps answering;
    * the front router's merged exposition nests ``host=`` outside the
      per-domain ``worker=`` labels and parses clean.

    ``stub=True`` (the CI path) runs protocol-faithful stub workers
    over TCP; ``stub=False`` boots real serve workers on TCP ports."""
    problems: list[str] = []
    burst: dict | None = None
    boot_timeout = 20.0 if stub else 240.0
    req_timeout = 10.0 if stub else 120.0
    token = "edge-selftest-token"
    domains: list[dict] = []
    front_router = None
    edge = None
    edge_thread = None
    statuses: list[int] = []

    def stub_tcp_argv(name: str, target: str) -> list[str]:
        return [
            sys.executable, "-m", "licensee_tpu.fleet.faults",
            "--socket", target, "--name", name, "--service-ms", "5",
        ]

    def serve_tcp_argv(name: str, target: str) -> list[str]:
        return [
            sys.executable, "-m", "licensee_tpu.cli.main", "serve",
            "--socket", target, "--max-delay-ms", "5",
        ]

    env = worker_env(None, None)
    env.setdefault("JAX_PLATFORMS", "cpu")
    try:
        # -- boot the per-host supervisor domains --
        for d in range(n_domains):
            workers = {
                f"d{d}w{i}": f"127.0.0.1:{_free_port()}"
                for i in range(workers_per_domain)
            }
            # the restart backoff is LONGER than the domain's dispatch
            # deadline below, so a killed worker's domain provably
            # answers no_backend_available before its replacement
            # boots — the drill must exercise the CROSS-HOST failover
            # path, not win a race against the local respawn
            supervisor = Supervisor(
                workers,
                argv_for=(stub_tcp_argv if stub else serve_tcp_argv),
                env_for=lambda name, chips: env,
                probe_interval_s=0.25,
                backoff_base_s=1.5 if stub else 0.25,
                backoff_max_s=3.0,
                startup_grace_s=boot_timeout,
            )
            # dispatch_wait_s is SHORT on the domain tier: a domain
            # with its worker down must answer no_backend_available
            # quickly so the front tier fails over to another host,
            # instead of parking the request until the local respawn
            router = Router(
                workers,
                supervisor=supervisor,
                probe_interval_s=0.1,
                request_timeout_s=req_timeout,
                dispatch_wait_s=1.0 if stub else 10.0,
                trace_sample=0.0,
            )
            domains.append({
                "supervisor": supervisor,
                "router": router,
                "front_target": None,
                "server": None,
                "thread": None,
            })
        for dom in domains:
            dom["supervisor"].start()
        for d, dom in enumerate(domains):
            if not dom["supervisor"].wait_healthy(boot_timeout):
                problems.append(
                    f"domain {d} workers never became healthy: "
                    f"{dom['supervisor'].status()}"
                )
                raise _Abort()
            dom["router"].start()
            # in-process listeners lease their ports race-free: bind
            # :0, read bound_port (only the worker SUBPROCESS targets
            # above need the close-then-rebind _free_port lease)
            dom["server"] = FrontServer(
                "127.0.0.1:0", dom["router"], stall_timeout_s=2.0
            )
            dom["front_target"] = f"127.0.0.1:{dom['server'].bound_port}"
            dom["thread"] = threading.Thread(
                target=dom["server"].serve_forever,
                kwargs={"poll_interval": 0.05}, daemon=True,
            )
            dom["thread"].start()

        # -- the federation tier: one front router over the domains --
        hosts = {
            f"host{d}": dom["front_target"]
            for d, dom in enumerate(domains)
        }
        front_router = Router(
            hosts,
            probe_interval_s=0.1,
            request_timeout_s=req_timeout + 5.0,
            dispatch_wait_s=req_timeout + 30.0,
            trace_sample=0.0,
            merge_label="host",
        )
        front_router.start()
        edge = HttpEdgeServer(
            "127.0.0.1:0", front_router,
            tokens={token: "drill"},
            rate_per_client=100000.0,
            stall_timeout_s=2.0,
        )
        edge_target = f"127.0.0.1:{edge.bound_port}"
        edge_thread = threading.Thread(
            target=edge.serve_forever, kwargs={"poll_interval": 0.05},
            daemon=True,
        )
        edge_thread.start()

        # -- HTTP open-loop burst through the edge --
        burst = _edge_burst(
            edge_target, token, problems,
            rate=600.0 if stub else 60.0,
        )

        # -- auth: a wrong token answers 401, backends untouched --
        client = _HttpClient(edge_target, "wrong-token", req_timeout)
        try:
            code, _hdrs, _body = client.post(
                "/classify", b'{"content": "auth probe"}'
            )
        finally:
            client.close()
        if code != 401:
            problems.append(f"bad token answered {code}, wanted 401")

        # -- SIGKILL one host's worker mid-stream: zero client errors,
        #    the front tier fails over across hosts --
        loris = faults.Slowloris(
            edge_target, mode="dribble", byte_interval_s=0.25,
            give_up_s=30.0,
            payload=b"POST /classify HTTP/1.1\r\nHost: edge\r\nContent-Le",
        )
        loris_box: dict = {}
        loris_thread = threading.Thread(
            target=lambda: loris_box.update(loris.run()), daemon=True
        )
        loris_thread.start()
        victim = domains[0]["supervisor"]
        kill_at = n_requests // 3
        client = _HttpClient(edge_target, token, req_timeout + 30.0)
        traces = set()
        try:
            for i in range(n_requests):
                body = json.dumps(
                    {"id": i, "content": f"federation drill {i}"}
                ).encode("utf-8")
                code, hdrs, _payload = client.post("/classify", body)
                statuses.append(code)
                if hdrs.get("x-trace-id"):
                    traces.add(hdrs["x-trace-id"])
                if i + 1 == kill_at:
                    handle = next(iter(victim.workers.values()))
                    if handle.pid is None:
                        problems.append("victim worker had no pid")
                    else:
                        faults.kill(handle.pid)
        except OSError as exc:
            problems.append(f"drill client failed: {exc}")
        finally:
            client.close()
        bad = [c for c in statuses if c != 200]
        if bad:
            problems.append(
                f"{len(bad)} non-200 responses during the SIGKILL "
                f"drill (e.g. {bad[:5]}) — a client saw the failure"
            )
        if len(statuses) != n_requests:
            problems.append(
                f"drill answered {len(statuses)}/{n_requests} requests"
            )
        if not traces:
            problems.append(
                "no X-Trace-Id header echoed — the telemetry plane "
                "does not span the edge"
            )
        # -- the front tier actually failed over across hosts --
        fstats = front_router.stats()["router"]
        if fstats["failovers"] + fstats["retries"] < 1:
            problems.append(
                f"no cross-host failover recorded — did the kill "
                f"land? {fstats}"
            )
        # -- the dead worker rejoined its domain --
        name = next(iter(victim.workers))
        deadline = time.perf_counter() + boot_timeout
        revived = False
        while time.perf_counter() < deadline:
            handle = victim.workers[name]
            if handle.restarts >= 1 and victim.probe(name) is not None:
                revived = True
                break
            time.sleep(0.1)
        if not revived:
            problems.append(
                f"domain-0 worker never rejoined: {victim.status()}"
            )
        health = victim.host_health()
        if not health.get("serving"):
            problems.append(f"domain-0 host health not serving: {health}")
        loris_thread.join(timeout=40.0)
        if not loris_box.get("reaped"):
            problems.append(
                f"HTTP header slowloris was not reaped: {loris_box}"
            )
        # -- merged exposition: host label OUTSIDE worker label --
        exposition = front_router.prometheus()
        grammar = check_exposition(exposition)
        if grammar:
            problems.append(f"merged exposition grammar: {grammar[:3]}")
        if 'host="host1"' not in exposition:
            problems.append("merged exposition missing host labels")
        if not re.search(r'host="host\d",worker="', exposition):
            problems.append(
                "merged exposition does not nest host= outside the "
                "per-domain worker= labels"
            )
    except _Abort:
        pass
    except Exception as exc:  # noqa: BLE001 — selftest must report, not die
        problems.append(f"selftest crashed: {type(exc).__name__}: {exc}")
    finally:
        if edge is not None:
            edge.shutdown()
            edge.server_close()
        if edge_thread is not None:
            edge_thread.join(timeout=5.0)
        if front_router is not None:
            front_router.close()
        for dom in domains:
            if dom["server"] is not None:
                dom["server"].shutdown()
                dom["server"].server_close()
            if dom["thread"] is not None:
                dom["thread"].join(timeout=5.0)
            dom["router"].close()
            dom["supervisor"].stop()
    if verbose:
        summary = {
            "fleet_tcp_selftest": "ok" if not problems else "FAIL",
            "stub_workers": stub,
            "domains": n_domains,
            "burst": burst,
            "drill_requests": len(statuses),
            "problems": problems,
        }
        sys.stderr.write(json.dumps(summary) + "\n")
    return 0 if not problems else 1


def _drive_traffic(
    front_path: str,
    blobs: list[str],
    n_requests: int,
    supervisor: Supervisor,
    problems: list[str],
    read_timeout: float,
    kill_at_fraction: float = 1.0 / 3.0,
) -> list[dict]:
    """Stream ``n_requests`` through the front socket from a writer
    thread, SIGKILL worker w0 once a third of the stream is out, and
    collect every response row."""
    kill_at = max(1, int(n_requests * kill_at_fraction))
    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    f = None
    try:
        sock.connect(front_path)
        sock.settimeout(read_timeout)
        f = sock.makefile("rwb")
        stream = f

        def writer() -> None:
            try:
                for i in range(n_requests):
                    line = json.dumps({
                        "id": i,
                        "content": blobs[i % len(blobs)],
                        "filename": "LICENSE",
                    })
                    stream.write(line.encode("utf-8") + b"\n")
                    stream.flush()
                    if i + 1 == kill_at:
                        pid = supervisor.workers["w0"].pid
                        if pid is None:
                            problems.append("w0 had no pid at kill time")
                        else:
                            faults.kill(pid)
                    # unpaced burst right before the kill: the paced
                    # stream can be fully drained at kill time (the
                    # probe conn's EOF flips the backend unhealthy the
                    # same instant, so nothing would ever fail over) —
                    # the gate wants the kill to land WITH requests in
                    # flight on the victim
                    if not (kill_at - 10 <= i + 1 < kill_at):
                        time.sleep(0.005)
            except OSError as exc:
                problems.append(f"client writer failed: {exc}")

        wt = threading.Thread(target=writer, daemon=True)
        wt.start()
        rows: list[dict] = []
        try:
            for _ in range(n_requests):
                raw = f.readline()
                if not raw:
                    problems.append(
                        f"front socket closed after {len(rows)} responses"
                    )
                    break
                rows.append(
                    json.loads(raw.decode("utf-8", errors="replace"))
                )
        except (OSError, ValueError) as exc:
            problems.append(f"client reader failed: {exc}")
        wt.join(timeout=read_timeout)
        return rows
    finally:
        # close on EVERY path (the static resource-leak rule's point):
        # a reader failure must not leak the session socket into the
        # next selftest stage
        try:
            if f is not None:
                f.close()
            sock.close()
        except OSError:
            pass


def _err_code(row: dict) -> str | None:
    """The error-code prefix of a response row, or None."""
    err = row.get("error")
    if not isinstance(err, str):
        return None
    return err.split(":", 1)[0]


class _TenantTraffic:
    """Continuous per-tenant HTTP traffic through the edge for the
    tenancy drill: sequential keep-alive POSTs under one bearer token,
    every answer collected (status + parsed body row, so the gates can
    read the worker name and corpus fingerprint each answer carries)."""

    def __init__(self, edge_target: str, token: str, timeout_s: float):
        self.edge_target = edge_target
        self.token = token
        self.timeout_s = timeout_s
        self.rows: list[dict] = []
        self.errors: list[str] = []
        self.reconnects = 0
        self.stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)

    def start(self) -> None:
        self._thread.start()

    def finish(self) -> None:
        self.stop.set()
        self._thread.join(timeout=self.timeout_s + 10.0)

    def _run(self) -> None:
        client = None
        i = 0
        while not self.stop.is_set():
            try:
                if client is None:
                    client = _HttpClient(
                        self.edge_target, self.token, self.timeout_s
                    )
                body = json.dumps({
                    "id": i,
                    "content": f"tenant drill {self.token} {i}",
                }).encode("utf-8")
                code, _hdrs, payload = client.post("/classify", body)
                try:
                    row = json.loads(payload.decode("utf-8", "replace"))
                except ValueError:
                    row = {}
                if not isinstance(row, dict):
                    row = {}
                row["_status"] = code
                self.rows.append(row)
                i += 1
            except OSError as exc:
                # the edge must never drop a keep-alive session during
                # a roll or an in-pool failover: reconnects are counted
                # as findings, a failure on a fresh connection is hard
                if client is None:
                    self.errors.append(str(exc))
                    self.stop.wait(0.2)
                else:
                    self.reconnects += 1
                    client.close()
                    client = None
            time.sleep(0.005)
        if client is not None:
            client.close()


def selftest_tenant(
    verbose: bool = True,
    stub: bool = True,
    workers_per_pool: int = 2,
) -> int:
    """The multi-tenant serving selftest (``licensee-tpu fleet
    --selftest-tenant``): two tenants with DISJOINT corpora on separate
    worker pools behind one router and one HTTP edge, drilled under
    live traffic.  The gates:

    * tagged routing: a ``corpus`` tag (tenant name, pool name, or
      fingerprint) lands on the right pool, untagged rows fall back to
      the default pool, an unknown tag answers ``unknown_corpus``;
    * ZERO cross-tenant rows: every answer a tenant's token receives
      stamps that tenant's corpus fingerprint and a worker from that
      tenant's pool — across an upload-roll and a SIGKILL;
    * self-serve onboarding: an authenticated ``POST /corpus`` from
      tenant A validates, journals, and rolls A's pool zero-downtime
      while tenant B's traffic keeps answering inside its latency SLO;
    * auth: a wrong bearer token answers 401; a valid token bound to
      no tenant answers 403 on ``POST /corpus``; a garbage artifact
      answers 400 ``corpus_invalid`` without touching the fleet;
    * SIGKILL of one pool's worker fails over ONLY inside that pool
      with zero client-visible errors, and the worker rejoins;
    * crash recovery: a dangling journaled ``roll_start`` is replayed
      by a fresh onboarder and the pool lands on the rolled corpus.

    ``stub=True`` (the CI path) runs protocol-faithful stub workers
    whose "corpus" is the fingerprint string their reload installs;
    ``stub=False`` boots real serve workers on vendored/spdx corpora
    and onboards a real corpus artifact."""
    from licensee_tpu.tenancy import (
        CorpusOnboarder, OnboardError, Tenant, TenantPools,
        TenantRegistry,
    )

    problems: list[str] = []
    tmpdir = tempfile.mkdtemp(prefix="licensee-tenant-fleet-")
    boot_timeout = 20.0 if stub else 240.0
    req_timeout = 10.0 if stub else 120.0
    pool_names = ("acme", "beta")
    pool_sockets = {
        pool: {
            f"{pool}{i}": os.path.join(tmpdir, f"{pool}{i}.sock")
            for i in range(workers_per_pool)
        }
        for pool in pool_names
    }
    if stub:
        boot_corpus = {"acme": "fp-acme-1", "beta": "fp-beta-1"}

        def argv_for(name: str, sock: str) -> list[str]:
            pool = name.rstrip("0123456789")
            return [
                sys.executable, "-m", "licensee_tpu.fleet.faults",
                "--socket", sock, "--name", name, "--service-ms", "5",
                "--fingerprint", boot_corpus[pool],
            ]
    else:
        boot_corpus = {"acme": "vendored", "beta": "spdx"}

        def argv_for(name: str, sock: str) -> list[str]:
            pool = name.rstrip("0123456789")
            return _serve_argv(name, sock) + [
                "--corpus", boot_corpus[pool]
            ]

    env = worker_env(None, None)
    env.setdefault("JAX_PLATFORMS", "cpu")
    pools = TenantPools({
        pool: Supervisor(
            sockets,
            argv_for=argv_for,
            env_for=lambda name, chips: env,
            probe_interval_s=0.25,
            backoff_base_s=0.25,
            backoff_max_s=2.0,
            startup_grace_s=boot_timeout,
        )
        for pool, sockets in pool_sockets.items()
    }, default_pool="acme")
    router = Router(
        pools.workers,
        supervisor=pools,
        probe_interval_s=0.25,
        request_timeout_s=req_timeout,
        dispatch_wait_s=req_timeout + 30.0,
        trace_sample=0.0,
        pools=pools.worker_pools(),
        default_pool="acme",
    )
    registry = TenantRegistry(
        os.path.join(tmpdir, "tenants.json"), create=True
    )
    registry.set_tenant(
        Tenant("acme", "tok-acme", boot_corpus["acme"]), save=False
    )
    registry.set_tenant(Tenant("beta", "tok-beta", boot_corpus["beta"]))
    front_path = os.path.join(tmpdir, "front.sock")
    server = None
    server_thread = None
    edge = None
    edge_thread = None
    traffic: dict[str, _TenantTraffic] = {}
    onboard_result: dict | None = None
    recovered: list[dict] = []
    try:
        pools.start()
        if not pools.wait_healthy(boot_timeout):
            problems.append(
                f"pools never became healthy: {pools.status()}"
            )
            raise _Abort()
        router.start()

        if stub:
            def validator(path: str) -> str:
                with open(path, encoding="utf-8", errors="replace") as fh:
                    text = fh.read().strip()
                if not text.startswith("fp-"):
                    raise ValueError(
                        f"stub artifact must start with 'fp-', got "
                        f"{text[:20]!r}"
                    )
                return text

            onboarder = CorpusOnboarder(
                registry, pools, router,
                staging_dir=os.path.join(tmpdir, "staging"),
                validator=validator,
                source_for=lambda path, fp: fp,
                reload_kwargs={
                    "timeout_s": req_timeout + 60.0,
                    "health_timeout_s": 30.0,
                    "argv_patch": _patch_stub_argv,
                },
            )
            pool_fps = dict(boot_corpus)
        else:
            onboarder = CorpusOnboarder(
                registry, pools, router,
                staging_dir=os.path.join(tmpdir, "staging"),
                reload_kwargs={
                    "timeout_s": req_timeout + 60.0,
                    "health_timeout_s": 30.0,
                },
            )
            fps = _fingerprints(pools)
            owners = pools.worker_pools()
            pool_fps = {
                owners[name]: fp
                for name, fp in fps.items() if fp
            }
            if set(pool_fps) != set(pool_names):
                problems.append(
                    f"workers report no fingerprints: {fps}"
                )
                raise _Abort()
        onboarder.sync_routes(fingerprints=pool_fps)

        server = FrontServer(front_path, router, stall_timeout_s=2.0)
        server_thread = threading.Thread(
            target=server.serve_forever, kwargs={"poll_interval": 0.05},
            daemon=True,
        )
        server_thread.start()
        edge_tokens = dict(registry.tokens())
        edge_tokens["tok-anon"] = "anon"  # valid token, no tenant
        edge = HttpEdgeServer(
            "127.0.0.1:0", router,
            tokens=edge_tokens,
            tenancy=onboarder,
            rate_per_client=100000.0,
            stall_timeout_s=2.0,
        )
        edge_target = f"127.0.0.1:{edge.bound_port}"
        edge_thread = threading.Thread(
            target=edge.serve_forever, kwargs={"poll_interval": 0.05},
            daemon=True,
        )
        edge_thread.start()

        workers_of = {
            pool: set(socks) for pool, socks in pool_sockets.items()
        }
        fp_observed: dict[str, set] = {pool: set() for pool in pool_names}

        def allowed_fps(pool: str, *fps) -> set:
            out = set()
            for fp in fps:
                if fp:
                    out.add(fp)
                    short = short_fingerprint(fp)
                    if short:
                        out.add(short)
            return out

        def check_row(label: str, row: dict, pool: str,
                      allowed: set) -> None:
            if _err_code(row) is not None:
                problems.append(f"{label}: error row {row}")
                return
            worker = row.get("worker")
            if worker not in workers_of[pool]:
                problems.append(
                    f"{label}: answered by {worker!r}, not a {pool} "
                    f"pool worker"
                )
            fp = row.get("corpus")
            fp_observed[pool].add(fp)
            if fp not in allowed:
                problems.append(
                    f"{label}: stamps corpus {fp!r}, allowed "
                    f"{sorted(allowed)}"
                )

        # -- phase 1: tagged JSONL routing through the front socket --
        probe_timeout = req_timeout + 30.0
        for tag, pool in (
            ("acme", "acme"),             # tenant name
            ("beta", "beta"),
            (pool_fps["beta"], "beta"),   # full fingerprint
            (None, "acme"),               # untagged -> default pool
        ):
            msg: dict = {"id": 1, "content": f"probe {tag}"}
            if tag is not None:
                msg["corpus"] = tag
            row = oneshot(front_path, msg, probe_timeout)
            check_row(
                f"tagged probe {tag!r}", row, pool,
                allowed_fps(pool, pool_fps[pool]),
            )
        row = oneshot(
            front_path,
            {"id": 1, "content": "probe", "corpus": "no-such-tenant"},
            probe_timeout,
        )
        if _err_code(row) != "unknown_corpus":
            problems.append(
                f"unknown corpus tag answered {row}, wanted an "
                f"unknown_corpus error"
            )

        # -- phase 2: live per-tenant HTTP traffic, then an upload-roll
        #    of tenant acme mid-stream --
        for name, token in (("acme", "tok-acme"), ("beta", "tok-beta")):
            traffic[name] = _TenantTraffic(
                edge_target, token, req_timeout + 30.0
            )
            traffic[name].start()
        time.sleep(0.6 if stub else 2.0)

        if stub:
            upload_blob = b"fp-acme-2"
        else:
            from licensee_tpu.corpus.artifact import write_artifact
            from licensee_tpu.corpus.compiler import default_corpus

            artifact_path = os.path.join(tmpdir, "upload.corpus.npz")
            write_artifact(
                artifact_path, default_corpus(), source="vendored"
            )
            with open(artifact_path, "rb") as fh:
                upload_blob = fh.read()
        import base64 as _b64

        upload_body = json.dumps({
            "artifact_b64": _b64.b64encode(upload_blob).decode("ascii"),
            "name": "upload.corpus.npz",
        }).encode("utf-8")
        client = _HttpClient(
            edge_target, "tok-acme", req_timeout + 120.0
        )
        try:
            code, _hdrs, payload = client.post("/corpus", upload_body)
        finally:
            client.close()
        if code != 200:
            problems.append(
                f"corpus upload answered {code}: {payload[:300]!r}"
            )
        else:
            onboard_result = (
                json.loads(payload.decode("utf-8", "replace"))
            ).get("corpus") or {}
            if onboard_result.get("pool") != "acme":
                problems.append(
                    f"upload rolled pool {onboard_result.get('pool')!r},"
                    f" wanted 'acme'"
                )
        rolled_fp = (onboard_result or {}).get("fingerprint")
        if stub and rolled_fp != "fp-acme-2":
            problems.append(
                f"upload rolled to {rolled_fp!r}, wanted 'fp-acme-2'"
            )
        time.sleep(0.4 if stub else 2.0)

        # -- phase 3: SIGKILL one beta worker under traffic: in-pool
        #    failover only, zero client-visible errors --
        victim = pools.pools["beta"]
        pid = victim.workers["beta0"].pid
        if pid is None:
            problems.append("beta0 had no pid at kill time")
        else:
            faults.kill(pid)
        if not _await_respawn(victim, "beta0", boot_timeout + 10.0):
            problems.append("beta0 never respawned after SIGKILL")
        time.sleep(0.4 if stub else 2.0)
        for t in traffic.values():
            t.finish()

        # -- the cross-tenant fence, across roll AND kill --
        acme_allowed = allowed_fps("acme", pool_fps["acme"], rolled_fp)
        beta_allowed = allowed_fps("beta", pool_fps["beta"])
        for name, allowed in (
            ("acme", acme_allowed), ("beta", beta_allowed),
        ):
            t = traffic[name]
            if t.errors:
                problems.append(
                    f"{name} traffic errors: {t.errors[:3]}"
                )
            if t.reconnects:
                problems.append(
                    f"{name} edge session dropped {t.reconnects} time(s)"
                )
            bad = [r for r in t.rows if r.get("_status") != 200]
            if bad:
                problems.append(
                    f"{name}: {len(bad)} non-200 answers, e.g. {bad[:3]}"
                )
            if len(t.rows) < 20:
                problems.append(
                    f"{name}: only {len(t.rows)} rows — the drill did "
                    f"not run under load"
                )
            for row in t.rows:
                if row.get("_status") != 200:
                    continue
                check_row(f"{name} traffic", row, name, allowed)
        if stub and "fp-acme-2" not in fp_observed["acme"]:
            problems.append(
                "acme traffic never reached the rolled corpus "
                f"(saw {sorted(fp_observed['acme'])})"
            )
        crossed = fp_observed["acme"] & fp_observed["beta"]
        if crossed:
            problems.append(
                f"cross-tenant fingerprints observed: {sorted(crossed)}"
            )

        # -- tenant B's latency SLO survived tenant A's roll --
        slo = router.stats().get("slo") or {}
        beta_slo = (
            (slo.get("objectives") or {}).get("pool_beta_latency_p99")
            or {}
        )
        if not beta_slo:
            problems.append(f"router stats carries no beta pool SLO: {slo}")
        else:
            if not (beta_slo.get("good") or 0) > 0:
                problems.append(f"beta pool SLO saw no traffic: {beta_slo}")
            max_burn = beta_slo.get("max_burn")
            if max_burn is None or not (max_burn < 1.0):
                problems.append(
                    f"beta latency SLO breached during acme's roll: "
                    f"max_burn={max_burn}"
                )
        # -- the kill actually exercised failover --
        rstats = router.stats()["router"]
        if rstats["failovers"] + rstats["retries"] < 1:
            problems.append(
                f"no failover recorded — did the kill land? {rstats}"
            )

        # -- auth probes --
        client = _HttpClient(edge_target, "wrong-token", req_timeout)
        try:
            code, _h, _b = client.post(
                "/classify", b'{"content": "auth probe"}'
            )
        finally:
            client.close()
        if code != 401:
            problems.append(f"bad token answered {code}, wanted 401")
        client = _HttpClient(edge_target, "tok-anon", req_timeout)
        try:
            code, _h, _b = client.post("/corpus", upload_body)
        finally:
            client.close()
        if code != 403:
            problems.append(
                f"tenant-less token answered {code} on POST /corpus, "
                f"wanted 403"
            )
        garbage = json.dumps({
            "artifact_b64": _b64.b64encode(
                b"garbage, not an artifact"
            ).decode("ascii"),
        }).encode("utf-8")
        client = _HttpClient(edge_target, "tok-acme", req_timeout + 60.0)
        try:
            code, _h, body = client.post("/corpus", garbage)
        finally:
            client.close()
        bad_row = {}
        try:
            bad_row = json.loads(body.decode("utf-8", "replace"))
        except ValueError:
            pass
        if code != 400 or _err_code(bad_row) != "corpus_invalid":
            problems.append(
                f"garbage artifact answered {code} {bad_row}, wanted "
                f"400 corpus_invalid"
            )

        # -- phase 4 (stub): crash recovery — a dangling journaled
        #    roll_start is replayed by a FRESH onboarder at boot --
        if stub:
            registry.record_roll(
                "roll_start", "acme",
                corpus="fp-acme-3", fingerprint="fp-acme-3",
            )
            recovery = CorpusOnboarder(
                registry, pools, router,
                staging_dir=os.path.join(tmpdir, "staging"),
                validator=validator,
                source_for=lambda path, fp: fp,
                reload_kwargs={
                    "timeout_s": req_timeout + 60.0,
                    "health_timeout_s": 30.0,
                    "argv_patch": _patch_stub_argv,
                },
            )
            try:
                recovered = recovery.recover()
            except OnboardError as exc:
                problems.append(f"journal recovery raised: {exc}")
            if len(recovered) != 1 or (
                recovered[0].get("fingerprint") != "fp-acme-3"
            ):
                problems.append(
                    f"journal recovery did not replay the dangling "
                    f"roll: {recovered}"
                )
            fps_now = {
                fp for name, fp in _fingerprints(pools).items()
                if name in workers_of["acme"]
            }
            if fps_now != {"fp-acme-3"}:
                problems.append(
                    f"recovered acme pool serves {fps_now}, wanted "
                    "{'fp-acme-3'}"
                )
            if router.pool_fingerprints().get("acme") != "fp-acme-3":
                problems.append(
                    f"router fence not re-armed after recovery: "
                    f"{router.pool_fingerprints()}"
                )
            if registry.pending_rolls():
                problems.append(
                    f"journal still pending after recovery: "
                    f"{registry.pending_rolls()}"
                )
    except _Abort:
        pass
    except Exception as exc:  # noqa: BLE001 — selftest must report, not die
        problems.append(f"selftest crashed: {type(exc).__name__}: {exc}")
    finally:
        for t in traffic.values():
            if not t.stop.is_set():
                t.finish()
        if edge is not None:
            edge.shutdown()
            edge.server_close()
        if edge_thread is not None:
            edge_thread.join(timeout=5.0)
        if server is not None:
            server.shutdown()
            server.server_close()
        if server_thread is not None:
            server_thread.join(timeout=5.0)
        router.close()
        pools.stop()
        registry.close()
    if verbose:
        summary = {
            "tenant_fleet_selftest": "ok" if not problems else "FAIL",
            "stub_workers": stub,
            "traffic_rows": {
                name: len(t.rows) for name, t in traffic.items()
            },
            "onboarded": onboard_result,
            "recovered": recovered,
            "problems": problems,
        }
        sys.stderr.write(json.dumps(summary) + "\n")
    return 0 if not problems else 1
