"""Fleet selftest — the CI smoke behind ``licensee-tpu fleet
--selftest``.

Boots a REAL fleet on this host: a supervisor spawning 2 serve worker
processes (CPU-pinned), the router fronting them on a Unix socket, and
a live client streaming classification traffic through the front door.
Mid-stream, one worker is SIGKILLed (faults.kill — a real SIGKILL to a
real process).  The gate:

* ZERO client-visible errors: every request answers with the correct
  verdict via retry/failover, connection resets and queue losses
  included;
* the supervisor restarts the dead worker within its backoff budget
  and the worker rejoins the rotation (answers probes again);
* trace IDs propagate: at least one router-minted trace ID (route
  span) appears verbatim in a worker's ``{"op": "trace"}`` tail;
* the merged fleet exposition (router registry + per-worker scrapes,
  ``worker``-labeled) parses clean against the Prometheus grammar;
* a graceful drain completes with zero in-flight work (the rolling-
  restart primitive).

``stub=True`` swaps the workers for the protocol-faithful stub
(faults.py) — same supervisor, router, sockets, and SIGKILL, no JAX
import per worker — the fast path the unit tests ride.
"""

from __future__ import annotations

import json
import os
import re
import socket
import sys
import tempfile
import threading
import time

from licensee_tpu.fleet import faults
from licensee_tpu.fleet.router import FrontServer, Router
from licensee_tpu.fleet.supervisor import Supervisor, worker_env
from licensee_tpu.fleet.wire import WireError, oneshot
from licensee_tpu.obs import check_exposition


def _stub_argv(name: str, sock: str) -> list[str]:
    return [
        sys.executable, "-m", "licensee_tpu.fleet.faults",
        "--socket", sock, "--name", name, "--service-ms", "10",
    ]


def _serve_argv(name: str, sock: str) -> list[str]:
    return [
        sys.executable, "-m", "licensee_tpu.cli.main", "serve",
        "--socket", sock, "--max-delay-ms", "5",
        "--trace-sample", "1.0",
    ]


def _client_blobs(stub: bool, n_unique: int = 8) -> list[str]:
    if stub:
        return [f"stub blob {i}" for i in range(n_unique)]
    from licensee_tpu.corpus.license import License

    body = re.sub(
        r"\[(\w+)\]", "example", License.find("mit").content or ""
    )
    # unique Dice-bound variants: defeat the Exact prefilter so rows
    # cross each worker's device path (the serving path under test)
    return [f"{body}\nzqfleet{i} zqtail{i}\n" for i in range(n_unique)]


def _worker_trace_ids(socket_path: str) -> set[str]:
    try:
        row = oneshot(socket_path, {"op": "trace", "n": 100}, 5.0)
    except WireError:
        return set()
    return {
        t.get("trace") for t in row.get("traces") or [] if t.get("trace")
    }


def selftest(
    verbose: bool = True,
    stub: bool = False,
    n_workers: int = 2,
    n_requests: int = 120,
) -> int:
    problems: list[str] = []
    tmpdir = tempfile.mkdtemp(prefix="licensee-fleet-")
    sockets = {
        f"w{i}": os.path.join(tmpdir, f"w{i}.sock")
        for i in range(n_workers)
    }
    boot_timeout = 20.0 if stub else 240.0
    req_timeout = 10.0 if stub else 120.0
    env = worker_env(None, None)
    env.setdefault("JAX_PLATFORMS", "cpu")  # the CI contract: CPU workers
    supervisor = Supervisor(
        sockets,
        argv_for=(_stub_argv if stub else _serve_argv),
        env_for=lambda name, chips: env,
        probe_interval_s=0.25,
        backoff_base_s=0.25,
        backoff_max_s=2.0,
        startup_grace_s=boot_timeout,
    )
    router = Router(
        sockets,
        supervisor=supervisor,
        probe_interval_s=0.25,
        request_timeout_s=req_timeout,
        dispatch_wait_s=req_timeout + 30.0,
        trace_sample=1.0,
    )
    front_path = os.path.join(tmpdir, "front.sock")
    server = None
    server_thread = None
    try:
        supervisor.start()
        if not supervisor.wait_healthy(boot_timeout):
            problems.append(
                f"workers never became healthy: {supervisor.status()}"
            )
            raise _Abort()
        router.start()
        server = FrontServer(front_path, router)
        server_thread = threading.Thread(
            target=server.serve_forever, kwargs={"poll_interval": 0.05},
            daemon=True,
        )
        server_thread.start()

        blobs = _client_blobs(stub)
        rows = _drive_traffic(
            front_path, blobs, n_requests, supervisor, problems,
            read_timeout=req_timeout + 60.0,
        )
        # -- zero client-visible errors, correct verdicts --
        want_key = "stub-mit" if stub else "mit"
        errors = [r for r in rows if r.get("error")]
        if errors:
            problems.append(
                f"{len(errors)} client-visible errors, e.g. {errors[:3]}"
            )
        wrong = [r for r in rows if not r.get("error")
                 and r.get("key") != want_key]
        if wrong:
            problems.append(f"wrong verdicts, e.g. {wrong[:3]}")
        if len(rows) != n_requests:
            problems.append(
                f"response count {len(rows)} != requests {n_requests}"
            )
        # -- the dead worker restarted within the backoff budget --
        handle = supervisor.workers["w0"]
        budget = (
            supervisor.backoff_delay_s(0)
            + supervisor.backoff_delay_s(1)
            + boot_timeout
        )
        deadline = time.perf_counter() + budget
        revived = False
        while time.perf_counter() < deadline:
            if handle.restarts >= 1 and supervisor.probe("w0") is not None:
                revived = True
                break
            time.sleep(0.1)
        if not revived:
            problems.append(
                f"w0 not restarted within {budget:.1f}s budget: "
                f"{supervisor.status()}"
            )
        # -- the router actually failed over (the kill landed mid-stream) --
        rstats = router.stats()["router"]
        if rstats["failovers"] + rstats["retries"] < 1:
            problems.append(
                f"no failover recorded — did the kill land? {rstats}"
            )
        # -- trace propagation router -> worker --
        routed_ids = {
            t["trace"]
            for t in router.trace_tail(200)
            if any(s["name"] == "route" for s in t.get("spans", ()))
        }
        worker_ids = set()
        for sock in sockets.values():
            worker_ids |= _worker_trace_ids(sock)
        if not routed_ids:
            problems.append("router retained no routed traces")
        elif not (routed_ids & worker_ids):
            problems.append(
                f"no router trace ID found in any worker tail "
                f"({len(routed_ids)} routed, {len(worker_ids)} worker-side)"
            )
        # -- merged fleet exposition --
        exposition = router.prometheus()
        grammar = check_exposition(exposition)
        if grammar:
            problems.append(f"merged exposition grammar: {grammar[:3]}")
        if 'worker="w1"' not in exposition:
            problems.append("merged exposition missing worker labels")
        if 'fleet_requests_total{worker="router",event="ok"}' not in (
            exposition
        ):
            problems.append("merged exposition missing router series")
        # -- graceful drain completes in-flight and stops the worker --
        drained_clean = supervisor.drain(
            "w1", timeout_s=30.0, restart=False
        )
        if not drained_clean:
            problems.append("drain of idle w1 was not clean")
        if supervisor.workers["w1"].state != "stopped":
            problems.append(
                f"drained worker state: {supervisor.workers['w1'].state}"
            )
    except _Abort:
        pass
    except Exception as exc:  # noqa: BLE001 — selftest must report, not die
        problems.append(f"selftest crashed: {type(exc).__name__}: {exc}")
    finally:
        if server is not None:
            server.shutdown()
            server.server_close()
        if server_thread is not None:
            server_thread.join(timeout=5.0)
        router.close()
        supervisor.stop()
    if verbose:
        summary = {
            "fleet_selftest": "ok" if not problems else "FAIL",
            "stub_workers": stub,
            "problems": problems,
        }
        sys.stderr.write(json.dumps(summary) + "\n")
    return 0 if not problems else 1


class _Abort(Exception):
    """Internal early-exit: boot failed, nothing further to assert."""


def _drive_traffic(
    front_path: str,
    blobs: list[str],
    n_requests: int,
    supervisor: Supervisor,
    problems: list[str],
    read_timeout: float,
    kill_at_fraction: float = 1.0 / 3.0,
) -> list[dict]:
    """Stream ``n_requests`` through the front socket from a writer
    thread, SIGKILL worker w0 once a third of the stream is out, and
    collect every response row."""
    kill_at = max(1, int(n_requests * kill_at_fraction))
    sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    f = None
    try:
        sock.connect(front_path)
        sock.settimeout(read_timeout)
        f = sock.makefile("rwb")
        stream = f

        def writer() -> None:
            try:
                for i in range(n_requests):
                    line = json.dumps({
                        "id": i,
                        "content": blobs[i % len(blobs)],
                        "filename": "LICENSE",
                    })
                    stream.write(line.encode("utf-8") + b"\n")
                    stream.flush()
                    if i + 1 == kill_at:
                        pid = supervisor.workers["w0"].pid
                        if pid is None:
                            problems.append("w0 had no pid at kill time")
                        else:
                            faults.kill(pid)
                    time.sleep(0.005)
            except OSError as exc:
                problems.append(f"client writer failed: {exc}")

        wt = threading.Thread(target=writer, daemon=True)
        wt.start()
        rows: list[dict] = []
        try:
            for _ in range(n_requests):
                raw = f.readline()
                if not raw:
                    problems.append(
                        f"front socket closed after {len(rows)} responses"
                    )
                    break
                rows.append(
                    json.loads(raw.decode("utf-8", errors="replace"))
                )
        except (OSError, ValueError) as exc:
            problems.append(f"client reader failed: {exc}")
        wt.join(timeout=read_timeout)
        return rows
    finally:
        # close on EVERY path (the static resource-leak rule's point):
        # a reader failure must not leak the session socket into the
        # next selftest stage
        try:
            if f is not None:
                f.close()
            sock.close()
        except OSError:
            pass
