"""Fleet tier: multi-worker serving supervision and routing.

One ``licensee-tpu fleet`` process = a :class:`Supervisor` (spawn N
serve workers, health-check, restart with backoff, drain gracefully —
fleet/supervisor.py), a :class:`Router` (least-loaded dispatch, hedged
retries, failover — fleet/router.py) fronting them on a single client
socket, and the fault harness (fleet/faults.py) + selftest
(fleet/selftest.py) that prove the pair rides out crashes, hangs, and
brownouts with zero client-visible errors.

Exports resolve lazily: ``python -m licensee_tpu.fleet.faults`` (the
stub worker the fault tests spawn by the dozen) must not pay the serve
import chain just to exist.
"""

from __future__ import annotations

_EXPORTS = {
    "Router": "licensee_tpu.fleet.router",
    "FrontServer": "licensee_tpu.fleet.router",
    "Supervisor": "licensee_tpu.fleet.supervisor",
    "WorkerHandle": "licensee_tpu.fleet.supervisor",
    "default_worker_argv": "licensee_tpu.fleet.supervisor",
    "worker_env": "licensee_tpu.fleet.supervisor",
    "Connection": "licensee_tpu.fleet.wire",
    "ConnectionPool": "licensee_tpu.fleet.wire",
    "WireError": "licensee_tpu.fleet.wire",
    "oneshot": "licensee_tpu.fleet.wire",
}

__all__ = list(_EXPORTS)


def __getattr__(name: str):
    module = _EXPORTS.get(name)
    if module is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(module), name)
