"""Deterministic SPDX license-list-XML synthesizer for at-scale runs.

The real license list (github.com/spdx/license-list-XML) holds ~600
entries; the reference vendors only the 47 choosealicense-mirrored XMLs
(`script/vendor-spdx:4-8`).  The full-width configs of BASELINE.md
("10M blobs vs full ~600 SPDX templates") therefore need a template pool
wider than anything shipped.  This module materializes one on disk:

- the vendored 47 XMLs, copied verbatim, plus
- N-47 synthetic licenses that are valid license-list-XML documents
  exercising the schema zoo (``<titleText>``, ``<copyrightText>``,
  ``<standardLicenseHeader>``, nested ``<list>``, ``<optional>``, inline
  ``<alt>``), with bodies derived from real templates by deterministic
  word perturbation — realistic token statistics, guaranteed-distinct
  wordsets.

Everything downstream (rendering, corpus compilation, device scoring)
then runs the SAME path real license-list XML would take
(`corpus/spdx.py`), so a bench over this pool measures the honest
full-SPDX-width configuration rather than synthetic bitset rows.
"""

from __future__ import annotations

import os
import random
import re
import shutil
from xml.sax.saxutils import escape


def _word_pool(contents: list[str]) -> list[str]:
    """A stable, sorted pool of replacement words drawn from real
    templates — substitutions stay inside realistic vocabulary."""
    pool = set()
    for content in contents:
        pool.update(re.findall(r"[a-z]{4,}", content.lower()))
    return sorted(pool)


def _perturb(body: str, rng: random.Random, pool: list[str], tag: str) -> str:
    """Replace ~8% of words and plant a unique marker token so every
    synthetic template has a distinct wordset (no exact-set collisions)."""
    words = body.split(" ")
    n_swap = max(1, len(words) // 12)
    for _ in range(n_swap):
        k = rng.randrange(len(words))
        if words[k].isalpha():
            words[k] = rng.choice(pool)
    out = " ".join(words)
    return (
        out
        + f"\n\nThis instrument is the {tag} revision of these terms "
        + f"and the term {tag} controls over any conflicting clause."
    )


def _synth_xml(key: str, name: str, body: str) -> str:
    """Wrap a plain-text body in a schema-exercising license-list XML."""
    blocks = [b.strip() for b in body.split("\n\n") if b.strip()]
    # middle block becomes a <list> with a nested sublist; one block is
    # marked <optional>; the rest are plain <p> paragraphs
    parts: list[str] = []
    for j, block in enumerate(blocks):
        text = escape(block)
        if j == 1 and len(blocks) > 3:
            sentences = [s for s in re.split(r"(?<=[.;:]) ", block) if s]
            items = "".join(
                f"\n        <item><bullet>{k + 1}.</bullet> "
                f"{escape(s)}</item>"
                for k, s in enumerate(sentences[:4])
            )
            rest = escape(" ".join(sentences[4:]))
            nested = (
                f"\n        <item><bullet>a.</bullet> <list>"
                f"<item><bullet>i.</bullet> {rest}</item>"
                f"</list></item>"
                if rest
                else ""
            )
            parts.append(f"      <list>{items}{nested}\n      </list>")
        elif j == 2:
            parts.append(f"      <optional><p>{text}</p></optional>")
        elif j == 3:
            # inline <alt> mid-paragraph, canonical body kept on render
            words = text.split(" ")
            mid = len(words) // 2
            head, alt, tail = (
                " ".join(words[:mid]),
                words[mid] if mid < len(words) else "terms",
                " ".join(words[mid + 1 :]),
            )
            parts.append(
                f'      <p>{head} <alt match="{alt}|conditions" '
                f'name="w{j}">{alt}</alt> {tail}</p>'
            )
        else:
            parts.append(f"      <p>{text}</p>")
    body_xml = "\n".join(parts)
    return f"""<?xml version="1.0" encoding="UTF-8"?>
<SPDXLicenseCollection xmlns="http://www.spdx.org/license">
  <license isOsiApproved="false" licenseId="{key}" name="{escape(name)}">
    <crossRefs>
      <crossRef>https://example.invalid/licenses/{key}</crossRef>
    </crossRefs>
    <standardLicenseHeader>
      <p>Include this header with an <alt match="notice|banner"
      name="hdr">notice</alt> in every <optional>covered</optional>
      source file of {escape(name)}.</p>
    </standardLicenseHeader>
    <text>
      <titleText>
        <p>{escape(name)}</p>
      </titleText>
      <copyrightText>
        <p>Copyright (c) 1999 Example Holder</p>
      </copyrightText>
{body_xml}
    </text>
  </license>
</SPDXLicenseCollection>
"""


def synth_spdx_dir(dest: str, n_templates: int = 608, seed: int = 0) -> str:
    """Write an ``n_templates``-entry license-list-XML directory: the
    vendored 47 verbatim + synthetic schema-valid licenses to width.

    Deterministic in (n_templates, seed); returns ``dest``."""
    from licensee_tpu import vendor_paths
    from licensee_tpu.corpus.spdx import SpdxTemplate

    os.makedirs(dest, exist_ok=True)
    src = vendor_paths.SPDX_DIR
    names = sorted(n for n in os.listdir(src) if n.endswith(".xml"))
    for name in names:
        shutil.copy(os.path.join(src, name), os.path.join(dest, name))
    bases = [SpdxTemplate(os.path.join(src, n)) for n in names]
    pool = _word_pool([b.content for b in bases])
    rng = random.Random(seed)
    for i in range(len(names), n_templates):
        base = bases[i % len(bases)]
        tag = f"synthrev{i:04d}"
        body = _perturb(base.content, rng, pool, tag)
        key = f"Synth-{i:04d}"
        name = f"Synthetic Derived License {i:04d}"
        with open(
            os.path.join(dest, f"{key}.xml"), "w", encoding="utf-8"
        ) as f:
            f.write(_synth_xml(key, name, body))
    return dest
