"""Corpus compiler: templates -> device-resident scoring constants.

The TPU analog of the reference's lazy `License.all` init
(license.rb:20-36 + content_helper memoization): eagerly normalize and
tokenize every template, build the global vocabulary, and emit the T×W
packed bit-matrix plus per-template score constants as arrays.

Per-template constants (see the similarity algebra in
content_helper.rb:128-133 and 337-347):
  bits        uint32[T, W]  — fieldless wordset as a bit-vector over vocab
  n_wf        int32[T]      — |wordset_fieldless|
  n_fieldset  int32[T]      — |fields_normalized_set|
  field_count int32[T]      — len(fields_normalized)  (duplicates counted)
  alt_count   int32[T]      — SPDX <alt> segments (license.rb:273-283)
  length      int32[T]      — normalized content length
  cc_flag     bool[T]       — Creative Commons (for the false-positive mask)
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field

import numpy as np

LANE = 32  # bits per packed word


def pack_ids(ids: np.ndarray, n_lanes: int) -> np.ndarray:
    """Pack a list of vocab ids into a uint32 bit-vector of n_lanes words.

    Vectorized via a boolean scatter + packbits instead of the former
    ``np.bitwise_or.at`` (a slow per-element ufunc loop): packbits with
    ``bitorder='little'`` viewed as little-endian uint32 puts id k at
    bit ``k & 31`` of word ``k >> 5`` — exactly the device layout."""
    if not len(ids):
        return np.zeros(n_lanes, dtype=np.uint32)
    flags = np.zeros(n_lanes * LANE, dtype=bool)
    flags[np.asarray(ids, dtype=np.int64)] = True
    return np.packbits(flags, bitorder="little").view("<u4").astype(
        np.uint32, copy=False
    )


@dataclass(frozen=True)
class CompiledCorpus:
    """Immutable scoring constants for a template pool."""

    keys: tuple[str, ...]
    vocab: dict[str, int]
    bits: np.ndarray         # uint32[T, W]
    n_wf: np.ndarray         # int32[T]
    n_fieldset: np.ndarray   # int32[T]
    field_count: np.ndarray  # int32[T]
    alt_count: np.ndarray    # int32[T]
    length: np.ndarray       # int32[T]
    cc_flag: np.ndarray      # bool[T]
    content_hashes: dict[str, str] = field(default_factory=dict)
    # full (fields included) template wordsets keyed for the Exact matcher's
    # set-equality test (matchers/exact.rb:6-13); first key wins on collision,
    # matching the reference's first-match license order
    exact_sets: dict[frozenset, str] = field(default_factory=dict)

    @property
    def n_templates(self) -> int:
        return len(self.keys)

    @property
    def n_lanes(self) -> int:
        return self.bits.shape[1]

    @property
    def vocab_size(self) -> int:
        return len(self.vocab)

    def file_features(self, normalized_file) -> tuple[np.ndarray, int, int]:
        """Extract (packed bits, |wordset|, length) for a candidate file.

        Out-of-vocabulary words cannot overlap any template, so only the
        in-vocab projection is packed — but the full wordset size still
        counts in the score denominator."""
        wordset = normalized_file.wordset or frozenset()
        # one dict probe per word (map + filter) instead of the former
        # membership-then-index double lookup
        ids = [i for i in map(self.vocab.get, wordset) if i is not None]
        return pack_ids(ids, self.n_lanes), len(wordset), normalized_file.length

    @staticmethod
    def compile(licenses, lane_align: int = 4) -> "CompiledCorpus":
        """Build scoring constants from License-like objects (anything with
        wordset_fieldless / fields_normalized / length / spdx_alt_segments /
        creative_commons_q)."""
        pool = [lic for lic in licenses if lic.wordset is not None]
        vocab: dict[str, int] = {}
        for lic in pool:
            for word in sorted(lic.wordset_fieldless):
                if word not in vocab:
                    vocab[word] = len(vocab)
        # Also cover every template's FULL wordset (field words included),
        # appended after the scoring words so template bit rows are
        # unchanged (field-word columns stay 0 in every row — they can
        # never contribute to an overlap).  This makes the batch Exact
        # check exact by construction: a blob whose in-vocab projection
        # equals a template's full-wordset bits AND whose |wordset| equals
        # the template's has zero out-of-vocab words, hence wordset
        # equality (matchers/exact.rb:6-13) — no hash trust needed.
        for lic in pool:
            for word in sorted(lic.wordset - lic.wordset_fieldless):
                if word not in vocab:
                    vocab[word] = len(vocab)

        n_lanes = -(-len(vocab) // LANE)
        n_lanes = -(-n_lanes // lane_align) * lane_align

        T = len(pool)
        bits = np.zeros((T, n_lanes), dtype=np.uint32)
        n_wf = np.zeros(T, dtype=np.int32)
        n_fieldset = np.zeros(T, dtype=np.int32)
        field_count = np.zeros(T, dtype=np.int32)
        alt_count = np.zeros(T, dtype=np.int32)
        length = np.zeros(T, dtype=np.int32)
        cc_flag = np.zeros(T, dtype=bool)
        hashes: dict[str, str] = {}
        exact_sets: dict[frozenset, str] = {}

        for t, lic in enumerate(pool):
            ids = [vocab[w] for w in lic.wordset_fieldless]
            bits[t] = pack_ids(ids, n_lanes)
            n_wf[t] = len(lic.wordset_fieldless)
            n_fieldset[t] = len(lic.fields_normalized_set)
            field_count[t] = len(lic.fields_normalized)
            alt_count[t] = getattr(lic, "spdx_alt_segments", 0)
            length[t] = lic.length
            cc_flag[t] = getattr(lic, "creative_commons_q", False)
            hashes[lic.content_hash] = lic.key
            exact_sets.setdefault(frozenset(lic.wordset), lic.key)

        return CompiledCorpus(
            keys=tuple(lic.key for lic in pool),
            vocab=vocab,
            bits=bits,
            n_wf=n_wf,
            n_fieldset=n_fieldset,
            field_count=field_count,
            alt_count=alt_count,
            length=length,
            cc_flag=cc_flag,
            content_hashes=hashes,
            exact_sets=exact_sets,
        )


@functools.cache
def default_corpus() -> CompiledCorpus:
    """The compiled vendored corpus (Dice's default candidate pool:
    hidden included, pseudo excluded — matcher.rb:29-31)."""
    from licensee_tpu.corpus.license import License

    return CompiledCorpus.compile(License.all(hidden=True, pseudo=False))
