"""Corpus refresh tooling: re-vendor data and regenerate goldens.

The reference keeps its vendored corpus refreshable with four scripts —
`script/vendor-licenses` (choosealicense _data + _licenses),
`script/vendor-spdx` (the license-list-XML sources for the vendored
spdx-ids), `script/hash-licenses` (spec/fixtures/license-hashes.json),
and `script/dump-fixture-licenses` (spec/fixtures/fixtures.yml)
(/root/reference/script/vendor-licenses:1-11, vendor-spdx:1-20,
hash-licenses:1-14, dump-fixture-licenses:1-25).  This module is their
TPU-repo twin, with one deliberate difference: the reference curls
GitHub tarballs; this environment has zero egress, so the vendor
functions take a local CHECKOUT path instead — the day choosealicense
adds a license, clone the two repos anywhere, point the scripts at
them, and re-run the golden generators.

The drift test (tests/test_scripts.py) asserts regenerated goldens ==
shipped goldens and that re-vendoring from a checkout shaped like the
current vendor tree is byte-identical — so the shipped corpus provably
IS what these tools produce.
"""

from __future__ import annotations

import json
import os
import re
import shutil

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(__file__)))
VENDOR_LICENSES_DIR = os.path.join(REPO_ROOT, "vendor", "choosealicense.com")
VENDOR_SPDX_DIR = os.path.join(REPO_ROOT, "vendor", "license-list-XML")
FIXTURES_DIR = os.path.join(REPO_ROOT, "tests", "fixtures")


def vendor_licenses(checkout: str, vendor_dir: str | None = None) -> list[str]:
    """Re-vendor `_data/*` and `_licenses/*` from a local
    choosealicense.com checkout (script/vendor-licenses:1-8: rm -Rf then
    extract exactly those two trees).  Returns the copied paths."""
    vendor_dir = vendor_dir or VENDOR_LICENSES_DIR
    for sub in ("_data", "_licenses"):
        src = os.path.join(checkout, sub)
        if not os.path.isdir(src):
            raise FileNotFoundError(
                f"not a choosealicense.com checkout: {checkout!r} has no "
                f"{sub}/"
            )
    if os.path.isdir(vendor_dir):
        shutil.rmtree(vendor_dir)
    copied = []
    for sub in ("_data", "_licenses"):
        dst = os.path.join(vendor_dir, sub)
        shutil.copytree(os.path.join(checkout, sub), dst)
        copied.extend(
            os.path.join(dst, name) for name in sorted(os.listdir(dst))
        )
    return copied


def vendored_spdx_ids(vendor_dir: str | None = None) -> list[str]:
    """The spdx-id of every vendored license text — the include list the
    reference greps out of the frontmatter (script/vendor-spdx:4)."""
    licenses_dir = os.path.join(
        vendor_dir or VENDOR_LICENSES_DIR, "_licenses"
    )
    ids = []
    for name in sorted(os.listdir(licenses_dir)):
        if not name.endswith(".txt"):
            continue
        with open(
            os.path.join(licenses_dir, name), encoding="utf-8"
        ) as f:
            m = re.search(r"^spdx-id: (.+)$", f.read(), re.M)
        if m:
            ids.append(m.group(1).strip())
    return ids


def vendor_spdx(
    checkout: str,
    vendor_dir: str | None = None,
    licenses_vendor_dir: str | None = None,
) -> list[str]:
    """Re-vendor `src/<spdx-id>.xml` for every vendored license from a
    local spdx/license-list-XML checkout (script/vendor-spdx:1-9).
    Returns the copied paths; raises if any vendored id has no XML in
    the checkout (a partial vendor tree would silently shrink the
    corpus).

    ``licenses_vendor_dir``: where the include-list of spdx-ids comes
    from — pass the SAME alternate dir a prior vendor_licenses(...,
    vendor_dir=...) wrote, or the default repo tree is consulted (an
    alternate-dir refresh that greps the stale default tree would
    silently skip newly added licenses)."""
    vendor_dir = vendor_dir or VENDOR_SPDX_DIR
    src_dir = os.path.join(checkout, "src")
    if not os.path.isdir(src_dir):
        raise FileNotFoundError(
            f"not a license-list-XML checkout: {checkout!r} has no src/"
        )
    ids = vendored_spdx_ids(licenses_vendor_dir)
    missing = [
        i for i in ids
        if not os.path.isfile(os.path.join(src_dir, f"{i}.xml"))
    ]
    if missing:
        raise FileNotFoundError(
            f"checkout {checkout!r} lacks XML for vendored ids: "
            + ", ".join(missing)
        )
    if os.path.isdir(vendor_dir):
        shutil.rmtree(vendor_dir)
    dst_dir = os.path.join(vendor_dir, "src")
    os.makedirs(dst_dir)
    copied = []
    for i in ids:
        dst = os.path.join(dst_dir, f"{i}.xml")
        shutil.copy(os.path.join(src_dir, f"{i}.xml"), dst)
        copied.append(dst)
    return copied


def license_hashes_json() -> str:
    """The license-hashes.json golden, regenerated (script/hash-licenses:
    1-14: every non-pseudo license's normalized content hash, pretty
    JSON)."""
    from licensee_tpu.corpus.license import License

    licenses = License.all(hidden=True, pseudo=False)
    hashes = {lic.key: lic.content_hash for lic in licenses}
    # no trailing newline: byte parity with the Ruby-written golden
    return json.dumps(hashes, indent=2)


# data-only fixture dirs (corpus inputs, not project trees) — excluded
# from fixtures.yml and from tests/test_fixtures.py alike
# ("analysis" is the static-analyzer rule corpus, tests/test_analysis.py)
NON_PROJECT_FIXTURES = frozenset({"spdx-adversarial", "analysis"})


def fixture_names() -> list[str]:
    """Every project fixture directory, sorted — the reference's
    `fixtures` helper (spec_helper.rb), minus the data-only dirs this
    repo adds."""
    return sorted(
        name
        for name in os.listdir(FIXTURES_DIR)
        if os.path.isdir(os.path.join(FIXTURES_DIR, name))
        and name not in NON_PROJECT_FIXTURES
    )


def fixtures_yml() -> str:
    """The fixtures.yml golden, regenerated: detect every fixture dir
    with packages+readme on and record key/matcher/hash
    (script/dump-fixture-licenses:1-25).  Emitted in the Ruby YAML.dump
    shape (bare `field:` for nil) with one deliberate simplification:
    always-plain scalars (Psych single-quotes the odd hash its scanner
    finds number-ish; the parsed value is identical, and the shipped
    golden is regenerated BY this function, so bytes match)."""
    import licensee_tpu

    lines = [
        "# Map of fixtures to expectation as an added integration test",
        "---",
    ]
    for name in fixture_names():
        project = licensee_tpu.project(
            os.path.join(FIXTURES_DIR, name),
            detect_packages=True,
            detect_readme=True,
        )
        key = project.license.key if project.license else None
        matcher = None
        hash_ = None
        if project.license_file:
            hash_ = project.license_file.content_hash
            m = project.license_file.matcher
            if m is not None and m.name:
                matcher = str(m.name)
        lines.append(f"{name}:")
        for field, value in (
            ("key", key), ("matcher", matcher), ("hash", hash_),
        ):
            lines.append(f"  {field}: {value}" if value else f"  {field}:")
    return "\n".join(lines) + "\n"
