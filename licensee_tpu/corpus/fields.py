"""Substitutable license fields ([year], [fullname], ...).

Parity target: `lib/licensee/license_field.rb`.  Fields are loaded from the
vendored `fields.yml`; ``FIELD_REGEX`` is used both to enumerate fields in a
license body and to excise field tokens from similarity scoring
(`lib/licensee/content_helper.rb:328-331`).
"""

from __future__ import annotations

import functools

import yaml

from licensee_tpu import vendor_paths
from licensee_tpu.rubytext import rb, union_patterns


class LicenseField:
    def __init__(self, name: str, description: str | None = None):
        self.name = name
        self.description = description

    @property
    def key(self) -> str:
        return self.name

    @property
    def label(self) -> str:
        # reference: license_field.rb:56-58
        return self.key.replace("fullname", "full name", 1).capitalize()

    def __str__(self) -> str:
        return self.label

    def __repr__(self) -> str:
        return f"<LicenseField name={self.name}>"

    def __eq__(self, other) -> bool:
        return isinstance(other, LicenseField) and other.name == self.name

    def __hash__(self) -> int:
        return hash(("LicenseField", self.name))

    @property
    def raw_text(self) -> str:
        return f"[{self.key}]"

    @staticmethod
    @functools.cache
    def all() -> tuple["LicenseField", ...]:
        with open(vendor_paths.FIELDS_YML, encoding="utf-8") as f:
            raw = yaml.safe_load(f)
        return tuple(
            LicenseField(entry["name"], entry.get("description")) for entry in raw
        )

    @staticmethod
    @functools.cache
    def keys() -> tuple[str, ...]:
        return tuple(f.key for f in LicenseField.all())

    @staticmethod
    def find(key: str) -> "LicenseField | None":
        for f in LicenseField.all():
            if f.key == key:
                return f
        return None

    @staticmethod
    def from_array(keys) -> list["LicenseField"]:
        return [LicenseField.find(k) for k in keys]

    @staticmethod
    def from_content(content: str | None) -> list["LicenseField"]:
        """All fields referenced in a license body, with duplicates, in order
        of appearance (reference: license_field.rb:44-48)."""
        if not content:
            return []
        return LicenseField.from_array(
            m.group(1) for m in field_regex().finditer(content)
        )


@functools.cache
def field_regex():
    """``/\\[(year|fullname|...)\\]/`` (reference: license_field.rb:53)."""
    return rb(r"\[(" + union_patterns(list(LicenseField.keys())) + r")\]")
