from licensee_tpu.corpus.fields import LicenseField
from licensee_tpu.corpus.meta import LicenseMeta
from licensee_tpu.corpus.rules import LicenseRules, Rule
from licensee_tpu.corpus.license import License

__all__ = ["License", "LicenseField", "LicenseMeta", "LicenseRules", "Rule"]
