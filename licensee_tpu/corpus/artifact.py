"""Versioned corpus artifacts: compile once, fingerprint, load anywhere.

Every serving tier so far froze its corpus at import: one compile of the
vendored pool per process, no way to name "the corpus this worker is
serving" and no way to hand a worker a new one without killing it.  This
module makes a compiled corpus a first-class, self-describing ARTIFACT:

* :func:`corpus_fingerprint` — the canonical content fingerprint of a
  :class:`~licensee_tpu.corpus.compiler.CompiledCorpus`: sha256 over a
  length-prefixed serialization of every field that shapes a verdict
  (template keys, vocab order, the packed bit matrix, the score
  constants, the per-template content hashes, the Exact wordsets).  Two
  corpora with the same fingerprint classify identically; one changed
  byte anywhere changes it.  This is the versioning primitive the
  result-cache fencing, the resume preflight, and the blue/green reload
  path all key on (it extends the resume sidecar's ``content_sha1``,
  which hashed template content only).

* :func:`write_artifact` / :func:`load_artifact` — a single-file bundle
  (numpy ``.npz``: a JSON manifest + the seven constant arrays) that
  loads WITHOUT recompiling: no template parse, no vocab build, no
  normalization pass.  ``load_artifact`` recomputes the fingerprint from
  the loaded payload and refuses a bundle whose manifest disagrees — a
  truncated copy or a flipped bit fails closed, it can never serve.

* :func:`resolve_corpus` — the one source resolver every consumer
  shares (the CLI ``--corpus`` flag, the serve ``reload`` verb, the
  fleet rolling reload): ``"vendored"``, ``"spdx"``, an SPDX
  license-list-XML directory, or an artifact file path.
"""

from __future__ import annotations

import hashlib
import io
import json
import os

import numpy as np

from licensee_tpu.corpus.compiler import CompiledCorpus

FORMAT = "licensee-tpu-corpus"
FORMAT_VERSION = 1

# the arrays serialized into (and hashed out of) every artifact, in
# canonical order, with their required dtypes — one table so the
# writer, the loader, and the fingerprint can never disagree
ARRAY_FIELDS = (
    ("bits", np.uint32),
    ("n_wf", np.int32),
    ("n_fieldset", np.int32),
    ("field_count", np.int32),
    ("alt_count", np.int32),
    ("length", np.int32),
    ("cc_flag", np.bool_),
)


class ArtifactError(ValueError):
    """The artifact cannot be trusted: unreadable, wrong format, or its
    payload no longer hashes to the manifest fingerprint."""


def _canonical_sections(corpus: CompiledCorpus):
    """Yield (name, bytes) sections of the corpus in canonical order.

    Everything that shapes a verdict is here; anything derivable (lane
    count, template count) is covered by the array bytes themselves."""
    yield "keys", "\n".join(corpus.keys).encode("utf-8")
    vocab_words = [None] * len(corpus.vocab)
    for word, i in corpus.vocab.items():
        vocab_words[i] = word
    yield "vocab", "\n".join(vocab_words).encode("utf-8")
    for name, dtype in ARRAY_FIELDS:
        arr = np.ascontiguousarray(getattr(corpus, name), dtype=dtype)
        yield name, arr.tobytes()
    yield "content_hashes", "\n".join(
        sorted(f"{key}:{h}" for h, key in corpus.content_hashes.items())
    ).encode("utf-8")
    yield "exact_sets", "\n".join(
        sorted(
            " ".join(sorted(words)) + "\t" + key
            for words, key in corpus.exact_sets.items()
        )
    ).encode("utf-8")


def corpus_fingerprint(corpus: CompiledCorpus) -> str:
    """The 64-hex sha256 content fingerprint of a compiled corpus.

    Memoized on the corpus object (the payload is a few MB; reload and
    cache fencing read the fingerprint on hot paths)."""
    cached = getattr(corpus, "_fingerprint", None)
    if cached is not None:
        return cached
    h = hashlib.sha256()
    h.update(f"{FORMAT}/v{FORMAT_VERSION}".encode())
    for name, payload in _canonical_sections(corpus):
        h.update(name.encode("utf-8"))
        h.update(len(payload).to_bytes(8, "little"))
        h.update(payload)
    fp = h.hexdigest()
    # CompiledCorpus is a frozen dataclass; the memo is not a field, so
    # it never enters equality/repr — object.__setattr__ is the blessed
    # way to attach a cache to a frozen instance
    object.__setattr__(corpus, "_fingerprint", fp)
    return fp


def short_fingerprint(fp: str | None) -> str | None:
    """The 12-hex display form (response rows, log lines, gauges)."""
    return fp[:12] if fp else fp


def build_manifest(corpus: CompiledCorpus, source: str = "") -> dict:
    """The self-description written into (and returned from) a bundle."""
    return {
        "format": FORMAT,
        "format_version": FORMAT_VERSION,
        "fingerprint": corpus_fingerprint(corpus),
        "source": source,
        "templates": corpus.n_templates,
        "vocab": corpus.vocab_size,
        "lanes": corpus.n_lanes,
    }


def write_artifact(
    path: str, corpus: CompiledCorpus, source: str = ""
) -> dict:
    """Serialize a compiled corpus to ``path`` (atomic replace).

    Returns the manifest.  The bundle is a plain ``np.savez`` zip: the
    JSON manifest+metadata as a uint8 array, plus the seven constant
    arrays — loadable with ``allow_pickle=False`` (no code execution
    surface in a file an operator ships between hosts)."""
    vocab_words = [None] * len(corpus.vocab)
    for word, i in corpus.vocab.items():
        vocab_words[i] = word
    manifest = build_manifest(corpus, source)
    meta = {
        "manifest": manifest,
        "keys": list(corpus.keys),
        "vocab": vocab_words,
        "content_hashes": corpus.content_hashes,
        "exact_sets": [
            [sorted(words), key]
            for words, key in sorted(
                corpus.exact_sets.items(),
                key=lambda kv: (kv[1], sorted(kv[0])),
            )
        ],
    }
    meta_bytes = json.dumps(meta, ensure_ascii=False).encode("utf-8")
    arrays = {
        name: np.ascontiguousarray(getattr(corpus, name), dtype=dtype)
        for name, dtype in ARRAY_FIELDS
    }
    buf = io.BytesIO()
    np.savez_compressed(
        buf, meta=np.frombuffer(meta_bytes, dtype=np.uint8), **arrays
    )
    tmp = f"{path}.tmp"
    with open(tmp, "wb") as f:
        f.write(buf.getvalue())
    os.replace(tmp, path)
    return manifest


def load_artifact(path: str) -> tuple[CompiledCorpus, dict]:
    """Load a bundle back into a CompiledCorpus, verifying integrity.

    Raises :class:`ArtifactError` on any defect: unreadable file, wrong
    format/version, missing arrays, wrong dtypes/shapes, or a payload
    whose recomputed fingerprint differs from the manifest's (bit rot,
    truncation, tampering).  A loaded corpus is therefore EXACTLY the
    corpus that was written, proven, not assumed."""
    import zipfile
    import zlib

    try:
        with np.load(path, allow_pickle=False) as npz:
            data = {name: npz[name] for name in npz.files}
    except (
        OSError, ValueError, KeyError, EOFError,
        zipfile.BadZipFile, zlib.error,
    ) as exc:
        # every way a torn/garbage/truncated bundle surfaces from the
        # zip + npy readers — all fail closed as "cannot be trusted"
        raise ArtifactError(f"cannot read artifact {path!r}: {exc}") from exc
    if "meta" not in data:
        raise ArtifactError(f"{path!r}: not a corpus artifact (no manifest)")
    try:
        meta = json.loads(bytes(data["meta"]).decode("utf-8"))
    except (ValueError, UnicodeDecodeError) as exc:
        raise ArtifactError(f"{path!r}: bad manifest: {exc}") from exc
    manifest = meta.get("manifest") or {}
    if manifest.get("format") != FORMAT:
        raise ArtifactError(
            f"{path!r}: format {manifest.get('format')!r} is not {FORMAT!r}"
        )
    if manifest.get("format_version") != FORMAT_VERSION:
        raise ArtifactError(
            f"{path!r}: format_version {manifest.get('format_version')!r} "
            f"unsupported (this build reads v{FORMAT_VERSION})"
        )
    missing = [name for name, _ in ARRAY_FIELDS if name not in data]
    if missing:
        raise ArtifactError(f"{path!r}: missing arrays: {missing}")
    keys = meta.get("keys")
    vocab_words = meta.get("vocab")
    if not isinstance(keys, list) or not isinstance(vocab_words, list):
        raise ArtifactError(f"{path!r}: bad keys/vocab metadata")
    arrays = {}
    for name, dtype in ARRAY_FIELDS:
        arr = np.ascontiguousarray(data[name], dtype=dtype)
        if name == "bits":
            if arr.ndim != 2 or arr.shape[0] != len(keys):
                raise ArtifactError(
                    f"{path!r}: bits shape {arr.shape} does not match "
                    f"{len(keys)} templates"
                )
        elif arr.shape != (len(keys),):
            raise ArtifactError(
                f"{path!r}: {name} shape {arr.shape} does not match "
                f"{len(keys)} templates"
            )
        arrays[name] = arr
    corpus = CompiledCorpus(
        keys=tuple(keys),
        vocab={w: i for i, w in enumerate(vocab_words)},
        content_hashes=dict(meta.get("content_hashes") or {}),
        exact_sets={
            frozenset(words): key
            for words, key in meta.get("exact_sets") or []
        },
        **arrays,
    )
    fp = corpus_fingerprint(corpus)
    if fp != manifest.get("fingerprint"):
        raise ArtifactError(
            f"{path!r}: payload fingerprint {short_fingerprint(fp)} does "
            f"not match manifest "
            f"{short_fingerprint(manifest.get('fingerprint'))} — the "
            "artifact is corrupt; rebuild it with `licensee-tpu "
            "corpus-build`"
        )
    return corpus, manifest


def check_corpus_source(source: str) -> str | None:
    """Cheap fail-closed check that SOURCE names a loadable corpus,
    WITHOUT compiling or loading it (submit-time validation for job
    specs and tenant bindings — milliseconds, not the seconds
    :func:`resolve_corpus` spends compiling).

    Returns the artifact's fingerprint when the source is a bundle
    file (its manifest carries one), else None.  Raises
    :class:`ArtifactError` for anything resolve_corpus would later
    refuse: an unknown source string, a file that is not a corpus
    artifact, or a bundle with the wrong format/version."""
    if not isinstance(source, str) or not source:
        raise ArtifactError("corpus source must be a non-empty string")
    if source in ("vendored", "spdx"):
        return None
    if os.path.isdir(source):
        return None  # an SPDX src/ checkout compiles at load time
    if not os.path.isfile(source):
        raise ArtifactError(
            f"cannot load corpus {source!r}: not 'vendored', 'spdx', an "
            "SPDX src/ directory, or a corpus artifact file"
        )
    import zipfile
    import zlib

    # peek ONLY the manifest array — the bit matrix stays on disk
    try:
        with np.load(source, allow_pickle=False) as npz:
            if "meta" not in npz.files:
                raise ArtifactError(
                    f"{source!r}: not a corpus artifact (no manifest)"
                )
            meta_bytes = bytes(npz["meta"])
    except (
        OSError, ValueError, KeyError, EOFError,
        zipfile.BadZipFile, zlib.error,
    ) as exc:
        raise ArtifactError(
            f"cannot read artifact {source!r}: {exc}"
        ) from exc
    try:
        meta = json.loads(meta_bytes.decode("utf-8"))
    except (ValueError, UnicodeDecodeError) as exc:
        raise ArtifactError(f"{source!r}: bad manifest: {exc}") from exc
    manifest = meta.get("manifest") or {}
    if manifest.get("format") != FORMAT:
        raise ArtifactError(
            f"{source!r}: format {manifest.get('format')!r} is not "
            f"{FORMAT!r}"
        )
    if manifest.get("format_version") != FORMAT_VERSION:
        raise ArtifactError(
            f"{source!r}: format_version "
            f"{manifest.get('format_version')!r} unsupported (this "
            f"build reads v{FORMAT_VERSION})"
        )
    fp = manifest.get("fingerprint")
    if not isinstance(fp, str) or not fp:
        raise ArtifactError(f"{source!r}: manifest has no fingerprint")
    return fp


def resolve_corpus(source: str) -> tuple[CompiledCorpus, str, dict | None]:
    """Resolve a corpus SOURCE string to (corpus, fingerprint, manifest).

    The one resolver behind ``--corpus`` and the reload verbs:

    * ``"vendored"`` — the compiled choosealicense pool (process-cached)
    * ``"spdx"`` — the vendored SPDX license-list-XML mirror
    * a directory — an SPDX license-list-XML ``src/`` checkout
    * a file — a corpus artifact written by :func:`write_artifact`

    ``manifest`` is None for compiled-on-the-spot sources.  Raises
    :class:`ArtifactError` (bad artifact / unknown source) or OSError
    (unreadable directory)."""
    if source == "vendored":
        from licensee_tpu.corpus.compiler import default_corpus

        corpus = default_corpus()
        return corpus, corpus_fingerprint(corpus), None
    if source == "spdx" or os.path.isdir(source):
        from licensee_tpu.corpus.spdx import spdx_corpus

        corpus = spdx_corpus(None if source == "spdx" else source)
        if not corpus.n_templates:
            raise ArtifactError(
                f"no license templates found in {source!r}"
            )
        return corpus, corpus_fingerprint(corpus), None
    if os.path.isfile(source):
        corpus, manifest = load_artifact(source)
        return corpus, manifest["fingerprint"], manifest
    raise ArtifactError(
        f"cannot load corpus {source!r}: not 'vendored', 'spdx', an SPDX "
        "src/ directory, or a corpus artifact file"
    )
