"""License metadata parsed from template YAML front matter.

Parity target: `lib/licensee/license_meta.rb`.  Defaults match
choosealicense.com's collection defaults (featured: false, hidden: true).
"""

from __future__ import annotations

import yaml

MEMBERS = (
    "title",
    "spdx_id",
    "source",
    "description",
    "how",
    "conditions",
    "permissions",
    "limitations",
    "using",
    "featured",
    "hidden",
    "nickname",
    "note",
)

DEFAULTS = {"featured": False, "hidden": True}

PREDICATE_FIELDS = ("featured", "hidden")


class LicenseMeta:
    members = MEMBERS

    def __init__(self, values: dict):
        for member in MEMBERS:
            setattr(self, member, values.get(member))

    @classmethod
    def from_yaml(cls, raw_yaml: str | None) -> "LicenseMeta":
        if raw_yaml is None or str(raw_yaml) == "":
            return cls.from_hash({})
        # Front matter arrives with its `---` document markers; take the
        # first YAML document like Ruby's YAML.safe_load does.
        for doc in yaml.safe_load_all(raw_yaml):
            if doc is not None:
                return cls.from_hash(doc)
        return cls.from_hash({})

    @classmethod
    def from_hash(cls, data: dict) -> "LicenseMeta":
        merged = dict(DEFAULTS)
        merged.update(data or {})
        merged["spdx_id"] = merged.pop("spdx-id", None)
        return cls(merged)

    @property
    def source(self):
        """The canonical source URL is derived from the SPDX id (reference:
        license_meta.rb:61-63 overrides the YAML `source` field)."""
        if self.spdx_id:
            return f"https://spdx.org/licenses/{self.spdx_id}.html"
        return None

    @source.setter
    def source(self, value):
        self._raw_source = value

    @property
    def featured_q(self) -> bool:
        return bool(self.featured)

    @property
    def hidden_q(self) -> bool:
        return bool(self.hidden)

    def __getitem__(self, key):
        if key == "spdx-id":
            key = "spdx_id"
        return getattr(self, key, None)

    def get(self, key, default=None):
        value = self[key]
        return default if value is None else value

    def to_h(self) -> dict:
        # reference: license_meta.rb HASH_METHODS = members - excluded
        excluded = {"conditions", "permissions", "limitations", "spdx_id"}
        return {m: getattr(self, m) for m in MEMBERS if m not in excluded}
