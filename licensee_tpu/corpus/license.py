"""The License model: vendored templates + pseudo-licenses.

Parity target: `lib/licensee/license.rb`.  Loads the 47 vendored
choosealicense templates plus the `other` / `no-license` pseudo-licenses
(49 keys total), synthesizes per-license title/source regexes, and exposes
the corpus-wide title regex used by the normalization engine's title strip.
"""

from __future__ import annotations

import functools
import glob
import os
import re

from licensee_tpu import vendor_paths
from licensee_tpu.corpus.fields import LicenseField
from licensee_tpu.corpus.meta import LicenseMeta
from licensee_tpu.corpus.rules import LicenseRules
from licensee_tpu.normalize.pipeline import NormalizedContent
from licensee_tpu.rubytext import rb, regexp_escape

DOMAIN = "http://choosealicense.com"


class InvalidLicense(ValueError):
    pass


# license.rb:92: placeholders with no content
PSEUDO_LICENSES = ("other", "no-license")

# license.rb:95-99
DEFAULT_OPTIONS = {"hidden": False, "featured": None, "pseudo": True}

SOURCE_PREFIX = r"https?://(?:www\.)?"
SOURCE_SUFFIX = r"(?:\.html?|\.txt|/)(?:\?[^\s]*)?"

_FRONT_MATTER = re.compile(r"\A(---\n.*\n---\n+)?(.*)", re.S)


class License(NormalizedContent):
    def __init__(self, key: str):
        self.key = key.lower()

    # -- class-level corpus access (license.rb:20-78) --

    @staticmethod
    def license_dir() -> str:
        return vendor_paths.LICENSE_DIR

    @staticmethod
    def spdx_dir() -> str:
        return vendor_paths.SPDX_DIR

    @staticmethod
    @functools.cache
    def license_files() -> tuple[str, ...]:
        return tuple(sorted(glob.glob(os.path.join(License.license_dir(), "*.txt"))))

    @staticmethod
    @functools.cache
    def keys() -> tuple[str, ...]:
        return tuple(
            os.path.basename(f)[: -len(".txt")].lower() for f in License.license_files()
        ) + PSEUDO_LICENSES

    @staticmethod
    @functools.cache
    def _licenses() -> tuple["License", ...]:
        return tuple(License(key) for key in License.keys())

    @staticmethod
    def all(hidden: bool = False, featured: bool | None = None, pseudo: bool | None = None, psuedo: bool | None = None) -> list["License"]:
        """All licenses, filtered (license.rb:20-36).  ``psuedo`` is the
        reference's historical misspelling, honored for parity."""
        if pseudo is None:
            pseudo = psuedo if psuedo is not None else DEFAULT_OPTIONS["pseudo"]
        out = [lic for lic in License._licenses() if hidden or not lic.hidden_q]
        if not pseudo:
            out = [lic for lic in out if not lic.pseudo_license]
        out.sort(key=lambda lic: lic.key)
        if featured is not None:
            out = [lic for lic in out if lic.featured_q == featured]
        return out

    @staticmethod
    def find(key: str, hidden: bool = True, **options) -> "License | None":
        options["hidden"] = hidden
        for lic in License.all(**options):
            if lic.key == key.lower():
                return lic
        return None

    find_by_key = find

    @staticmethod
    def find_by_title(title: str) -> "License | None":
        for lic in License.all(hidden=True, pseudo=False):
            pattern = rb(
                r"\A(the )?(?:" + lic.title_regex_pattern + r")( license)?\Z", i=True
            )
            if pattern.match(title):
                return lic
        return None

    # -- metadata --

    @property
    def path(self) -> str:
        return os.path.join(License.license_dir(), f"{self.key}.txt")

    @property
    def meta(self) -> LicenseMeta:
        cached = self.__dict__.get("_meta")
        if cached is None:
            cached = LicenseMeta.from_yaml(self._yaml())
            self.__dict__["_meta"] = cached
        return cached

    @property
    def spdx_id(self) -> str | None:
        if self.meta.spdx_id:
            return self.meta.spdx_id
        if self.key == "other":
            return "NOASSERTION"
        if self.key == "no-license":
            return "NONE"
        return None

    @property
    def title(self):
        return self.meta.title

    @property
    def nickname(self):
        return self.meta.nickname

    @property
    def description(self):
        return self.meta.description

    @property
    def conditions(self):
        return self.meta.conditions

    @property
    def permissions(self):
        return self.meta.permissions

    @property
    def limitations(self):
        return self.meta.limitations

    @property
    def featured_q(self) -> bool:
        return self.meta.featured_q

    @property
    def hidden_q(self) -> bool:
        return self.meta.hidden_q

    @property
    def name(self) -> str:
        # license.rb:134-138
        if self.pseudo_license:
            return self.key.replace("-", " ").capitalize()
        return self.title or self.spdx_id

    @property
    def name_without_version(self) -> str:
        return re.match(r"(.+?)(( v?\d\.\d)|$)", self.name).group(1)

    # -- regex synthesis (license.rb:144-194) --

    @property
    def title_regex_pattern(self) -> str:
        """Pattern string matching this license's title and key variants.

        Reproduces license.rb:144-175: a union of (1) the raw lowercase name,
        (2) the escaped name with optional 'the'/'license'/version spellings,
        (3) the key with flexible separator, and (4) the nickname (the only
        case-sensitive member, per Regexp.new without /i)."""
        cached = self.__dict__.get("_title_regex_pattern")
        if cached is not None:
            return cached

        string = self.name.lower().replace("*", "u", 1)
        simple = string

        string = re.sub(r"\Athe ", "", string, count=1, flags=re.I)
        string = re.sub(r",? version ", " ", string, count=1)
        string = re.sub(r"v(\d+\.\d+)", r"\1", string, count=1)
        string = regexp_escape(string)
        string = re.sub(
            r"\\ licen[sc]e",
            lambda _m: r"(?:\ licen[sc]e)?",
            string,
            count=1,
            flags=re.I,
        )
        version_match = re.search(r"\d+\\.(\d+)", string)
        if version_match:
            minor_is_zero = version_match.group(1) == "0"

            def _vsub(m):
                prefix = r",?\s+(?:version\ |v(?:\. )?)?"
                if minor_is_zero:
                    return prefix + m.group(1) + "(" + m.group(2) + ")?"
                return prefix + m.group(1) + m.group(2)

            string = re.sub(r"\\ (\d+)(\\\.\d+)", _vsub, string, count=1)
        string = re.sub(r"\bgnu\\ ", "(?:GNU )?", string, count=1)
        title = string

        key = self.key.replace("-", "[- ]", 1)
        key = key.replace(".", r"\.", 1)
        key += r"(?:\ licen[sc]e)?"

        parts = [f"(?i:{simple})", f"(?i:{title})", f"(?i:{key})"]
        if self.meta.nickname:
            nick = re.sub(r"\bGNU ", "(?:GNU )?", self.meta.nickname, count=1, flags=re.I)
            parts.append(f"(?:{nick})")
        cached = "|".join(parts)
        self.__dict__["_title_regex_pattern"] = cached
        return cached

    @property
    def title_regex(self) -> re.Pattern:
        return rb(self.title_regex_pattern)

    @property
    def source_regex_pattern(self) -> str | None:
        """Pattern matching the license source URL with http(s)/www/suffix
        variations (license.rb:185-194)."""
        if not self.meta.source:
            return None
        source = re.sub(r"\A" + SOURCE_PREFIX, "", self.meta.source, count=1, flags=re.I)
        source = re.sub(SOURCE_SUFFIX + r"\Z", "", source, count=1, flags=re.I)
        return f"(?i:{SOURCE_PREFIX}{regexp_escape(source)}(?:{SOURCE_SUFFIX})?)"

    @property
    def source_regex(self) -> re.Pattern | None:
        pattern = self.source_regex_pattern
        return rb(pattern) if pattern else None

    @property
    def reference_regex(self) -> re.Pattern:
        """The compiled title|source union the Reference matcher scans a
        README with (reference.rb:9-13).  Compiled once per License (the
        pool is process-global and memoized), not per matcher call —
        recompiling ~47 large unions for every README is fatal at
        batch-readme-scan scale."""
        cached = self.__dict__.get("_reference_regex")
        if cached is None:
            parts = [self.title_regex_pattern]
            source = self.source_regex_pattern
            if source:
                parts.append(source)
            cached = rb(r"\b(?:" + "|".join(parts) + r")\b")
            self.__dict__["_reference_regex"] = cached
        return cached

    # -- predicates (license.rb:196-231) --

    @property
    def other_q(self) -> bool:
        return self.key == "other"

    @property
    def gpl_q(self) -> bool:
        return self.key in ("gpl-2.0", "gpl-3.0")

    @property
    def lgpl_q(self) -> bool:
        return self.key in ("lgpl-2.1", "lgpl-3.0")

    @property
    def creative_commons_q(self) -> bool:
        return self.key.startswith("cc-")

    cc_q = creative_commons_q

    @property
    def pseudo_license(self) -> bool:
        return self.key in PSEUDO_LICENSES

    # -- content (license.rb:215-283) --

    @property
    def content(self) -> str | None:
        parts = self._parts()
        return parts[1] if parts and parts[1] else None

    @property
    def url(self) -> str:
        return f"{DOMAIN}/licenses/{self.key}/"

    @property
    def rules(self) -> LicenseRules:
        cached = self.__dict__.get("_rules")
        if cached is None:
            cached = LicenseRules.from_meta(self.meta)
            self.__dict__["_rules"] = cached
        return cached

    @property
    def fields(self) -> list[LicenseField]:
        return LicenseField.from_content(self.content)

    @property
    def content_for_mustache(self) -> str:
        from licensee_tpu.corpus.fields import field_regex

        return field_regex().sub(lambda m: "{{{" + m.group(1) + "}}}", self.content)

    @property
    def spdx_alt_segments(self) -> int:
        """Count of <alt> substitution segments in the vendored SPDX XML for
        this license, after removing copyright/title/optional blocks
        (license.rb:273-283).  Feeds the length-delta adjustment."""
        cached = self.__dict__.get("_spdx_alt_segments")
        if cached is None:
            path = os.path.join(License.spdx_dir(), f"{self.spdx_id}.xml")
            with open(path, encoding="utf-8") as f:
                raw_xml = f.read()
            text = re.search(r"<text>(.*)</text>", raw_xml, re.S).group(1)
            text = re.sub(r"<copyrightText>.*?</copyrightText>", "", text, flags=re.S)
            text = re.sub(r"<titleText>.*?</titleText>", "", text, flags=re.S)
            text = re.sub(r"<optional.*?>.*?</optional>", "", text, flags=re.S)
            cached = len(re.findall(r"<alt .*?>", text, re.S))
            self.__dict__["_spdx_alt_segments"] = cached
        return cached

    def _raw_content(self) -> str | None:
        if self.pseudo_license:
            return None
        cached = self.__dict__.get("_raw")
        if cached is None:
            if not os.path.exists(self.path):
                raise InvalidLicense(f"'{self.key}' is not a valid license key")
            with open(self.path, encoding="utf-8") as f:
                cached = f.read()
            self.__dict__["_raw"] = cached
        return cached

    def _parts(self) -> tuple[str | None, str | None] | None:
        raw = self._raw_content()
        if raw is None:
            return None
        m = _FRONT_MATTER.match(raw)
        return (m.group(1), m.group(2))

    def _yaml(self) -> str | None:
        parts = self._parts()
        return parts[0] if parts else None

    # -- dunder / serialization --

    def __eq__(self, other) -> bool:
        return isinstance(other, License) and self.key == other.key

    def __hash__(self) -> int:
        return hash(("License", self.key))

    def __repr__(self) -> str:
        return f"<License key={self.key}>"

    def __str__(self) -> str:
        return self.content or ""

    def to_h(self) -> dict:
        # license.rb:104-106 HASH_METHODS
        return {
            "key": self.key,
            "spdx_id": self.spdx_id,
            "meta": self.meta.to_h(),
            "url": self.url,
            "rules": self.rules.to_h(),
            "fields": [{"name": f.name, "description": f.description} for f in self.fields],
            "other": self.other_q,
            "gpl": self.gpl_q,
            "lgpl": self.lgpl_q,
            "cc": self.cc_q,
        }


@functools.cache
def global_title_parts() -> tuple[str, ...]:
    """The alternatives of the corpus-wide title union, in union order.

    Shared by :func:`global_title_regex` and the native pipeline's
    literal-prefix gate derivation (licensee_tpu/native/pipeline.py), so
    the gate can never drift from the pattern it fronts."""
    licenses = License.all(hidden=True, pseudo=False)
    parts = [lic.title_regex_pattern for lic in licenses]
    for lic in licenses:
        if lic.title != lic.name_without_version:
            parts.append(f"(?i:{regexp_escape(lic.name_without_version)})")
    return tuple(parts)


@functools.cache
def global_title_regex() -> re.Pattern:
    """The corpus-wide title-strip regex (content_helper.rb:199-215):
    any license title (or unversioned name), optionally parenthesized or
    preceded by 'the', through end of line."""
    union = "|".join(global_title_parts())
    return rb(r"\A\s*\(?(?:the )?(?:" + union + r").*?$", i=True)
