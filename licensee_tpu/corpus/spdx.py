"""Extended corpus: compile SPDX license-list-XML templates directly.

The reference vendors only the 47 choosealicense-mirrored SPDX XMLs
(`script/vendor-spdx:4-8`), so the north-star "full ~600 SPDX set"
configs (BASELINE.md) require ingesting templates beyond what
choosealicense ships.  This module renders any SPDX license-list-XML
`src/` directory — e.g. a checkout of github.com/spdx/license-list-XML —
into License-like template objects and compiles them with the same
corpus compiler / Dice algebra as the vendored pool, so the whole device
path (DiceXLA, the pallas kernel, sharded scoring) works unchanged over
an arbitrary template count.

Rendering follows the SPDX matching guidelines the reference's
normalization already encodes: the canonical `<text>` is flattened with
`<p>` as paragraphs and `<list>/<item>` as bullet lines; `<alt>` falls
back to its canonical body; `<optional>` bodies are kept (the
normalization pipeline strips the optional/copyright/title content the
same way it does for the vendored txt templates); `<alt>` segments are
counted for the length-delta adjustment exactly like
`License#spdx_alt_segments` (license.rb:273-283).
"""

from __future__ import annotations

import functools
import os
import re
import xml.etree.ElementTree as ET

from licensee_tpu.corpus.compiler import CompiledCorpus
from licensee_tpu.normalize.pipeline import NormalizedContent

_NS = "{http://www.spdx.org/license}"


def _strip_ns(tag: str) -> str:
    return tag[len(_NS):] if tag.startswith(_NS) else tag


def _render(node, out: list[str]) -> None:
    """Flatten an SPDX <text> subtree into plain text blocks."""
    tag = _strip_ns(node.tag)
    if tag in ("p", "titleText", "copyrightText"):
        parts: list[str] = []
        _render_inline(node, parts)
        text = re.sub(r"\s+", " ", "".join(parts)).strip()
        if text:
            out.append(text)
    elif tag == "list":
        for child in node:
            _render(child, out)
    elif tag == "item":
        parts = []
        bullet = ""
        for child in node:
            if _strip_ns(child.tag) == "bullet":
                bullet = (child.text or "").strip()
        _render_inline(node, parts, skip=("bullet",))
        text = re.sub(r"\s+", " ", "".join(parts)).strip()
        out.append((bullet + " " + text).strip() if bullet else text)
    elif tag in ("standardLicenseHeader",):
        return  # not part of the license body proper
    else:
        # text / optional / alt and unknown containers: recurse block-wise
        if node.text and node.text.strip():
            out.append(re.sub(r"\s+", " ", node.text).strip())
        for child in node:
            _render(child, out)
            if child.tail and child.tail.strip():
                out.append(re.sub(r"\s+", " ", child.tail).strip())


def _render_inline(node, parts: list[str], skip: tuple[str, ...] = ()) -> None:
    """Inline flattening: text, <alt> canonical bodies, <br/> as newline."""
    if node.text:
        parts.append(node.text)
    for child in node:
        tag = _strip_ns(child.tag)
        if tag in skip:
            pass
        elif tag == "br":
            parts.append("\n")
        else:
            _render_inline(child, parts)
        if child.tail:
            parts.append(child.tail)


class SpdxTemplate(NormalizedContent):
    """A License-like template rendered from one SPDX license-list XML."""

    def __init__(self, path: str):
        self.path = path
        with open(path, encoding="utf-8") as f:
            raw = f.read()
        root = ET.fromstring(raw)
        lic = root.find(f"{_NS}license")
        if lic is None:
            lic = root.find(f"{_NS}exception")
        if lic is None:
            raise ValueError(f"no <license> element in {path}")
        self.key = (lic.get("licenseId") or "").lower()
        self.spdx_id = lic.get("licenseId")
        self.title = lic.get("name")
        text_node = lic.find(f"{_NS}text")
        blocks: list[str] = []
        if text_node is not None:
            _render(text_node, blocks)
        self.content = "\n\n".join(blocks)

        # alt-segment count per license.rb:273-283: on the raw XML with
        # copyright/title/optional blocks removed
        text = re.search(r"<text>(.*)</text>", raw, re.S)
        body = text.group(1) if text else ""
        body = re.sub(r"<copyrightText>.*?</copyrightText>", "", body, flags=re.S)
        body = re.sub(r"<titleText>.*?</titleText>", "", body, flags=re.S)
        body = re.sub(r"<optional.*?>.*?</optional>", "", body, flags=re.S)
        self.spdx_alt_segments = len(re.findall(r"<alt .*?>", body, re.S))

    @property
    def creative_commons_q(self) -> bool:
        return self.key.startswith("cc-")

    def __repr__(self) -> str:
        return f"<SpdxTemplate {self.spdx_id}>"


def load_spdx_dir(path: str) -> list[SpdxTemplate]:
    """Every parseable license XML under an SPDX `src/` directory."""
    templates = []
    for name in sorted(os.listdir(path)):
        if not name.endswith(".xml"):
            continue
        try:
            templates.append(SpdxTemplate(os.path.join(path, name)))
        except (ET.ParseError, ValueError):
            continue  # deprecated/malformed entries don't sink the corpus
    return templates


@functools.cache
def spdx_corpus(path: str | None = None) -> CompiledCorpus:
    """Compile an SPDX license-list-XML directory (default: the vendored
    47-license mirror) into device scoring constants."""
    from licensee_tpu import vendor_paths

    if path is None:
        path = vendor_paths.SPDX_DIR
    return CompiledCorpus.compile(load_spdx_dir(path))
