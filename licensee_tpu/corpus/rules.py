"""License rules (permissions / conditions / limitations).

Parity target: `lib/licensee/rule.rb` and `lib/licensee/license_rules.rb`.
Rules are loaded from the vendored `rules.yml` and resolved against a
license's meta tags.
"""

from __future__ import annotations

import functools

import yaml

from licensee_tpu import vendor_paths


class Rule:
    def __init__(self, tag=None, label=None, description=None, group=None):
        self.tag = tag
        self.label = label
        self.description = description
        self.group = group

    def __repr__(self) -> str:
        return f'<Rule @tag="{self.tag}">'

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, Rule)
            and other.tag == self.tag
            and other.group == self.group
        )

    def __hash__(self) -> int:
        return hash(("Rule", self.tag, self.group))

    def to_h(self) -> dict:
        return {"tag": self.tag, "label": self.label, "description": self.description}

    @staticmethod
    @functools.cache
    def raw_rules() -> dict:
        with open(vendor_paths.RULES_YML, encoding="utf-8") as f:
            return yaml.safe_load(f)

    @staticmethod
    @functools.cache
    def all() -> tuple["Rule", ...]:
        out = []
        for group, rules in Rule.raw_rules().items():
            for rule in rules:
                out.append(
                    Rule(
                        tag=rule.get("tag"),
                        label=rule.get("label"),
                        description=rule.get("description"),
                        group=group,
                    )
                )
        return tuple(out)

    @staticmethod
    def find_by_tag_and_group(tag: str, group: str | None = None) -> "Rule | None":
        for rule in Rule.all():
            if rule.tag == tag and (group is None or rule.group == group):
                return rule
        return None

    find_by_tag = find_by_tag_and_group

    @staticmethod
    def groups() -> list[str]:
        return list(Rule.raw_rules().keys())


class LicenseRules:
    def __init__(self, mapping: dict[str, list[Rule]]):
        self._mapping = {group: list(rules) for group, rules in mapping.items()}

    @classmethod
    def from_license(cls, license) -> "LicenseRules":
        return cls.from_meta(license.meta)

    @classmethod
    def from_meta(cls, meta) -> "LicenseRules":
        rules = {}
        for group in Rule.groups():
            tags = meta[group] or []
            rules[group] = [Rule.find_by_tag_and_group(tag, group) for tag in tags]
        return cls(rules)

    def __getitem__(self, group):
        return self._mapping.get(group)

    def __getattr__(self, name):
        mapping = object.__getattribute__(self, "_mapping")
        if name in mapping:
            return mapping[name]
        raise AttributeError(name)

    def flatten(self) -> list[Rule]:
        out = []
        for group in self._mapping.values():
            out.extend(group)
        return out

    def key_q(self, key: str) -> bool:
        return key in self._mapping

    has_key = key_q
    __contains__ = key_q

    def to_h(self) -> dict:
        return {
            group: [r.to_h() for r in rules] for group, rules in self._mapping.items()
        }
