"""Pure-Python git-style inline word diff.

The CLI's local ``diff`` command shells out to ``git diff --word-diff``
in a tempdir (commands/diff.rb:27-37 does the same); a serving worker
answering the ``{"op": "diff"}`` wire verb cannot spawn a subprocess
and build a throwaway repository per request, so this renders the same
``[-removed-]`` / ``{+added+}`` inline markers from a difflib opcode
walk over the normalized, wrapped text the featurizer already
computes.  Deterministic, dependency-free, newline-preserving.
"""

from __future__ import annotations

import re
from difflib import SequenceMatcher

# words and hard newlines; the normalized text is already wrapped, so
# newlines carry the layout and must survive the diff
_TOKEN_RE = re.compile(r"\n|[^\s]+")


def _tokens(text: str | None) -> list[str]:
    return _TOKEN_RE.findall(text or "")


def _render(tokens: list[str]) -> str:
    out: list[str] = []
    for tok in tokens:
        if tok == "\n":
            if out and out[-1] == " ":
                out.pop()
            out.append("\n")
        else:
            out.append(tok)
            out.append(" ")
    if out and out[-1] == " ":
        out.pop()
    return "".join(out)


def word_diff(expected: str | None, actual: str | None) -> str:
    """Inline word diff from ``expected`` to ``actual``.

    Removed runs render as ``[-...-]``, added runs as ``{+...+}`` —
    the ``git diff --word-diff`` inline format the reference's diff
    command prints, minus the hunk headers (the whole normalized text
    is one hunk)."""
    a, b = _tokens(expected), _tokens(actual)
    pieces: list[str] = []
    for op, a0, a1, b0, b1 in SequenceMatcher(
        a=a, b=b, autojunk=False
    ).get_opcodes():
        if op == "equal":
            pieces.extend(a[a0:a1])
            continue
        removed = _render(a[a0:a1]) if op in ("delete", "replace") else ""
        added = _render(b[b0:b1]) if op in ("insert", "replace") else ""
        if removed and added:
            # a replaced run renders as one adjacent pair, no joining
            # space — exactly git's inline form: [-old-]{+new+}
            pieces.append(f"[-{removed}-]{{+{added}+}}")
        elif removed:
            pieces.append(f"[-{removed}-]")
        elif added:
            pieces.append(f"{{+{added}+}}")
    return _render(pieces)
