"""Minimal HTML -> Markdown conversion for .html license files.

Parity target: the reference converts HTML license files with the
`reverse_markdown` gem (`lib/licensee/content_helper.rb:293-299`,
`unknown_tags: :bypass`) before normalization.  This implements the subset of
that conversion the license corpus exercises (paragraphs, headings, inline
emphasis, links, lists, rules), with reverse_markdown's text-node whitespace
treatment: newlines/tabs inside text become spaces, runs of spaces collapse,
and border whitespace survives as a single space.
"""

from __future__ import annotations

import re
from html.parser import HTMLParser

_DROP = {"style", "script", "head", "title", "meta", "link"}
_BLANK_AROUND = {"p", "div", "table", "blockquote", "ul", "ol", "pre"}

_HEADING = {"h1": 1, "h2": 2, "h3": 3, "h4": 4, "h5": 5, "h6": 6}


def _treat_text(text: str) -> str:
    # reverse_markdown's Text converter: strip, fold \n/\t to spaces, squeeze
    # spaces, but preserve a single leading/trailing space if one was present.
    lead = " " if re.match(r"\A\s", text) else ""
    trail = " " if re.search(r"\s\Z", text) else ""
    core = re.sub(r" {2,}", " ", re.sub(r"[\n\t]", " ", text.strip()))
    if not core:
        return " " if (lead or trail) else ""
    return lead + core + trail


class _MarkdownBuilder(HTMLParser):
    def __init__(self):
        super().__init__(convert_charrefs=True)
        self.out: list[str] = []
        self.drop_depth = 0
        self.list_stack: list[str] = []

    def handle_starttag(self, tag, attrs):
        if tag in _DROP:
            self.drop_depth += 1
            return
        if self.drop_depth:
            return
        if tag in _BLANK_AROUND:
            self.out.append("\n\n")
        elif tag in _HEADING:
            self.out.append("\n" + "#" * _HEADING[tag] + " ")
        elif tag in ("b", "strong"):
            self.out.append("**")
        elif tag in ("i", "em"):
            self.out.append("_")
        elif tag == "br":
            self.out.append("\n")
        elif tag == "hr":
            self.out.append("\n* * *\n")
        elif tag in ("ul", "ol"):
            self.list_stack.append(tag)
            self.out.append("\n")
        elif tag == "li":
            marker = "- " if (not self.list_stack or self.list_stack[-1] == "ul") else "1. "
            self.out.append("\n" + marker)
        elif tag == "a":
            self._href = dict(attrs).get("href")
            self.out.append("[")
        # unknown tags: bypass (children processed, tag dropped)

    def handle_startendtag(self, tag, attrs):
        if tag == "br":
            self.out.append("\n")
        elif tag == "hr":
            self.out.append("\n* * *\n")

    def handle_endtag(self, tag):
        if tag in _DROP:
            self.drop_depth = max(0, self.drop_depth - 1)
            return
        if self.drop_depth:
            return
        if tag in _BLANK_AROUND:
            self.out.append("\n\n")
        elif tag in _HEADING:
            self.out.append("\n")
        elif tag in ("b", "strong"):
            self.out.append("**")
        elif tag in ("i", "em"):
            self.out.append("_")
        elif tag in ("ul", "ol"):
            if self.list_stack:
                self.list_stack.pop()
            self.out.append("\n")
        elif tag == "a":
            href = getattr(self, "_href", None)
            self.out.append(f"]({href})" if href else "]")

    def handle_data(self, data):
        if self.drop_depth:
            return
        self.out.append(_treat_text(data))


def html_to_markdown(html: str) -> str:
    parser = _MarkdownBuilder()
    parser.feed(html)
    parser.close()
    text = "".join(parser.out)
    # reverse_markdown cleanup: drop whitespace-only lines between paragraphs,
    # collapse >2 consecutive newlines, trim the ends.
    text = re.sub(r"\n[ \t]+\n", "\n\n", text)
    text = re.sub(r"\n{3,}", "\n\n", text)
    return text.strip()
