from licensee_tpu.normalize.pipeline import NormalizedContent, wrap

__all__ = ["NormalizedContent", "wrap"]
