"""The license text normalization engine.

This is the host-side hot path of the framework: every candidate file and
every license template is folded through the same deterministic, ordered
pipeline before wordset extraction and Dice scoring.

Parity target: `lib/licensee/content_helper.rb` (the ContentHelper mixin).
The pipeline order is load-bearing — each pass sees the output of the
previous one — and the SHA1 of the normalized output of every vendored
template must reproduce `spec/fixtures/license-hashes.json` byte-for-byte.
That golden corpus is enforced by tests/test_normalize_hashes.py.

Stage 1 (`content_without_title_and_version`, reference content_helper.rb:144-151):
    html -> hrs -> comments -> markdown_headings -> link_markup -> title -> version
Stage 2 (`content_normalized`, reference content_helper.rb:153-168):
    downcase, then normalizations (lists, https, ampersands, dashes, quote,
    hyphenated, spelling, span_markup, bullets), then strip methods (bom,
    cc_optional, cc0_optional, unlicense_optional, borders, title, version,
    url, copyright, title, block_markup, developed_by, end_of_terms,
    whitespace, mit_optional).
"""

from __future__ import annotations

import hashlib
import re

from licensee_tpu.rubytext import (
    rb,
    regexp_escape,
    ruby_split_lines,
    ruby_strip,
    squeeze_spaces,
)

START = r"\A\s*"

# reference: content_helper.rb:11-33
REGEXES = {
    "bom": rb(START + "﻿"),
    "hrs": rb(r"^\s*[=\-*]{3,}\s*$"),
    "all_rights_reserved": rb(START + r"all rights reserved\.?$", i=True),
    "whitespace": rb(r"\s+"),
    "markdown_headings": rb(r"^\s*#+"),
    "version": rb(START + r"version.*$", i=True),
    "span_markup": rb(r"[_*~]+(.*?)[_*~]+"),
    "link_markup": rb(r"\[(.+?)\]\(.+?\)"),
    "block_markup": rb(r"^\s*>"),
    "border_markup": rb(r"^[*-](.*?)[*-]$"),
    "comment_markup": rb(r"^\s*?[/*]{1,2}"),
    "url": rb(START + r"https?://[^ ]+\n"),
    "bullet": rb(r"\n\n\s*(?:[*-]|\(?[\da-z]{1,2}[).])\s+", i=True),
    "developed_by": rb(START + r"developed by:.*?\n\n", i=True, m=True),
    "cc_dedication": rb(
        r"The\s+text\s+of\s+the\s+Creative\s+Commons.*?Public\s+Domain\s+Dedication.",
        i=True,
        m=True,
    ),
    "cc_wiki": rb(r"wiki.creativecommons.org", i=True),
    "cc_legal_code": rb(r"^\s*Creative Commons Legal Code\s*$", i=True),
    "cc0_info": rb(r"For more information, please see\s*\S+zero\S+", i=True, m=True),
    "cc0_disclaimer": rb(r"CREATIVE COMMONS CORPORATION.*?\n\n", i=True, m=True),
    "unlicense_info": rb(r"For more information, please.*\S+unlicense\S+", i=True, m=True),
    "mit_optional": rb(r"\(including the next paragraph\)", i=True),
}

END_OF_TERMS = rb(r"^[\s#*_]*end of (the )?terms and conditions[\s#*_]*$", i=True)

# reference: content_helper.rb:45-88 — SPDX matching-guideline word folds.
# Insertion order is load-bearing: it is the regex alternation order.
VARIETAL_WORDS = {
    "acknowledgment": "acknowledgement",
    "analogue": "analog",
    "analyse": "analyze",
    "artefact": "artifact",
    "authorisation": "authorization",
    "authorised": "authorized",
    "calibre": "caliber",
    "cancelled": "canceled",
    "capitalisations": "capitalizations",
    "catalogue": "catalog",
    "categorise": "categorize",
    "centre": "center",
    "emphasised": "emphasized",
    "favour": "favor",
    "favourite": "favorite",
    "fulfil": "fulfill",
    "fulfilment": "fulfillment",
    "initialise": "initialize",
    "judgment": "judgement",
    "labelling": "labeling",
    "labour": "labor",
    "licence": "license",
    "maximise": "maximize",
    "modelled": "modeled",
    "modelling": "modeling",
    "offence": "offense",
    "optimise": "optimize",
    "organisation": "organization",
    "organise": "organize",
    "practise": "practice",
    "programme": "program",
    "realise": "realize",
    "recognise": "recognize",
    "signalling": "signaling",
    "sub-license": "sublicense",
    "sub license": "sublicense",
    "utilisation": "utilization",
    "whilst": "while",
    "wilful": "wilfull",
    "non-commercial": "noncommercial",
    "per cent": "percent",
    "copyright owner": "copyright holder",
}

_SPELLING = rb(
    r"\b(?:" + "|".join(regexp_escape(k) for k in VARIETAL_WORDS) + r")\b"
)

# reference: content_helper.rb:34-41 (applied in insertion order)
_LISTS = rb(r"^\s*(?:\d\.|[*-])(?: [*_]{0,2}\(?[\da-z]\)[*_]{0,2})?\s+([^\n])")
_HTTP = rb(r"http:")
_QUOTES = rb("[`'\"‘“’”]")
_HYPHENATED = rb(r"(\w+)-\s*\n\s*(\w+)")
_BULLET_JOIN = rb(r"\)\s+\(")

# Ruby's `(?<!^)…(?!$)` (not at line start / not at line end).  Python rejects
# zero-width anchors in lookbehind on some versions, so express the same
# predicate positionally: preceded by a non-newline char, followed by one.
_DASHES = rb(r"(?<=[^\n])([—–-]+)(?=[^\n])")

# reference: matchers/copyright.rb:8-11 — also used by strip_copyright
COPYRIGHT_SYMBOLS = r"(?:copyright|\(c\)|©)"
_MAIN_LINE = r"[_*\-\s]*" + COPYRIGHT_SYMBOLS + r".*$"
_OPTIONAL_LINE = r"[_*\-\s]*with Reserved Font Name.*$"
COPYRIGHT_PATTERN = START + r"((?:" + _MAIN_LINE + r")(?:" + _OPTIONAL_LINE + r")*)+$"
COPYRIGHT_REGEX = rb(COPYRIGHT_PATTERN, i=True)
# Copyright matcher full-content test: /#{REGEX}+\z/i (matchers/copyright.rb:13)
COPYRIGHT_FULL_REGEX = rb(r"(?:" + COPYRIGHT_PATTERN + r")+\Z", i=True)

_STRIP_COPYRIGHT = rb(
    r"(?:" + COPYRIGHT_PATTERN + r")|(?:" + START + r"all rights reserved\.?$)",
    i=True,
)

WORDSET_TOKEN = rb(r"(?:[\w/-](?:'s|(?<=s)')?)+")


def _get_title_regex():
    # Lazy: the global title regex is synthesized from the full license corpus
    # (content_helper.rb:199-215); importing here avoids a circular import.
    from licensee_tpu.corpus.license import global_title_regex

    return global_title_regex()


def _native():
    """The native textops scanners (or None) — bit-identical C++ twins of
    the hottest passes; the import is deferred to break the cycle with
    VARIETAL_WORDS, and textops.load() memoizes itself."""
    from licensee_tpu.native import textops

    return textops.load()


# [`'"‘“’”] -> "'" is a pure character map: str.translate runs it at C
# speed, byte-identically to _QUOTES.sub("'", ...)
_QUOTE_TABLE = str.maketrans({ch: "'" for ch in "`'\"‘“’”"})


_RUN3_MASK = None  # lazy: one 256-entry bool mask for [=\-*], built once


def _has_run3(c: str) -> bool:
    """Vectorized gate for the hrs pass: ^\\s*[=\\-*]{3,}\\s*$ cannot
    match without 3 consecutive bytes from the class — one numpy
    frombuffer + shift-AND answers that without a regex scan."""
    global _RUN3_MASK
    import numpy as np

    if _RUN3_MASK is None:
        mask = np.zeros(256, dtype=bool)
        mask[[ord(ch) for ch in "=-*"]] = True
        _RUN3_MASK = mask
    b = c.encode("utf-8", "surrogatepass")
    if len(b) < 3:
        return False
    m = _RUN3_MASK[np.frombuffer(b, dtype=np.uint8)]
    return bool((m[:-2] & m[1:-1] & m[2:]).any())


def _starts_after_ws(c: str, needle_lower: str) -> bool:
    """Gate for \\A\\s*<literal> heads (version/url/developed_by): skip
    the Ruby-\\s run, then a caseless literal compare — no regex."""
    i = 0
    n = len(c)
    while i < n and c[i] in " \t\n\v\f\r":
        i += 1
    return c[i : i + len(needle_lower)].lower() == needle_lower


def _plain_strip(content: str, regex: re.Pattern, might: bool = True) -> str:
    """Ruby ContentHelper#strip: gsub(regex, ' ').squeeze(' ').strip —
    the squeeze and strip apply even when the regex does not match.

    ``might=False`` means a literal gate proved the regex cannot match:
    the sub is skipped but the squeeze/strip contract still holds."""
    nat = _native()
    if nat is not None:
        if regex is REGEXES["whitespace"]:
            return nat.strip_whitespace(content)
        return nat.squeeze_strip(regex.sub(" ", content) if might else content)
    subbed = regex.sub(" ", content) if might else content
    return ruby_strip(squeeze_spaces(subbed))


class NormalizedContent:
    """Mixin providing the normalization pipeline, wordsets, and Dice
    similarity.  Subclasses provide ``content`` (str | None) and may provide
    ``filename`` and ``spdx_alt_segments``."""

    content: str | None = None

    # -- public surface (content_helper.rb:108-168) --

    @property
    def wordset(self) -> frozenset[str]:
        cached = self.__dict__.get("_wordset")
        if cached is None:
            cn = self.content_normalized()
            if cn is None:
                cached = None
            else:
                nat = _native()
                cached = (
                    nat.wordset(cn)
                    if nat is not None
                    else frozenset(WORDSET_TOKEN.findall(cn))
                )
            self.__dict__["_wordset"] = cached
        return cached

    @property
    def length(self) -> int:
        cn = self.content_normalized()
        return len(cn) if cn else 0

    def length_delta(self, other) -> int:
        return abs(self.length - other.length)

    def similarity(self, other) -> float:
        """Sørensen–Dice word-set similarity as a percentage, with the
        length-delta false-positive penalty (content_helper.rb:128-133).

        Note the asymmetry: ``self`` is normally the License — the field
        excision and the SPDX-alt-adjusted delta use self's metadata.  The
        delta divide is Ruby Integer division (floor)."""
        overlap = len(self.wordset_fieldless & other.wordset)
        total = (
            len(self.wordset_fieldless)
            + len(other.wordset)
            - len(self.fields_normalized_set)
        )
        return (overlap * 200.0) / (total + self._variation_adjusted_length_delta(other) // 4)

    @property
    def content_hash(self) -> str:
        cached = self.__dict__.get("_content_hash")
        if cached is None:
            cached = hashlib.sha1(
                self.content_normalized().encode("utf-8")
            ).hexdigest()
            self.__dict__["_content_hash"] = cached
        return cached

    @property
    def content_without_title_and_version(self) -> str:
        cached = self.__dict__.get("_cwtv")
        if cached is None:
            c = ruby_strip(self.content if self.content is not None else "")
            c = self._strip_html(c)
            # literal gates: a pass whose pattern requires a byte/substring
            # the text lacks cannot match — same rationale (and the same
            # gate set) as the native pipeline's plain_strip_gated
            c = _plain_strip(c, REGEXES["hrs"], might=_has_run3(c))
            c = self._strip_comments(c)
            c = _plain_strip(
                c, REGEXES["markdown_headings"], might="#" in c
            )
            if "[" in c:
                c = REGEXES["link_markup"].sub(lambda m: m.group(1), c)
            c = self._strip_title(c)
            c = _plain_strip(
                c, REGEXES["version"], might=_starts_after_ws(c, "version")
            )
            cached = c
            self.__dict__["_cwtv"] = cached
        return cached

    def content_normalized(self, wrap_at: int | None = None) -> str | None:
        cached = self.__dict__.get("_content_normalized")
        if cached is None:
            c = self.content_without_title_and_version.lower()

            # normalizations (gsub only — no squeeze/strip side effects);
            # the dash/quote/hyphenation/spelling passes run as native
            # scanners when built (bit-identical, tests/test_textops.py).
            # Each gated pass is a literal no-op when its required byte is
            # absent; _HTTP and the quote class are literal/char-class
            # transforms, so str.replace / str.translate run them at C
            # speed byte-identically on the fallback path.
            nat = _native()
            c = _LISTS.sub(lambda m: "- " + m.group(1), c)
            c = c.replace("http:", "https:")
            c = c.replace("&", "and")
            has_dashish = "-" in c or "–" in c or "—" in c
            if nat is not None:
                if has_dashish:
                    c = nat.dashes(c)
                c = nat.quotes(c)
                if "-" in c:
                    c = nat.hyphenated(c)
                c = nat.spelling(c)
            else:
                if has_dashish:
                    c = _DASHES.sub("-", c)
                c = c.translate(_QUOTE_TABLE)
                if "-" in c:
                    c = _HYPHENATED.sub(
                        lambda m: m.group(1) + "-" + m.group(2), c
                    )
                c = _SPELLING.sub(lambda m: VARIETAL_WORDS[m.group(0)], c)
            if "_" in c or "*" in c or "~" in c:
                c = REGEXES["span_markup"].sub(lambda m: m.group(1), c)
            if "\n\n" in c:
                c = REGEXES["bullet"].sub(lambda _m: "\n\n- ", c)
            if ")" in c:
                c = _BULLET_JOIN.sub(lambda _m: ")(", c)

            # strip methods (content_helper.rb:89-105), in order
            c = _plain_strip(c, REGEXES["bom"], might="﻿" in c)
            c = self._strip_cc_optional(c)
            c = self._strip_cc0_optional(c)
            c = self._strip_unlicense_optional(c)
            if "*" in c or "-" in c:
                c = REGEXES["border_markup"].sub(lambda m: m.group(1), c)
            c = self._strip_title(c)
            c = _plain_strip(
                c, REGEXES["version"], might=_starts_after_ws(c, "version")
            )
            c = _plain_strip(
                c, REGEXES["url"], might=_starts_after_ws(c, "http")
            )
            c = self._strip_copyright(c)
            c = self._strip_title(c)
            c = _plain_strip(c, REGEXES["block_markup"], might=">" in c)
            c = _plain_strip(
                c,
                REGEXES["developed_by"],
                might=_starts_after_ws(c, "developed by:"),
            )
            c = self._strip_end_of_terms(c)
            c = _plain_strip(c, REGEXES["whitespace"])
            c = _plain_strip(
                c, REGEXES["mit_optional"], might="(including" in c
            )

            cached = c
            self.__dict__["_content_normalized"] = cached
        if wrap_at is None:
            return cached
        return wrap(cached, wrap_at)

    # -- field excision (content_helper.rb:323-335) --

    @property
    def wordset_fieldless(self) -> frozenset[str]:
        cached = self.__dict__.get("_wordset_fieldless")
        if cached is None:
            cached = self.wordset - self.fields_normalized_set
            self.__dict__["_wordset_fieldless"] = cached
        return cached

    @property
    def fields_normalized(self) -> list[str]:
        """Substitutable-field names in normalized content, duplicates kept."""
        cached = self.__dict__.get("_fields_normalized")
        if cached is None:
            from licensee_tpu.corpus.fields import field_regex

            cached = [
                m.group(1) for m in field_regex().finditer(self.content_normalized())
            ]
            self.__dict__["_fields_normalized"] = cached
        return cached

    @property
    def fields_normalized_set(self) -> frozenset[str]:
        return frozenset(self.fields_normalized)

    def _variation_adjusted_length_delta(self, other) -> int:
        # content_helper.rb:337-347: Licenses get the SPDX-alt-segment
        # adjusted delta; plain files get the raw delta.
        delta = self.length_delta(other)
        alt = getattr(self, "spdx_alt_segments", None)
        if alt is None:
            return delta
        adjusted = delta - max(len(self.fields_normalized), alt) * 5
        return adjusted if adjusted > 0 else 0

    # -- strip helpers --

    def _strip_html(self, c: str) -> str:
        filename = getattr(self, "filename", None)
        if not filename:
            return c
        dot = filename.rfind(".")
        ext = filename[dot:] if dot >= 0 else ""
        if not re.match(r".*\.html?", ext, re.I):
            return c
        from licensee_tpu.normalize.html2md import html_to_markdown

        return html_to_markdown(c)

    def _strip_comments(self, c: str) -> str:
        # content_helper.rb:246-252: only strip when every line is a comment
        lines = ruby_split_lines(c)
        if len(lines) == 1:
            return c
        if not all(REGEXES["comment_markup"].search(line) for line in lines):
            return c
        return _plain_strip(c, REGEXES["comment_markup"])

    def _strip_title(self, c: str) -> str:
        # content_helper.rb:238-240: peel title lines from the front
        title_regex = _get_title_regex()
        while title_regex.search(c):
            c = _plain_strip(c, title_regex)
        return c

    def _strip_copyright(self, c: str) -> str:
        while _STRIP_COPYRIGHT.search(c):
            c = _plain_strip(c, _STRIP_COPYRIGHT)
        return c

    def _strip_cc_optional(self, c: str) -> str:
        if "creative commons" not in c:
            return c
        c = _plain_strip(c, REGEXES["cc_dedication"])
        return _plain_strip(c, REGEXES["cc_wiki"])

    def _strip_cc0_optional(self, c: str) -> str:
        if "associating cc0" not in c:
            return c
        c = _plain_strip(c, REGEXES["cc_legal_code"])
        c = _plain_strip(c, REGEXES["cc0_info"])
        return _plain_strip(c, REGEXES["cc0_disclaimer"])

    def _strip_unlicense_optional(self, c: str) -> str:
        if "unlicense" not in c:
            return c
        return _plain_strip(c, REGEXES["unlicense_info"])

    def _strip_end_of_terms(self, c: str) -> str:
        m = END_OF_TERMS.search(c)
        return c[: m.start()] if m else c


def wrap(text: str | None, line_width: int = 80) -> str | None:
    """Re-wrap normalized text (content_helper.rb:177-193), used by the diff
    command and the detection-quality specs."""
    if text is None:
        return None
    text = REGEXES["bullet"].sub(lambda m: "\n" + m.group(0) + "\n", text)
    text = rb(r"([^\n])\n([^\n])").sub(lambda m: m.group(1) + " " + m.group(2), text)

    fill = rb(r"(.{1," + str(line_width) + r"})(\s+|$)")
    lines = []
    for line in ruby_split_lines(text):
        if REGEXES["hrs"].search(line) or len(line) <= line_width:
            lines.append(line)
        else:
            lines.append(ruby_strip(fill.sub(lambda m: m.group(1) + "\n", line)))
    return ruby_strip("\n".join(lines))


def format_percent(value: float) -> str:
    return f"{value:.2f}%"
