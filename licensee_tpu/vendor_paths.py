"""Locations of the vendored license corpus data.

Mirrors the data layout consumed by the reference (see
`lib/licensee/license.rb:58-68` and `lib/licensee/rule.rb:40-43`):
choosealicense.com license templates + rules/fields metadata, and the SPDX
license-list-XML sources used for <alt> segment counting.
"""

from __future__ import annotations

import os

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
VENDOR_DIR = os.path.join(REPO_ROOT, "vendor")

LICENSE_DIR = os.path.join(VENDOR_DIR, "choosealicense.com", "_licenses")
DATA_DIR = os.path.join(VENDOR_DIR, "choosealicense.com", "_data")
SPDX_DIR = os.path.join(VENDOR_DIR, "license-list-XML", "src")

RULES_YML = os.path.join(DATA_DIR, "rules.yml")
FIELDS_YML = os.path.join(DATA_DIR, "fields.yml")
META_YML = os.path.join(DATA_DIR, "meta.yml")
