from licensee_tpu.parallel.mesh import (
    build_mesh,
    make_sharded_scorer,
    shard_batch,
)

__all__ = ["build_mesh", "make_sharded_scorer", "shard_batch"]
