"""Elastic capacity control for the striped batch path and the fleet.

The measured host model (bench.py ``bench_host_model``) prices what a
STATIC ``--stripes N`` buys; this module closes the loop: a small,
pure state machine (:class:`AutoscaleDecider`) watches a pressure
signal — the per-stripe ``pipeline_featurize_busy`` lane gauge for the
batch runner, queue depth / SLO burn for the serving fleet — and
proposes capacity changes under the three rules every production
autoscaler needs:

* **hysteresis** — a threshold crossing must hold for
  ``confirm_ticks`` consecutive observations before it counts (one
  noisy scrape must never move the fleet);
* **cooldown** — after a scale event the decider holds for
  ``cooldown_s`` regardless of pressure (the new capacity needs time
  to show up in the signal it is judged by);
* **bounds** — proposals clamp to ``[min_units, max_units]``, always.

Scale-ups are additionally **payoff-checked**: the decider remembers
the throughput measured before a grow step, and if the next decision
window shows no improvement (``payoff_min``), it steps back and pins a
ceiling at the last paying size — this is what makes a saturated-host
signal (featurize busy sticks at 1.0 no matter how many stripes pile
on) converge to the best static size instead of running away to
``max_units``.  The ceiling unpins when pressure falls back below the
scale-down threshold (the workload changed).

The decider is deliberately process-free: the striped batch runner
(parallel/stripes.py ``--stripes elastic``) and the fleet supervisor
(fleet/supervisor.py + :class:`FleetAutoscaler`) own the actual
drain/respawn mechanics and feed observations in.  Freshness of the
scraped per-stripe expositions is the scraper's job:
:class:`ExpositionScraper` reads the atomic ``--prom-file`` dumps and
rejects any file whose ``stripe_scrape_epoch`` gauge has stopped
advancing — the signature of a just-killed (or wedged) stripe whose
last exposition would otherwise be read as live forever.

House rules: monotonic clocks only, nothing printed — callers surface
events through their own channels.
"""

from __future__ import annotations

import re
import time

__all__ = [
    "AutoscaleConfig",
    "AutoscaleDecider",
    "ExpositionScraper",
    "FleetAutoscaler",
    "capacity_plan",
    "parse_exposition_gauges",
]


class AutoscaleConfig:
    """Bounds + control constants for one decider.

    ``up_at``/``down_at`` are pressure thresholds in [0, 1] with
    ``up_at > down_at`` (the hysteresis band between them is the hold
    region); ``confirm_ticks`` is how many consecutive observations a
    crossing must hold; ``cooldown_s`` gates consecutive scale events;
    ``payoff_min`` is the fractional throughput improvement a grow
    step must show to keep its ceiling open (0 disables the check)."""

    def __init__(
        self,
        min_units: int = 1,
        max_units: int = 8,
        *,
        up_at: float = 0.85,
        down_at: float = 0.40,
        confirm_ticks: int = 3,
        cooldown_s: float = 30.0,
        payoff_min: float = 0.05,
    ):
        if min_units < 1:
            raise ValueError(f"min_units must be >= 1, got {min_units!r}")
        if max_units < min_units:
            raise ValueError(
                f"max_units ({max_units!r}) must be >= min_units "
                f"({min_units!r})"
            )
        if not 0.0 <= down_at < up_at <= 1.0:
            raise ValueError(
                f"need 0 <= down_at < up_at <= 1, got "
                f"down_at={down_at!r} up_at={up_at!r}"
            )
        if confirm_ticks < 1:
            raise ValueError(
                f"confirm_ticks must be >= 1, got {confirm_ticks!r}"
            )
        if cooldown_s < 0:
            raise ValueError(
                f"cooldown_s must be >= 0, got {cooldown_s!r}"
            )
        self.min_units = int(min_units)
        self.max_units = int(max_units)
        self.up_at = float(up_at)
        self.down_at = float(down_at)
        self.confirm_ticks = int(confirm_ticks)
        self.cooldown_s = float(cooldown_s)
        self.payoff_min = float(payoff_min)

    def clamp(self, units: int) -> int:
        return max(self.min_units, min(self.max_units, int(units)))


class AutoscaleDecider:
    """The hysteresis/cooldown/bounds state machine.

    ``observe(now, pressure, throughput=None)`` feeds one observation
    and returns the proposed new unit count, or None to hold.  The
    CALLER owns the mechanics of acting on a proposal and must treat a
    returned value as a commitment — the decider's cooldown starts at
    the proposal.  ``pressure`` is the saturation signal in [0, 1]
    (clamped); ``throughput`` (any monotone goodness rate, e.g. rows/s)
    enables the grow payoff check.  Steps are +-1 unit: single-step
    moves plus cooldown are what make convergence observable — the
    signal is re-measured at every size along the way."""

    def __init__(self, config: AutoscaleConfig, units: int):
        self.config = config
        self.units = config.clamp(units)
        self._up_streak = 0
        self._down_streak = 0
        self._last_event_t: float | None = None
        self._last_pressure = 0.0
        self._events_counter = None
        # the grow payoff check: (units before the step, throughput
        # before the step); judged at the next post-cooldown decision
        self._pending_payoff: tuple[int, float] | None = None
        # units above this never pay (measured): pinned by a failed
        # payoff check, unpinned when pressure falls below down_at
        self._ceiling: int | None = None
        self.events: list[dict] = []

    # -- telemetry --

    def register(self, registry) -> "AutoscaleDecider":
        """Publish the decider's live state as gauges/counters on
        ``registry`` (idempotent per registry via set_fn re-pointing)."""
        registry.gauge(
            "autoscale_capacity_units",
            "Current capacity units the autoscaler is running "
            "(stripes + featurize-procs for the batch runner, workers "
            "for the fleet)",
        ).set_fn(lambda: self.units)
        registry.gauge(
            "autoscale_pressure",
            "Last observed saturation pressure in [0, 1] (featurize-"
            "lane occupancy for the batch runner, queue/SLO pressure "
            "for the fleet); up/down thresholds bracket it",
        ).set_fn(lambda: self._last_pressure)
        self._events_counter = registry.counter(
            "autoscale_scale_events_total",
            "Scale events proposed by the autoscaler",
            labels=("direction",),
        )
        return self

    # -- the decision step --

    def _in_cooldown(self, now: float) -> bool:
        return (
            self._last_event_t is not None
            and now - self._last_event_t < self.config.cooldown_s
        )

    def _record(self, now: float, new_units: int, why: str,
                pressure: float) -> int:
        direction = "up" if new_units > self.units else "down"
        self.events.append({
            "t": round(now, 3),
            "from": self.units,
            "to": new_units,
            "why": why,
            "pressure": round(pressure, 4),
        })
        if self._events_counter is not None:
            self._events_counter.labels(direction=direction).inc()
        self.units = new_units
        self._last_event_t = now
        self._up_streak = 0
        self._down_streak = 0
        return new_units

    def observe(
        self,
        now: float,
        pressure: float | None,
        throughput: float | None = None,
    ) -> int | None:
        """One observation; returns the new unit count or None (hold).

        ``pressure=None`` means no fresh signal this tick (every
        exposition was stale): streaks reset — staleness must never
        accumulate toward a scale event."""
        cfg = self.config
        if pressure is None:
            self._up_streak = 0
            self._down_streak = 0
            return None
        pressure = max(0.0, min(1.0, float(pressure)))
        self._last_pressure = pressure
        if self._in_cooldown(now):
            # cooldown holds the fleet steady AND keeps the streak
            # counters quiet: observations during the settle window
            # reflect the old size as much as the new one
            self._up_streak = 0
            self._down_streak = 0
            return None
        # the payoff judgment happens at the first post-cooldown
        # observation that carries a throughput sample: a grow step
        # that didn't raise throughput by payoff_min steps back and
        # pins the ceiling at the size that last paid
        if self._pending_payoff is not None and throughput is not None:
            prev_units, prev_tp = self._pending_payoff
            self._pending_payoff = None
            if prev_tp > 0 and throughput < prev_tp * (
                1.0 + cfg.payoff_min
            ):
                self._ceiling = prev_units
                return self._record(
                    now, prev_units, "grow did not pay; stepping back",
                    pressure,
                )
        if pressure >= cfg.up_at:
            self._down_streak = 0
            self._up_streak += 1
            limit = cfg.max_units
            if self._ceiling is not None:
                limit = min(limit, self._ceiling)
            if self._up_streak >= cfg.confirm_ticks and self.units < limit:
                if throughput is not None and cfg.payoff_min > 0:
                    self._pending_payoff = (self.units, throughput)
                return self._record(
                    now, self.units + 1, "pressure high", pressure
                )
            return None
        if pressure <= cfg.down_at:
            self._up_streak = 0
            self._down_streak += 1
            # low pressure says the workload changed: the measured
            # ceiling no longer describes it
            self._ceiling = None
            self._pending_payoff = None
            if (
                self._down_streak >= cfg.confirm_ticks
                and self.units > cfg.min_units
            ):
                return self._record(
                    now, self.units - 1, "pressure low", pressure
                )
            return None
        # the hold band between down_at and up_at
        self._up_streak = 0
        self._down_streak = 0
        return None


def capacity_plan(
    units: int, *, max_stripes: int, base_featurize_procs: int = 0
) -> tuple[int, int]:
    """Map abstract capacity units to the batch runner's two levers:
    ``(stripes, featurize_procs)``.

    Stripes are the primary lever (each adds a whole pipeline — its
    own serial section, GIL, and writer); once ``max_stripes`` is
    reached, further units become per-stripe ``--featurize-procs``
    (sidecar featurize processes behind each stripe's produce lane).
    ``featurize_procs`` of 0 means "don't forward the flag"."""
    if units < 1:
        raise ValueError(f"units must be >= 1, got {units!r}")
    stripes = min(units, max_stripes)
    extra = units - stripes
    procs = base_featurize_procs + extra if extra else base_featurize_procs
    return stripes, procs


# one exposition sample line with NO labels: `name value` — the lane
# gauges and the epoch stamp are unlabeled by construction, so the
# scraper needs nothing fancier (labeled series pass through unparsed)
_BARE_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*) "
    r"([+-]?(?:[0-9]*\.?[0-9]+(?:[eE][+-]?[0-9]+)?|Inf|inf)|NaN|nan)$"
)


def parse_exposition_gauges(text: str) -> dict[str, float]:
    """{name: value} for every UNLABELED sample in a Prometheus text
    exposition (last sample wins).  Comments, labeled series, and
    malformed lines are skipped — a torn or foreign file parses to
    fewer keys, never to an exception."""
    out: dict[str, float] = {}
    for line in text.splitlines():
        m = _BARE_SAMPLE_RE.match(line)
        if m is None:
            continue
        try:
            out[m.group(1)] = float(m.group(2))
        except ValueError:
            continue
    return out


class ExpositionScraper:
    """Freshness-checked reads of the per-stripe ``--prom-file`` dumps.

    Each worker's heartbeat atomically rewrites its exposition with a
    monotonically increasing ``stripe_scrape_epoch`` gauge;
    ``sample(key, path, now)`` returns the parsed gauges only while
    that epoch keeps advancing.  A file whose epoch has not moved for
    ``stale_after_s`` belongs to a dead, wedged, or not-yet-started
    incarnation and reads as None — the decider then sees "no signal",
    never a frozen lane snapshot from a just-killed stripe.

    With a fleet telemetry store attached (``store=``, a
    :class:`~licensee_tpu.obs.tsdb.TsdbStore`), ``sample_store``
    offers the same occupancy signal without a file in the path: the
    router's scrape scheduler already ingests every worker's gauges
    under per-worker labels, so the autoscaler can read the stored
    samples directly.  The file path stays — the striped batch runner
    has no router and keeps scraping ``--prom-file`` dumps."""

    def __init__(self, stale_after_s: float = 10.0, *, store=None):
        if stale_after_s <= 0:
            raise ValueError(
                f"stale_after_s must be > 0, got {stale_after_s!r}"
            )
        self.stale_after_s = float(stale_after_s)
        self.store = store
        # key -> (last epoch seen, monotonic time the epoch last moved)
        self._seen: dict[str, tuple[float, float]] = {}

    def forget(self, key: str) -> None:
        """Drop a key's epoch history (its worker was retired — a
        respawn under the same key starts a fresh freshness clock)."""
        self._seen.pop(key, None)

    def sample(
        self, key: str, path: str, now: float | None = None
    ) -> dict[str, float] | None:
        now = time.perf_counter() if now is None else now
        try:
            with open(path, encoding="utf-8") as f:
                gauges = parse_exposition_gauges(f.read())
        except OSError:
            return None
        epoch = gauges.get("stripe_scrape_epoch")
        if epoch is None:
            # no heartbeat stamp: a final merge-input dump or a foreign
            # file — not a live scrape target
            return None
        last = self._seen.get(key)
        if last is None or epoch != last[0]:
            self._seen[key] = (epoch, now)
            return gauges
        if now - last[1] > self.stale_after_s:
            return None
        return gauges

    def sample_store(
        self,
        labels: dict,
        now: float | None = None,
        *,
        names: tuple = ("pipeline_featurize_busy",),
    ) -> dict[str, float] | None:
        """Store-backed twin of ``sample``: each named gauge's freshest
        stored sample carrying ``labels`` (the scrape scheduler's
        per-worker ingest labels).  Freshness is the sample's own
        timestamp — a series that stopped advancing reads as None
        exactly like a frozen ``--prom-file`` epoch.  Timestamps live
        in the store's clock domain (``time.monotonic``), so a passed
        ``now`` must too; None reads that clock."""
        if self.store is None:
            return None
        now = time.monotonic() if now is None else now
        out: dict[str, float] = {}
        for name in names:
            hit = self.store.latest(name, labels)
            if hit is None or now - hit[0] > self.stale_after_s:
                continue
            out[name] = hit[1]
        return out or None


class FleetAutoscaler:
    """Queue-depth / SLO-burn worker scaling for the serving fleet.

    Wraps a :class:`~licensee_tpu.fleet.supervisor.Supervisor`:
    ``tick()`` reads every worker's last stats probe (scheduler queue
    depth + in flight, the PR 4 probe), folds in the SLO engine's burn
    alerts (the PR 12 ladder) as a pressure floor, feeds the decider,
    and acts on proposals through ``supervisor.add_worker`` /
    ``remove_worker``.  ``socket_for(index)`` names each elastic
    worker's socket; elastic workers are named ``{prefix}{index}`` and
    retire newest-first (the static seed workers are never removed).

    ``slo_snapshot`` is an optional zero-arg callable returning the
    engine's evaluation dict (``SLOEngine.last``-shaped): any
    objective's ``fast_burn_alert`` pins pressure to 1.0 — burning the
    error budget at page rate IS saturation, whatever the queues say
    — and ``slow_burn_alert`` floors it at the up threshold."""

    def __init__(
        self,
        supervisor,
        config: AutoscaleConfig,
        socket_for,
        *,
        target_inflight_per_worker: int = 8,
        slo_snapshot=None,
        name_prefix: str = "auto",
        on_event=None,
    ):
        if target_inflight_per_worker < 1:
            raise ValueError(
                "target_inflight_per_worker must be >= 1, got "
                f"{target_inflight_per_worker!r}"
            )
        self.supervisor = supervisor
        self.socket_for = socket_for
        self.target_inflight = int(target_inflight_per_worker)
        self.slo_snapshot = slo_snapshot
        self.name_prefix = name_prefix
        self._on_event = on_event
        base = len(supervisor.workers)
        # the static seed fleet is the floor: the autoscaler only
        # manages the workers it added
        config = AutoscaleConfig(
            min_units=max(config.min_units, base),
            max_units=max(config.max_units, base),
            up_at=config.up_at,
            down_at=config.down_at,
            confirm_ticks=config.confirm_ticks,
            cooldown_s=config.cooldown_s,
            payoff_min=0.0,  # fleet adds capacity per worker linearly
        )
        self.decider = AutoscaleDecider(config, base)
        self._elastic: list[str] = []
        self._next_index = 0

    def _event(self, message: str) -> None:
        if self._on_event is not None:
            self._on_event(message)

    def pressure(self) -> float | None:
        """The fleet's saturation signal in [0, 1]: mean outstanding
        work (queue depth + in flight) per worker against the target,
        floored by the SLO burn alerts."""
        depths = []
        for handle in self.supervisor.workers.values():
            sched = (handle.last_stats or {}).get("scheduler") or {}
            queue = sched.get("queue_depth")
            inflight = sched.get("in_flight")
            if queue is None and inflight is None:
                continue
            depths.append((queue or 0) + (inflight or 0))
        if not depths:
            return None
        load = sum(depths) / len(depths) / self.target_inflight
        p = min(1.0, load)
        snap = self.slo_snapshot() if self.slo_snapshot is not None else None
        for row in ((snap or {}).get("objectives") or {}).values():
            if row.get("fast_burn_alert"):
                return 1.0
            if row.get("slow_burn_alert"):
                p = max(p, self.decider.config.up_at)
        return p

    def tick(self, now: float | None = None) -> int | None:
        """One control step; returns the new worker count if a scale
        event fired, else None."""
        now = time.perf_counter() if now is None else now
        proposal = self.decider.observe(now, self.pressure())
        if proposal is None:
            return None
        current = len(self.supervisor.workers)
        if proposal > current:
            name = f"{self.name_prefix}{self._next_index}"
            self._next_index += 1
            self.supervisor.add_worker(name, self.socket_for(name))
            self._elastic.append(name)
            self._event(
                f"autoscale: +1 worker ({name}) -> {proposal} "
                f"(pressure {self.decider._last_pressure:.2f})"
            )
        elif proposal < current and self._elastic:
            name = self._elastic.pop()
            self.supervisor.remove_worker(name)
            self._event(
                f"autoscale: -1 worker ({name}) -> {proposal} "
                f"(pressure {self.decider._last_pressure:.2f})"
            )
        return proposal
