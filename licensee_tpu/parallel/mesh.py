"""Device-mesh sharding for the batch scoring path.

The reference is single-process Ruby (SURVEY.md §2.7 — no parallelism of
any kind); this module is the TPU-native scale-out design:

* **data axis** (primary): the candidate-blob batch is sharded across
  chips — each chip scores its slice against the full template matrix.
  This is the 10M-files lever; no cross-chip communication is needed in
  the steady state, so throughput scales linearly over ICI-connected
  chips.
* **model axis**: the template bit-matrix is sharded along the vocab
  (lane) dimension for corpora whose T×V matrix outgrows per-chip HBM
  (full SPDX + large vocab).  Each chip computes partial popcounts over
  its lane slice, and the partial overlaps are summed with a `psum` over
  the model axis inside `shard_map` — the collective rides ICI.

Multi-host (DCN) runs use the same meshes built over
`jax.distributed`-initialized global devices: `jax.make_mesh` lays out
axes so that the model axis stays within a slice (ICI) and the data axis
spans slices (DCN), which is the right placement because the data axis
never communicates.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from licensee_tpu.kernels.dice_xla import (
    CorpusArrays,
    _argmax_exact,
    finish_scores,
    overlap_pairs,
    score_pairs,
    topk_candidates,
)


def build_mesh(
    n_data: int | None = None,
    n_model: int = 1,
    devices=None,
) -> Mesh:
    """Build a ('data', 'model') mesh over the available devices."""
    devices = devices if devices is not None else jax.devices()
    if n_data is None:
        n_data = len(devices) // n_model
    grid = np.asarray(devices[: n_data * n_model]).reshape(n_data, n_model)
    return Mesh(grid, ("data", "model"))


def shard_batch(mesh: Mesh, *arrays):
    """Place per-blob arrays with their batch dim sharded over 'data'."""
    out = []
    for a in arrays:
        spec = P("data", *([None] * (a.ndim - 1)))
        out.append(jax.device_put(a, NamedSharding(mesh, spec)))
    return tuple(out)


def make_sharded_scorer(
    corpus: CorpusArrays, mesh: Mesh, method: str = "popcount",
    topk: int = 0,
):
    """A scorer jitted over the mesh.

    Blob features come in sharded over 'data'.  The template matrix is
    sharded over 'model' along the packed-lane axis; partial overlaps are
    psum-reduced.  With n_model == 1 the psum is the identity and XLA
    compiles a pure data-parallel program.

    ``topk > 0`` appends per-blob top-k candidate columns (the
    closest-licenses view): a purely per-row reduction, so it needs no
    extra collectives on either axis."""

    n_model = mesh.shape["model"]
    if method not in ("popcount", "matmul"):
        raise ValueError(f"unknown scoring method: {method!r}")

    def _finish_best(num, den):
        best = _argmax_exact(num, den)
        if not topk:
            return best
        return (*best, *topk_candidates(num, den, topk))

    def _score(corpus_arrays, file_bits, n_words, lengths, cc_fp):
        num, den = score_pairs(
            corpus_arrays, file_bits, n_words, lengths, cc_fp, method=method
        )
        return _finish_best(num, den)

    if n_model == 1:
        # Pure DP: replicate the corpus, shard the batch; XLA partitions
        # everything else automatically.
        corpus_sharding = jax.tree.map(
            lambda _a: NamedSharding(mesh, P()), corpus
        )
        data_shardings = (
            NamedSharding(mesh, P("data", None)),
            NamedSharding(mesh, P("data")),
            NamedSharding(mesh, P("data")),
            NamedSharding(mesh, P("data")),
        )
        out_shardings = NamedSharding(mesh, P("data"))
        if topk:
            out_shardings = tuple(
                [NamedSharding(mesh, P("data"))] * 3
                + [NamedSharding(mesh, P("data", None))] * 3
            )
        fn = jax.jit(
            _score,
            in_shardings=(corpus_sharding, *data_shardings),
            out_shardings=out_shardings,
        )
        corpus_on_mesh = jax.device_put(
            corpus, jax.tree.map(lambda _a: NamedSharding(mesh, P()), corpus)
        )
        return partial(fn, corpus_on_mesh)

    # DP × TP: lanes of the bit-matrix (and of blob bitsets) are sharded
    # over 'model'; each chip popcounts its lane slice and the partial
    # overlaps are summed over the model axis.
    try:
        from jax import shard_map
    except ImportError:  # jax<=0.4.x keeps it under experimental
        from jax.experimental.shard_map import shard_map

    def _tp_score(corpus_arrays, file_bits, n_words, lengths, cc_fp):
        # Inside shard_map: arrays hold this chip's (data, model) block.
        # Each chip popcounts its lane slice; psum over 'model' rebuilds
        # the full overlap, then the shared exact algebra finishes it.
        partial_overlap = overlap_pairs(corpus_arrays, file_bits, method)
        overlap = lax.psum(partial_overlap, "model")
        num, den = finish_scores(
            corpus_arrays, overlap, n_words, lengths, cc_fp
        )
        return _finish_best(num, den)

    # lanes of the bit-matrix sharded over the model axis; scalars replicated
    spec_fields = {
        "bits": P(None, "model"),
        "n_wf": P(),
        "n_fieldset": P(),
        "field_count": P(),
        "alt_count": P(),
        "length": P(),
        "cc_flag": P(),
        "valid": P(),
    }
    corpus_specs = CorpusArrays(**spec_fields)
    out_specs = (P("data"),) * 3
    if topk:
        out_specs = out_specs + (P("data", None),) * 3
    fn = shard_map(
        _tp_score,
        mesh=mesh,
        in_specs=(
            corpus_specs,
            P("data", "model"),
            P("data"),
            P("data"),
            P("data"),
        ),
        out_specs=out_specs,
    )
    jitted = jax.jit(fn)

    corpus_on_mesh = CorpusArrays(
        **{
            name: jax.device_put(
                getattr(corpus, name), NamedSharding(mesh, spec)
            )
            for name, spec in spec_fields.items()
        }
    )

    def run(file_bits, n_words, lengths, cc_fp):
        return jitted(corpus_on_mesh, file_bits, n_words, lengths, cc_fp)

    return run
