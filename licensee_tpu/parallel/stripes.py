"""One-command striped scale-out for the offline batch path.

The measured host scaling model (bench.py ``bench_host_model``, the ADR
in projects/batch_project.py) says one process's pipeline is bounded by
its serial section no matter the core count, and that the 10M-files-in-
60s north star therefore needs >=3 manifest-striped processes.  Striping
has existed since PR 0 as a hand-assembled env contract
(parallel/distributed.py: ``LICENSEE_TPU_COORDINATOR`` /
``_NUM_PROCESSES`` / ``_PROCESS_ID`` / ``_VISIBLE_CHIPS``); this module
makes the documented scaling lever ONE command::

    licensee-tpu batch-detect manifest.txt --output out.jsonl --stripes 4

The runner spawns N co-located worker processes on this host, each
classifying a contiguous stripe of the manifest (the same
``manifest_stripe`` math the multi-host path uses, so a stripe IS a
rank) and writing its own resume-safe JSONL shard.  Container
manifests ('::' forms) stripe by their EXPANDED blob count — a single
million-member tarball splits across stripes, each worker expanding
the same manifest metadata-only and reading just its span — and the
container-verdict sidecar is derived once from the MERGED output
(exactly one row per container, even when its blobs spanned stripes).  No
``jax.distributed`` bootstrap is involved: the scoring workload has no
cross-blob collectives, so co-located stripes need no coordinator — the
stripe index/count ride the child's argv and chip subsets ride the SAME
``LICENSEE_TPU_VISIBLE_CHIPS`` dict-env contract the serving fleet's
supervisor uses (``chips_for_worker`` + ``apply_visible_chips`` over the
CHILD's env dict — this process's environment is never touched).

Supervision reuses the PR-4 fleet patterns via the extracted core in
fleet/supervisor.py: crash restart with capped exponential backoff
(``BackoffPolicy``) — a restarted stripe RESUMES from its own shard's
``_resume_point``, never re-scoring rows another stripe owns — a
progress probe that SIGKILLs a wedged worker (alive but its shard has
not grown past the stall timeout), and a graceful SIGTERM drain
(``request_stop()`` forwards SIGTERM and waits; a mid-write kill leaves
at most one torn line, which the per-shard resume truncates).

When every stripe exits clean the runner deterministically merges:

* **rows** — shards concatenate in stripe order into ``<output>``
  (atomic ``os.replace``), after verifying each shard's newline-
  terminated row count equals its stripe span, so the merged file is
  bit-identical to a single-process run over the same manifest;
* **stats** — per-stripe ``BatchStats`` JSON sums into one dict
  (``merge_stats``);
* **metrics** — per-stripe Prometheus expositions merge into
  ``<output>.prom`` via the fleet's ``merge_expositions`` with a
  ``stripe`` label.

House rules (script/lint): monotonic clocks only, and nothing is ever
printed from this module — progress surfaces through the ``on_event``
callback (the CLI points it at stderr), so the runner can never corrupt
a pipeline that shares its stdout.

The runner is a LIBRARY first and a CLI second: ``run()`` raises
(``StripeError`` / ``StripeStopped``) instead of exiting, touches no
terminal, and reports machine-readable lifecycle through the optional
``on_progress(kind, info)`` callback (``spawn`` / ``stripe_done`` /
``restart`` / ``progress`` / ``merged``) so an embedding parent — the
jobs executor (licensee_tpu/jobs) is the first — can mirror stripe
lifecycle into its own telemetry without parsing the human strings
``on_event`` carries.  ``request_stop()`` stays signal-handler safe,
and a stop surfaces as ``StripeStopped`` so parents can tell an
operator cancel from a permanent failure.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

from licensee_tpu.fleet.supervisor import (
    BackoffPolicy,
    terminate_process,
    worker_env,
)
from licensee_tpu.parallel.distributed import (
    chips_for_worker,
    count_manifest_entries,
    manifest_stripe,
    shard_output_path,
)

__all__ = [
    "StripeError",
    "StripeRunner",
    "StripeStopped",
    "auto_stripe_count",
    "count_manifest_entries",
    "load_scaling_model",
    "merge_stats",
    "parse_stripes_arg",
    "selftest",
    "selftest_autoscale",
    "stripe_argv",
]

# how many cores one stripe can productively use before its own serial
# section caps it: parallel/serial ~= 255.9/6 us per blob post-writer-
# thread (BENCH_DETAILS.json host_model.scaling_model) — but the USEFUL
# lower bound is 2 (one core feeding produce workers, one for the
# dispatch/finish loop + writer), which is what auto sizing guarantees
CORES_PER_STRIPE_MIN = 2
AUTO_STRIPE_CAP = 16


class StripeError(RuntimeError):
    """A stripe failed permanently (restart budget exhausted), a shard
    failed verification at merge time, or the runner was stopped."""


class StripeStopped(StripeError):
    """The runner drained because ``request_stop()`` was called — not a
    failure: the shards are resume-safe and a rerun continues.  A
    subclass so existing ``except StripeError`` callers keep working
    while an embedding parent (the jobs executor) can tell a cancel
    from a crash."""


def load_scaling_model(details_path: str | None = None) -> dict | None:
    """The bench's measured host scaling model
    (``details.host_model.scaling_model`` in BENCH_DETAILS.json), or
    None when no bench artifact is readable — auto sizing then falls
    back to pure core-count math."""
    if details_path is None:
        details_path = os.path.join(
            os.path.dirname(
                os.path.dirname(
                    os.path.dirname(os.path.abspath(__file__))
                )
            ),
            "BENCH_DETAILS.json",
        )
    try:
        with open(details_path, encoding="utf-8") as f:
            details = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    model = ((details.get("details") or {}).get("host_model") or {}).get(
        "scaling_model"
    )
    return model if isinstance(model, dict) else None


def auto_stripe_count(
    cores: int | None = None, scaling_model: dict | None = None
) -> int:
    """``--stripes auto``: how many stripes THIS host should run.

    Every stripe needs at least ``CORES_PER_STRIPE_MIN`` cores to
    overlap its produce workers with its serial loop, so the host
    supports ``cores // 2`` stripes, capped at ``AUTO_STRIPE_CAP``.
    When the bench scaling model is available, its
    ``striped_processes_needed_10M_60s`` floor applies whenever the
    cores allow it — the north-star target must never be under-sized by
    auto on a host that can afford it (with the measured model the
    core-count rule already clears the floor, so the floor only matters
    if a future model demands more stripes than ``AUTO_STRIPE_CAP``)."""
    if cores is None:
        cores = os.cpu_count() or 1
    by_cores = max(1, cores // CORES_PER_STRIPE_MIN)
    n = min(by_cores, AUTO_STRIPE_CAP)
    if scaling_model:
        need = scaling_model.get("striped_processes_needed_10M_60s")
        if isinstance(need, (int, float)) and need >= 1:
            n = max(n, min(int(need), by_cores))
    return n


def parse_stripes_arg(value: str) -> int | str:
    """CLI ``--stripes`` value: a positive int, ``auto`` (sized once
    from the host + bench scaling model), or ``elastic`` (start small
    and let the runner's autoscaler grow/shrink against the measured
    per-stripe lane gauges — returned as the literal string)."""
    if value == "auto":
        return auto_stripe_count(scaling_model=load_scaling_model())
    if value == "elastic":
        return "elastic"
    try:
        n = int(value)
    except ValueError:
        raise ValueError(
            f"--stripes wants a positive integer, 'auto' or 'elastic', "
            f"got {value!r}"
        ) from None
    if n < 1:
        raise ValueError(f"--stripes must be >= 1, got {n}")
    return n


def stripe_argv(
    manifest: str,
    output: str,
    index: int,
    count: int,
    forward: tuple[str, ...] = (),
    *,
    resume: bool = True,
) -> list[str]:
    """The child command for one stripe: the batch-detect CLI with the
    internal stripe rank args plus the per-stripe stats/metrics dump
    paths the merge reads.  ``resume=False`` (first spawn of a
    ``--no-resume`` run) restarts the shard from scratch; RESTARTS
    always resume — that is the whole point of the per-shard
    ``_resume_point``."""
    shard = shard_output_path(output, index, count)
    argv = [
        sys.executable, "-m", "licensee_tpu.cli.main", "batch-detect",
        manifest,
        "--output", output,
        "--stripe-index", str(index),
        "--stripe-count", str(count),
        "--stats-file", f"{shard}.stats.json",
        "--prom-file", f"{shard}.prom",
    ]
    if not resume:
        argv.append("--no-resume")
    argv.extend(forward)
    return argv


def merge_stats(stats_list: list[dict]) -> dict:
    """Sum per-stripe ``BatchStats.as_dict()`` rows into one fleet-level
    dict: integer counters add, ``routed`` adds per route, and
    ``stage_seconds`` adds per stage (they are already thread-seconds,
    so cross-process addition keeps the same unit; ``elapsed`` becomes
    the sum of per-stripe elapsed — the runner reports wall clock
    separately).

    Resume semantics, same as a single-process resumed run: each
    stripe's stats count the rows ITS FINAL INCARNATION classified, so
    after a crash-restart the merged ``total`` is less than
    ``rows_written`` (the rows the dead incarnation already wrote are
    on disk, not re-scored).  ``rows_written`` in the runner summary is
    the completeness guarantee; the stats are the work accounting."""
    merged: dict = {}
    routed: dict = {}
    stages: dict = {}
    for stats in stats_list:
        for key, value in stats.items():
            if key == "routed":
                for route, n in value.items():
                    routed[route] = routed.get(route, 0) + n
            elif key == "stage_seconds":
                for stage, s in value.items():
                    stages[stage] = round(stages.get(stage, 0.0) + s, 4)
            elif isinstance(value, (int, float)):
                merged[key] = merged.get(key, 0) + value
    if routed:
        merged["routed"] = routed
    merged["stage_seconds"] = stages
    return merged


def _forwarded_int(forward: tuple[str, ...], flag: str) -> int | None:
    """The int value a forward-args tuple carries for ``flag``, or
    None (absent or malformed — the child argv parser owns erroring)."""
    for i, arg in enumerate(forward):
        if arg == flag and i + 1 < len(forward):
            try:
                return int(forward[i + 1])
            except ValueError:
                return None
    return None


class _StripeHandle:
    """One supervised stripe worker: its argv/env, live process, and
    restart/progress bookkeeping (the offline twin of the fleet's
    WorkerHandle)."""

    def __init__(self, index: int, shard: str, argv_first, argv_resume, env):
        self.index = index
        self.shard = shard
        self.argv_first = list(argv_first)
        self.argv_resume = list(argv_resume)
        self.env = dict(env)
        self.proc: subprocess.Popen | None = None
        self.log: str = f"{shard}.log"
        self.done = False
        # restarts is the BACKOFF-WINDOW counter (reset after sustained
        # progress, like the fleet supervisor's stable_after_s earn-
        # back); total_restarts is the lifetime count status reports
        self.restarts = 0
        self.total_restarts = 0
        self.spawned_at: float | None = None
        self.next_spawn_at = 0.0
        self.exit_codes: list[int] = []
        # progress probe state: (last observed shard size, when it last
        # changed) — a live process whose shard stops growing is wedged
        self.last_size = -1
        self.last_growth_t: float | None = None
        # deterministic-failure detector: consecutive nonzero exits
        # whose incarnation never CHANGED the shard at all (a config
        # error, a broken argv — those die before touching the file) —
        # burning the whole restart-backoff budget on those only delays
        # the real error message.  "Changed", not "grew": a --no-resume
        # child legitimately truncates a stale shard below its old size
        # and must still count as progress.
        self.size_at_spawn = -1
        self.changed_since_spawn = False
        self.no_growth_failures = 0

    @property
    def pid(self) -> int | None:
        return self.proc.pid if self.proc is not None else None

    def as_dict(self) -> dict:
        return {
            "shard": self.shard,
            "pid": self.pid,
            "done": self.done,
            "restarts": self.total_restarts,
            "exit_codes": self.exit_codes[-5:],
        }


class StripeRunner:
    """Spawn + supervise + merge N manifest-striped batch workers.

    ``argv_for(index, count, resume)`` / ``env_for(index, chips)``
    override the child command/environment so tests and the fault
    harness can drive the exact production restart/merge machinery over
    stub workers (the fleet Supervisor's ``argv_for`` pattern)."""

    def __init__(
        self,
        manifest: str,
        output: str,
        stripes: int,
        *,
        forward_args: tuple[str, ...] = (),
        resume: bool = True,
        auto_clamp: bool = False,
        chips_per_stripe: int | None = None,
        argv_for=None,
        env_for=None,
        base_env: dict | None = None,
        max_restarts: int = 5,
        backoff: BackoffPolicy | None = None,
        stall_timeout_s: float = 600.0,
        startup_grace_s: float = 180.0,
        poll_interval_s: float = 0.25,
        sigterm_timeout_s: float = 10.0,
        progress_every: float = 0,
        on_event=None,
        on_progress=None,
        container_layout: dict | None = None,
        elastic=None,
        elastic_interval_s: float = 2.0,
        elastic_stale_after_s: float = 10.0,
    ):
        if stripes < 1:
            raise ValueError(f"stripes must be >= 1, got {stripes!r}")
        if chips_per_stripe is not None and chips_per_stripe < 1:
            raise ValueError(
                f"chips_per_stripe must be >= 1, got {chips_per_stripe!r}"
            )
        if max_restarts < 0:
            raise ValueError(
                f"max_restarts must be >= 0, got {max_restarts!r}"
            )
        self.manifest = manifest
        self.output = output
        self.n_entries = count_manifest_entries(manifest)
        # container manifests ('::' forms): the striping denominator
        # moves to the EXPANDED blob count — a single million-member
        # tarball splits across stripes (each worker expands the same
        # manifest metadata-only and keeps its span; the span math
        # agrees with this layout by construction) — and the merged
        # output's container-verdict sidecar is derived HERE after the
        # merge (exactly one row per container, even when its blobs
        # spanned stripes), so workers write per-blob shards only.
        self.container_layout = None
        from licensee_tpu.ingest.sources import is_container_entry

        # streamed probe first: a 50M-line LOOSE manifest must never
        # materialize in the supervisor
        with open(manifest, encoding="utf-8") as f:
            has_containers = any(
                is_container_entry(line.strip()) for line in f
            )
        if has_containers:
            if container_layout is None:
                from licensee_tpu.ingest.sources import expanded_layout

                with open(manifest, encoding="utf-8") as f:
                    entries = [
                        line.strip() for line in f if line.strip()
                    ]
                # metadata-only counting pass; every handle closed
                # before returning (workers open their own)
                container_layout = expanded_layout(entries)
            # else: the caller already paid the expansion (the CLI's
            # resume preflight probe) — don't rescan the archives
            self.container_layout = container_layout
            self.n_entries = self.container_layout["total"]
        if stripes > max(1, self.n_entries):
            if auto_clamp:
                # `--stripes auto` sized from the HOST; a small manifest
                # simply can't use that many — clamp, don't lecture the
                # operator about a number they never chose
                stripes = max(1, self.n_entries)
            else:
                raise ValueError(
                    f"more stripes ({stripes}) than manifest entries "
                    f"({self.n_entries}); an empty stripe would write "
                    "an empty shard forever — lower --stripes"
                )
        self.stripes = int(stripes)
        self.resume = bool(resume)
        self.max_restarts = int(max_restarts)
        self.backoff = backoff or BackoffPolicy(base_s=0.5, max_s=30.0)
        self.stall_timeout_s = float(stall_timeout_s)
        self.startup_grace_s = float(startup_grace_s)
        self.poll_interval_s = float(poll_interval_s)
        self.sigterm_timeout_s = float(sigterm_timeout_s)
        # CLI --progress: emit a shard-growth event at most every SECS
        # (per-stripe shard BYTES — cheap stat()s, no row counting on
        # the supervision path); 0 disables
        self.progress_every = float(progress_every or 0)
        if not (self.progress_every >= 0):  # rejects negatives AND NaN
            raise ValueError(
                f"progress_every must be >= 0, got {progress_every!r}"
            )
        self._on_event = on_event
        self._on_progress = on_progress
        self._stop_requested = False
        # spawn ingredients, kept so an elastic rescale can rebuild the
        # handle set at a different stripe count / featurize-procs
        self._forward_args = tuple(forward_args)
        self._argv_for = argv_for
        self._env_for = env_for
        self._base_env = base_env
        self._chips_per_stripe = chips_per_stripe
        # --stripes elastic (parallel/autoscale.py): ``elastic`` is an
        # AutoscaleConfig; the runner scrapes each live stripe's
        # --prom-file heartbeat for pipeline_featurize_busy, feeds the
        # decider, and a proposal becomes a DRAIN + RESPAWN at the new
        # plan (each worker exits resume-safe; shard names embed the
        # stripe count, so a revisit of an earlier count resumes its
        # own shards and the final merge's cleanup sweeps the rest)
        self.elastic = elastic
        self._scale_events = 0
        self._decider = None
        self._scraper = None
        self._featurize_procs = _forwarded_int(
            self._forward_args, "--featurize-procs"
        )
        if elastic is not None:
            from licensee_tpu.parallel.autoscale import (
                AutoscaleDecider,
                ExpositionScraper,
            )

            if elastic_interval_s <= 0:
                raise ValueError(
                    "elastic_interval_s must be > 0, got "
                    f"{elastic_interval_s!r}"
                )
            self.elastic_interval_s = float(elastic_interval_s)
            # stripes beyond this become per-stripe featurize procs
            # (capacity_plan): a stripe needs CORES_PER_STRIPE_MIN
            # cores to be worth its serial section
            self._elastic_max_stripes = max(1, min(
                elastic.max_units, AUTO_STRIPE_CAP, self.n_entries
            ))
            self._decider = AutoscaleDecider(
                elastic, elastic.clamp(self.stripes)
            )
            self.stripes = min(self._decider.units,
                               self._elastic_max_stripes)
            self._scraper = ExpositionScraper(
                stale_after_s=elastic_stale_after_s
            )
            self._last_autoscale_t: float | None = None
            self._tp_last: tuple[float, int] | None = None
        self._initial_stripes = self.stripes
        self.handles: list[_StripeHandle] = self._build_handles(
            self.stripes, self._featurize_procs
        )
        # shard paths THIS RUN has already started: a --no-resume
        # elastic rescale clears a count's stale shards only on the
        # first visit (revisits resume this run's own work)
        self._counts_started = {h.shard for h in self.handles}

    def _forward_with_procs(self, procs: int | None) -> tuple[str, ...]:
        """The forward args with ``--featurize-procs`` swapped to
        ``procs`` (dropped when falsy) — the elastic rescale's second
        lever rides the respawn argv."""
        out: list[str] = []
        skip = False
        for arg in self._forward_args:
            if skip:
                skip = False
                continue
            if arg == "--featurize-procs":
                skip = True
                continue
            out.append(arg)
        if procs:
            out += ["--featurize-procs", str(procs)]
        return tuple(out)

    def _build_handles(
        self, stripes: int, featurize_procs: int | None = None
    ) -> list:
        forward = (
            self._forward_with_procs(featurize_procs)
            if self.elastic is not None
            else self._forward_args
        )
        handles = []
        for i in range(stripes):
            shard = shard_output_path(self.output, i, stripes)
            chips = (
                chips_for_worker(i, self._chips_per_stripe)
                if self._chips_per_stripe is not None
                else None
            )
            env = (
                self._env_for(i, chips)
                if self._env_for is not None
                else worker_env(self._base_env, chips)
            )
            if self._argv_for is not None:
                argv_first = self._argv_for(i, stripes, resume=self.resume)
                argv_resume = self._argv_for(i, stripes, resume=True)
            else:
                argv_first = stripe_argv(
                    self.manifest, self.output, i, stripes, forward,
                    resume=self.resume,
                )
                argv_resume = stripe_argv(
                    self.manifest, self.output, i, stripes, forward,
                    resume=True,
                )
            handles.append(
                _StripeHandle(i, shard, argv_first, argv_resume, env)
            )
        return handles

    # -- events --

    def _event(self, message: str) -> None:
        if self._on_event is not None:
            self._on_event(message)

    def _notify(self, kind: str, **info) -> None:
        """Machine-readable lifecycle for embedding parents: ``kind``
        is one of ``spawn`` / ``stripe_done`` / ``restart`` /
        ``progress`` / ``merged``; ``info`` carries the stripe index
        and whatever the event measured.  Runs on the supervising
        thread — a callback that blocks stalls the poll loop, so
        parents should only snapshot state here."""
        if self._on_progress is not None:
            self._on_progress(kind, info)

    # -- lifecycle primitives --

    def _spawn(self, handle: _StripeHandle, *, first: bool) -> None:
        argv = handle.argv_first if first else handle.argv_resume
        log = open(handle.log, "ab")
        try:
            handle.proc = subprocess.Popen(
                argv,
                env=handle.env,
                stdin=subprocess.DEVNULL,
                stdout=subprocess.DEVNULL,
                stderr=log,
            )
        finally:
            log.close()  # the child holds its own descriptor
        now = time.perf_counter()
        handle.spawned_at = now
        handle.last_growth_t = now
        handle.last_size = self._shard_size(handle)
        handle.size_at_spawn = handle.last_size
        handle.changed_since_spawn = False

    def _shard_size(self, handle: _StripeHandle) -> int:
        try:
            return os.path.getsize(handle.shard)
        except OSError:
            return -1

    def _schedule_restart(self, handle: _StripeHandle, why: str) -> None:
        delay = self.backoff.delay_s(handle.restarts)
        handle.restarts += 1
        handle.total_restarts += 1
        handle.next_spawn_at = time.perf_counter() + delay
        handle.proc = None
        self._event(
            f"stripe {handle.index}: {why}; restart "
            f"{handle.restarts}/{self.max_restarts} in {delay:.2f}s "
            "(resuming from its shard's completed prefix)"
        )
        self._notify(
            "restart", stripe=handle.index, why=why, delay_s=delay
        )

    def request_stop(self) -> None:
        """Ask the run loop to drain: forward SIGTERM to every live
        stripe, wait for exits, and return without merging.  Signal-
        handler safe (sets a flag only)."""
        self._stop_requested = True

    def _drain(self) -> None:
        for handle in self.handles:
            proc = handle.proc
            if proc is not None and proc.poll() is None:
                try:
                    proc.send_signal(signal.SIGTERM)
                except OSError:
                    pass
        deadline = time.perf_counter() + self.sigterm_timeout_s
        for handle in self.handles:
            proc = handle.proc
            if proc is None:
                continue
            budget = deadline - time.perf_counter()
            try:
                proc.wait(timeout=max(0.05, budget))
            except subprocess.TimeoutExpired:
                pass
            terminate_process(proc, 0.5)

    def _abort(self, why: str) -> None:
        self._drain()
        raise StripeError(why)

    def _log_tail(self, handle: _StripeHandle, n: int = 800) -> str:
        try:
            with open(handle.log, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - n))
                return f.read().decode("utf-8", "replace")
        except OSError:
            return ""

    # -- elastic autoscaling (--stripes elastic) --

    def _throughput(self, now: float) -> float | None:
        """Aggregate shard growth in bytes/s since the previous tick —
        the payoff signal the decider judges a grow step by.  Reset at
        every rescale (shard sets change; the first post-rescale tick
        re-baselines instead of comparing across shard generations)."""
        total = sum(max(0, self._shard_size(h)) for h in self.handles)
        last = self._tp_last
        self._tp_last = (now, total)
        if last is None or now - last[0] <= 0:
            return None
        return (total - last[1]) / (now - last[0])

    def _autoscale_tick(self, now: float) -> None:
        if (
            self._last_autoscale_t is not None
            and now - self._last_autoscale_t < self.elastic_interval_s
        ):
            return
        self._last_autoscale_t = now
        live = [
            h for h in self.handles if not h.done and h.proc is not None
        ]
        if not live:
            return
        pressures = []
        for handle in live:
            gauges = self._scraper.sample(
                handle.shard, f"{handle.shard}.prom", now
            )
            if gauges is None:
                continue  # stale/absent heartbeat: not a live signal
            busy = gauges.get("pipeline_featurize_busy")
            if busy is not None:
                pressures.append(busy)
        pressure = (
            sum(pressures) / len(pressures) if pressures else None
        )
        proposal = self._decider.observe(
            now, pressure, self._throughput(now)
        )
        if proposal is None:
            return
        from licensee_tpu.parallel.autoscale import capacity_plan

        stripes, procs = capacity_plan(
            proposal, max_stripes=self._elastic_max_stripes,
            base_featurize_procs=self._featurize_procs or 0,
        )
        if stripes == self.stripes and (procs or None) == (
            self._current_procs()
        ):
            return
        self._rescale(stripes, procs or None, proposal)

    def _current_procs(self) -> int | None:
        return getattr(self, "_live_procs", self._featurize_procs)

    def _rescale(
        self, stripes: int, procs: int | None, units: int
    ) -> None:
        """One scale event: drain every worker (SIGTERM, resume-safe
        exit), rebuild the handle set at the new plan, respawn.  Shard
        names embed the stripe count, so workers at the new count never
        resume another count's rows; partial shards from the old count
        stay on disk — a later return to that count resumes them, and
        the final merge's cleanup glob sweeps whatever never merged."""
        self._event(
            f"autoscale: {self.stripes} -> {stripes} stripes"
            + (f" (+{procs} featurize-procs)" if procs else "")
            + f" [units {units}]; draining for resume-safe respawn"
        )
        self._notify(
            "rescale", from_stripes=self.stripes, to_stripes=stripes,
            featurize_procs=procs, units=units,
        )
        self._drain()
        for handle in self.handles:
            self._scraper.forget(handle.shard)
        self.stripes = int(stripes)
        self._live_procs = procs
        self._scale_events += 1
        self._tp_last = None
        self.handles = self._build_handles(stripes, procs)
        for handle in self.handles:
            if not self.resume:
                # a --no-resume run must not adopt a stale same-count
                # shard from an EARLIER run: the first visit to each
                # count starts it clean (revisits within this run
                # resume — that is this run's own work)
                if handle.shard not in self._counts_started:
                    try:
                        os.remove(handle.shard)
                    except OSError:
                        pass
            self._counts_started.add(handle.shard)
            if (
                self.resume
                and self._count_complete_rows(handle.shard)
                == self._stripe_span(handle.index, stripes)
            ):
                # this span finished on an earlier visit to this count:
                # nothing to respawn (a worker would exit 0 instantly,
                # but not spawning keeps the event log honest)
                handle.done = True
                continue
            try:
                self._spawn(handle, first=False)
            except OSError as exc:
                self._abort(
                    f"stripe {handle.index}: respawn after rescale "
                    f"failed: {exc}"
                )
            self._notify(
                "spawn", stripe=handle.index, pid=handle.pid,
                first=False,
            )

    def _stripe_span(self, index: int, stripes: int) -> int:
        lo, hi = manifest_stripe(self.n_entries, index, stripes)
        return hi - lo

    # -- the run loop --

    def run(self) -> dict:
        """Run every stripe to completion, then merge.  Returns the
        summary dict (rows written, merged stats, per-stripe detail).
        Raises StripeError on permanent failure or an operator stop."""
        t0 = time.perf_counter()
        if self.resume and self._already_complete():
            rows = self.n_entries
            self._event(
                f"{self.output}: already complete ({rows} rows); "
                "nothing to do"
            )
            # the merge persisted the run's stats/exposition beside the
            # output, so even a no-op rerun surfaces them (an operator's
            # --stats-file/--prom-file contract must not silently lapse)
            stats = None
            try:
                with open(
                    f"{self.output}.stats.json", encoding="utf-8"
                ) as f:
                    stats = json.load(f)
            except (OSError, json.JSONDecodeError):
                pass
            prom = f"{self.output}.prom"
            return {
                "stripes": self.stripes,
                "files": self.n_entries,
                "rows_written": rows,
                "already_complete": True,
                "elapsed_s": 0.0,
                "stats": stats,
                "prom": prom if os.path.exists(prom) else None,
                "per_stripe": [],
            }
        for handle in self.handles:
            try:
                self._spawn(handle, first=True)
            except OSError as exc:
                # drain whatever already spawned: a supervisor that
                # dies mid-boot must not orphan half a fleet
                self._abort(
                    f"stripe {handle.index}: spawn failed: {exc}"
                )
            self._event(
                f"stripe {handle.index}/{self.stripes}: pid "
                f"{handle.pid} -> {os.path.basename(handle.shard)}"
            )
            self._notify(
                "spawn", stripe=handle.index, pid=handle.pid, first=True
            )
        t_progress = t0
        while not all(h.done for h in self.handles):
            if self._stop_requested:
                self._drain()
                raise StripeStopped(
                    "stopped by operator before completion; shards are "
                    "resume-safe — rerun the same command to continue"
                )
            now = time.perf_counter()
            for handle in self.handles:
                if handle.done:
                    continue
                proc = handle.proc
                if proc is None:
                    if now >= handle.next_spawn_at:
                        try:
                            self._spawn(handle, first=False)
                        except OSError as exc:
                            self._abort(
                                f"stripe {handle.index}: respawn "
                                f"failed: {exc}"
                            )
                        self._event(
                            f"stripe {handle.index}: respawned as pid "
                            f"{handle.pid}"
                        )
                        self._notify(
                            "spawn", stripe=handle.index,
                            pid=handle.pid, first=False,
                        )
                    continue
                rc = proc.poll()
                if rc is not None:
                    handle.exit_codes.append(rc)
                    if rc == 0:
                        handle.done = True
                        handle.proc = None
                        self._event(
                            f"stripe {handle.index}: complete"
                        )
                        self._notify("stripe_done", stripe=handle.index)
                        continue
                    changed = (
                        handle.changed_since_spawn
                        or self._shard_size(handle)
                        != handle.size_at_spawn
                    )
                    if changed:
                        handle.no_growth_failures = 0
                    elif rc >= 0:
                        # signal deaths (rc < 0: OOM kill, a stray
                        # SIGKILL) are environmental, not a config
                        # error — they use the backoff budget and never
                        # feed the deterministic-failure counter
                        handle.no_growth_failures += 1
                    if handle.no_growth_failures >= 2:
                        # two consecutive failures without a single row
                        # written: deterministic (bad corpus path, a
                        # resume-config mismatch, broken argv) — more
                        # backoff cycles only delay the real error
                        tail = self._log_tail(handle)
                        self._abort(
                            f"stripe {handle.index} is failing "
                            "deterministically (repeated exits with no "
                            f"shard progress, exit codes "
                            f"{handle.exit_codes[-5:]}); giving up. "
                            f"Last stderr:\n{tail}"
                        )
                    if handle.restarts >= self.max_restarts:
                        tail = self._log_tail(handle)
                        self._abort(
                            f"stripe {handle.index} failed "
                            f"{handle.restarts + 1} times (exit codes "
                            f"{handle.exit_codes[-5:]}); giving up. "
                            f"Last stderr:\n{tail}"
                        )
                    self._schedule_restart(handle, f"exit {rc}")
                    continue
                # progress probe: the offline twin of the fleet's stats
                # probe — a live worker whose shard has stopped growing
                # past the stall timeout is wedged (hung compile,
                # stopped process) and gets the SIGKILL + restart path
                size = self._shard_size(handle)
                if size != handle.last_size:
                    handle.last_size = size
                    handle.last_growth_t = now
                    handle.changed_since_spawn = True
                    if handle.restarts and (
                        now - (handle.spawned_at or now)
                        >= self.backoff.stable_after_s
                    ):
                        # sustained progress earns the backoff counter
                        # back (the fleet supervisor's stable_after_s
                        # rule): an isolated transient crash per hour
                        # must never exhaust a lifetime budget mid-run
                        handle.restarts = 0
                elif self.stall_timeout_s > 0 and (
                    now - (handle.spawned_at or now) > self.startup_grace_s
                ) and (
                    now - (handle.last_growth_t or now)
                    > self.stall_timeout_s
                ):
                    try:
                        proc.kill()
                        proc.wait(timeout=5.0)
                    except (OSError, subprocess.TimeoutExpired):
                        pass
                    if proc.poll() is None:
                        # still not dead (e.g. wedged in uninterruptible
                        # sleep): do NOT respawn over a process that may
                        # wake and keep appending to the shard — retry
                        # the kill on the next poll instead
                        self._event(
                            f"stripe {handle.index}: wedged and "
                            "SIGKILL has not taken effect yet; "
                            "retrying before respawn"
                        )
                        continue
                    handle.exit_codes.append(proc.returncode)
                    if handle.restarts >= self.max_restarts:
                        self._abort(
                            f"stripe {handle.index} wedged (no shard "
                            f"growth for {self.stall_timeout_s:.0f}s) "
                            "and out of restarts"
                        )
                    self._schedule_restart(
                        handle,
                        f"wedged (no shard growth for "
                        f"{self.stall_timeout_s:.0f}s) — SIGKILLed",
                    )
            if (
                self.progress_every
                and now - t_progress >= self.progress_every
            ):
                t_progress = now
                shard_bytes = [
                    max(0, self._shard_size(h)) for h in self.handles
                ]
                done = sum(h.done for h in self.handles)
                sizes = " ".join(
                    f"{h.index}:{shard_bytes[h.index]}B"
                    + ("(done)" if h.done else "")
                    for h in self.handles
                )
                self._event(
                    f"progress: {done}/"
                    f"{self.stripes} stripes done; shards {sizes}"
                )
                self._notify(
                    "progress", done=done, stripes=self.stripes,
                    shard_bytes=shard_bytes,
                )
            if self._decider is not None:
                self._autoscale_tick(now)
            time.sleep(self.poll_interval_s)
        summary = self._merge()
        summary["elapsed_s"] = round(time.perf_counter() - t0, 3)
        files = summary["rows_written"]
        if summary["elapsed_s"] > 0:
            summary["files_per_sec"] = round(
                files / summary["elapsed_s"], 1
            )
        if self._decider is not None:
            summary["autoscale"] = {
                "initial_stripes": self._initial_stripes,
                "final_stripes": self.stripes,
                "featurize_procs": self._current_procs(),
                "units": self._decider.units,
                "scale_events": self._scale_events,
                "events": list(self._decider.events),
            }
        return summary

    # -- completion + merge --

    def _count_complete_rows(self, path: str) -> int:
        """Newline-terminated line count (a torn tail does not count —
        the same definition as BatchProject._resume_point, without the
        truncation side effect)."""
        n = 0
        try:
            with open(path, "rb") as f:
                for line in f:
                    if line.endswith(b"\n"):
                        n += 1
        except OSError:
            return 0
        return n

    def _already_complete(self) -> bool:
        return (
            os.path.exists(self.output)
            and self._count_complete_rows(self.output) == self.n_entries
        )

    def _merge(self) -> dict:
        """Deterministic shard -> output merge: verify every shard's
        row count equals its stripe span, concatenate in stripe order
        (atomic replace), merge stats and Prometheus expositions, then
        remove the per-stripe files.  With one stripe the child already
        wrote ``output`` directly (shard_output_path keeps the plain
        path at count<=1) and only the bookkeeping merges."""
        per_stripe = {h.index: h.as_dict() for h in self.handles}
        total = 0
        for handle in self.handles:
            lo, hi = manifest_stripe(
                self.n_entries, handle.index, self.stripes
            )
            rows = self._count_complete_rows(handle.shard)
            if rows != hi - lo:
                raise StripeError(
                    f"shard {handle.shard} has {rows} complete rows, "
                    f"expected {hi - lo} (stripe [{lo}, {hi})); refusing "
                    "to merge a short shard"
                )
            total += rows
        if self.stripes > 1:
            tmp = f"{self.output}.merge.tmp"
            with open(tmp, "wb") as out:
                for handle in self.handles:
                    with open(handle.shard, "rb") as f:
                        while True:
                            block = f.read(1 << 20)
                            if not block:
                                break
                            out.write(block)
            os.replace(tmp, self.output)
            # the merged output is a complete single-file run: carry
            # shard 0's config sidecar so a later single-process resume
            # of this output file sees the config that produced it
            # (the expansion fingerprint inside it is span-independent,
            # so it matches what a single-process run would record)
            shard0_meta = f"{self.handles[0].shard}.meta.json"
            if os.path.exists(shard0_meta):
                os.replace(shard0_meta, f"{self.output}.meta.json")
        if self.container_layout is not None and (
            self.container_layout["spans"]
            or self.container_layout["subsets"]
        ):
            # the blob-level JOIN: striped workers wrote per-blob rows
            # only (a container may span shards), so the ONE container
            # sidecar derives here from the merged output over the
            # full-expansion groups — the license algebra re-runs over
            # each container's merged row set and every container
            # emits exactly one verdict row
            from licensee_tpu.ingest.verdict import (
                write_container_verdicts,
            )

            write_container_verdicts(
                self.output,
                self.container_layout["spans"],
                self.container_layout["subsets"],
            )
        stats_rows = []
        expositions: dict[str, str] = {}
        for handle in self.handles:
            stats_path = f"{handle.shard}.stats.json"
            try:
                with open(stats_path, encoding="utf-8") as f:
                    row = json.load(f)
            except (OSError, json.JSONDecodeError):
                row = None
            if row is not None:
                stats_rows.append(row)
                # per-stripe detail rides the summary (the bench reads
                # each stripe's steady-state elapsed from here)
                per_stripe[handle.index]["stats"] = row
            prom_path = f"{handle.shard}.prom"
            try:
                with open(prom_path, encoding="utf-8") as f:
                    expositions[f"stripe{handle.index}"] = f.read()
            except OSError:
                pass
        merged_stats = merge_stats(stats_rows) if stats_rows else None
        if merged_stats is not None:
            # persist beside the output (atomic) so a rerun over the
            # complete output can still surface the run's stats
            tmp = f"{self.output}.stats.json.tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(merged_stats, f)
                f.write("\n")
            os.replace(tmp, f"{self.output}.stats.json")
        prom_out = None
        if expositions:
            from licensee_tpu.obs import merge_expositions

            prom_out = f"{self.output}.prom"
            with open(prom_out, "w", encoding="utf-8") as f:
                f.write(merge_expositions(expositions, label="stripe"))
        self._cleanup()
        self._event(
            f"merged {self.stripes} shard(s) -> {self.output} "
            f"({total} rows)"
        )
        self._notify("merged", rows=total, stripes=self.stripes)
        return {
            "stripes": self.stripes,
            "files": self.n_entries,
            "rows_written": total,
            "already_complete": False,
            "stats": merged_stats,
            "prom": prom_out,
            "per_stripe": [per_stripe[i] for i in sorted(per_stripe)],
        }

    def _cleanup(self) -> None:
        """Remove per-stripe intermediates after a successful merge (the
        work is complete and lives in ``output``; stale shards would
        otherwise confuse the next striped run's resume).  The shard
        sweep is a GLOB over ``<output>.shard-*`` — an earlier aborted
        run at a DIFFERENT stripe count left shards this run's handles
        don't name, and a future run at that count must never resume
        from months-stale rows.  Merge products themselves are kept —
        with one stripe the shard paths ARE the output paths."""
        import glob as globlib

        keep = {
            self.output,
            f"{self.output}.prom",
            f"{self.output}.meta.json",
            f"{self.output}.stats.json",
        }
        doomed = set(
            globlib.glob(f"{globlib.escape(self.output)}.shard-*")
        )
        for handle in self.handles:
            for suffix in ("", ".meta.json", ".stats.json", ".prom",
                           ".log"):
                doomed.add(f"{handle.shard}{suffix}")
        for path in doomed:
            if path in keep:
                continue
            try:
                os.remove(path)
            except OSError:
                pass


def selftest(stream=None) -> int:
    """The 2-stripe CPU smoke for script/cibuild: a small synthetic
    corpus runs once single-striped and once 2-striped through REAL
    batch-detect child processes; the merged 2-stripe output must be
    bit-identical to the 1-stripe run, stats must sum to the manifest
    length, and the merged exposition must parse.  Returns 0/1."""
    import tempfile

    stream = stream if stream is not None else sys.stderr

    def say(msg: str) -> None:
        stream.write(f"stripes-selftest: {msg}\n")
        stream.flush()

    import re

    from licensee_tpu.corpus.license import License
    from licensee_tpu.obs import check_exposition

    bodies = [
        re.sub(r"\[(\w+)\]", "example", License.find(k).content or "")
        for k in ("mit", "isc", "bsd-3-clause")
    ]
    with tempfile.TemporaryDirectory(prefix="licensee-stripes-") as tmpdir:
        paths = []
        for i in range(42):
            p = os.path.join(tmpdir, f"LICENSE_{i}")
            with open(p, "w", encoding="utf-8") as f:
                f.write(
                    f"Copyright (c) {2000 + i} Example Author {i}\n\n"
                    + bodies[i % len(bodies)]
                )
            paths.append(p)
        manifest = os.path.join(tmpdir, "manifest.txt")
        with open(manifest, "w", encoding="utf-8") as f:
            f.write("\n".join(paths) + "\n")
        base_env = {**os.environ, "JAX_PLATFORMS": "cpu"}
        forward = ("--batch-size", "16", "--mesh", "none")
        outputs = {}
        for n in (1, 2):
            out = os.path.join(tmpdir, f"out-{n}.jsonl")
            runner = StripeRunner(
                manifest, out, n,
                forward_args=forward,
                base_env=base_env,
                on_event=say,
            )
            summary = runner.run()
            if summary["rows_written"] != len(paths):
                say(
                    f"FAIL: {n}-stripe run wrote "
                    f"{summary['rows_written']} rows, want {len(paths)}"
                )
                return 1
            if n > 1:
                stats = summary["stats"] or {}
                if stats.get("total") != len(paths):
                    say(f"FAIL: merged stats total {stats.get('total')}")
                    return 1
                prom = summary.get("prom")
                if prom:
                    with open(prom, encoding="utf-8") as f:
                        problems = check_exposition(f.read())
                    if problems:
                        say(f"FAIL: merged exposition: {problems[:3]}")
                        return 1
            with open(out, "rb") as f:
                outputs[n] = f.read()
        if outputs[1] != outputs[2]:
            say("FAIL: 2-stripe merged output != 1-stripe output")
            return 1
        rows = [
            json.loads(line)
            for line in outputs[2].decode().splitlines()
        ]
        seen_paths = [r["path"] for r in rows]
        if len(set(seen_paths)) != len(paths):
            say("FAIL: duplicate paths across shards")
            return 1
        matched = sum(1 for r in rows if r.get("key"))
        say(
            f"OK: 2-stripe merge bit-identical to 1-stripe "
            f"({len(rows)} rows, {matched} matched)"
        )
        # overlap smoke: the SAME manifest through the in-process
        # software pipeline at depth 1 (the synchronous dispatch ->
        # await -> write loop) and depth 3 — the async-submit /
        # FIFO-await contract must keep the JSONL bit-identical at
        # every pipeline depth, and both must match the striped runs
        from licensee_tpu.projects.batch_project import BatchProject

        overlap_out = {}
        for depth in (1, 3):
            out = os.path.join(tmpdir, f"out-depth{depth}.jsonl")
            project = BatchProject(
                paths, batch_size=16, mesh=None, pipeline_depth=depth
            )
            project.run(out, resume=False)
            with open(out, "rb") as f:
                overlap_out[depth] = f.read()
        if overlap_out[1] != overlap_out[3]:
            say("FAIL: depth-3 pipeline output != synchronous output")
            return 1
        if overlap_out[1] != outputs[1]:
            say("FAIL: pipelined output != striped-run output")
            return 1
        say("OK: overlap pipeline depth 1/3 bit-identical to sync")
        # tar-ingest smoke: the SAME blobs streamed out of a tarball
        # (members stored under the loose files' own absolute names,
        # manifest entry `archive.tar::*`) must produce bit-identical
        # per-blob JSONL to the loose-file manifest run, plus the
        # container-level verdict sidecar
        import io
        import tarfile

        tar_path = os.path.join(tmpdir, "archive.tar")
        with tarfile.open(tar_path, "w") as tf:
            for p in paths:
                with open(p, "rb") as f:
                    data = f.read()
                info = tarfile.TarInfo(name=p)
                info.size = len(data)
                tf.addfile(info, io.BytesIO(data))
        tar_out = os.path.join(tmpdir, "out-tar.jsonl")
        project = BatchProject(
            [f"{tar_path}::*"], batch_size=16, mesh=None
        )
        project.run(tar_out, resume=False)
        project.close()
        with open(tar_out, "rb") as f:
            tar_bytes = f.read()
        if tar_bytes != outputs[1]:
            say("FAIL: tar-ingest output != loose-file output")
            return 1
        with open(f"{tar_out}.containers.jsonl", encoding="utf-8") as f:
            containers = [json.loads(line) for line in f]
        if len(containers) != 1 or containers[0].get("files") != len(paths):
            say(f"FAIL: container verdict sidecar: {containers}")
            return 1
        say(
            "OK: tar-ingest bit-identical to loose files "
            f"(container license={containers[0].get('license')!r})"
        )
        # 2-stripe tar-ingest smoke: the SAME tarball striped by its
        # EXPANDED blob count across 2 real worker subprocesses — the
        # container's blobs span both stripes by construction — must
        # merge bit-identical to the 1-process tar run, and the merged
        # container sidecar must carry exactly one row per container
        tar_manifest = os.path.join(tmpdir, "tar_manifest.txt")
        with open(tar_manifest, "w", encoding="utf-8") as f:
            f.write(f"{tar_path}::*\n")
        tar2_out = os.path.join(tmpdir, "out-tar2.jsonl")
        runner = StripeRunner(
            tar_manifest, tar2_out, 2,
            forward_args=forward,
            base_env=base_env,
            on_event=say,
        )
        summary = runner.run()
        if runner.n_entries != len(paths):
            say(
                f"FAIL: expanded striping denominator "
                f"{runner.n_entries}, want {len(paths)}"
            )
            return 1
        if summary["rows_written"] != len(paths):
            say(
                f"FAIL: 2-stripe tar run wrote "
                f"{summary['rows_written']} rows, want {len(paths)}"
            )
            return 1
        with open(tar2_out, "rb") as f:
            if f.read() != tar_bytes:
                say("FAIL: 2-stripe tar merge != 1-process tar output")
                return 1
        with open(
            f"{tar2_out}.containers.jsonl", encoding="utf-8"
        ) as f:
            striped_containers = [json.loads(line) for line in f]
        if striped_containers != containers:
            say(
                "FAIL: striped container sidecar != 1-process sidecar: "
                f"{striped_containers}"
            )
            return 1
        say(
            "OK: 2-stripe tar-ingest bit-identical to 1-process "
            "(one container row, blobs spanned both stripes)"
        )
    return 0


def selftest_remote(stream=None) -> int:
    """The ``--selftest-remote`` drill for script/cibuild: a loopback
    HTTP host (stdlib, the PR 13 pattern) serves a tar and a zip of a
    synthetic corpus, and the remote ingest tier (ingest/remote.py)
    must scan them bit-identical to the same tarball read off local
    disk — through one scripted 503-then-recover fault on the ranged
    path and one mid-stream body truncation on the zip path, and
    through a REAL 2-stripe StripeRunner merge whose children fetch
    their spans over 127.0.0.1.  Returns 0/1."""
    import io
    import tarfile
    import zipfile

    stream = stream if stream is not None else sys.stderr

    def say(msg: str) -> None:
        stream.write(f"remote-selftest: {msg}\n")
        stream.flush()

    import re

    from licensee_tpu.corpus.license import License

    bodies = [
        re.sub(r"\[(\w+)\]", "example", License.find(k).content or "")
        for k in ("mit", "isc", "bsd-3-clause")
    ]
    members = {
        f"blob{i:03d}/LICENSE": (
            f"Copyright (c) {2000 + i} Example Author {i}\n\n"
            + bodies[i % len(bodies)]
        ).encode()
        for i in range(42)
    }
    tar_buf = io.BytesIO()
    with tarfile.open(fileobj=tar_buf, mode="w") as tf:
        for name, data in members.items():
            info = tarfile.TarInfo(name=name)
            info.size = len(data)
            tf.addfile(info, io.BytesIO(data))
    tar_bytes = tar_buf.getvalue()
    zip_buf = io.BytesIO()
    with zipfile.ZipFile(zip_buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for name, data in members.items():
            zf.writestr(name, data)
    zip_bytes = zip_buf.getvalue()

    # fast backoff for the scripted faults — in this process AND the
    # striped children (restored on exit: tests call this in-process)
    saved_backoff = os.environ.get("LICENSEE_TPU_REMOTE_BACKOFF_MS")
    os.environ["LICENSEE_TPU_REMOTE_BACKOFF_MS"] = "1"
    try:
        return _selftest_remote_body(stream, say, members,
                                     tar_bytes, zip_bytes)
    finally:
        if saved_backoff is None:
            os.environ.pop("LICENSEE_TPU_REMOTE_BACKOFF_MS", None)
        else:
            os.environ["LICENSEE_TPU_REMOTE_BACKOFF_MS"] = saved_backoff


def _selftest_remote_body(stream, say, members, tar_bytes,
                          zip_bytes) -> int:
    import tempfile

    from licensee_tpu.ingest.loopback import LoopbackBlobHost
    from licensee_tpu.projects.batch_project import BatchProject

    with tempfile.TemporaryDirectory(
        prefix="licensee-remote-"
    ) as tmpdir, LoopbackBlobHost(
        {"archive.tar": tar_bytes, "archive.zip": zip_bytes}
    ) as host:
        base_env = {
            **os.environ,
            "JAX_PLATFORMS": "cpu",
            "LICENSEE_TPU_REMOTE_BACKOFF_MS": "1",
        }

        # baseline: the tarball read off local disk
        tar_path = os.path.join(tmpdir, "archive.tar")
        with open(tar_path, "wb") as f:
            f.write(tar_bytes)
        base_out = os.path.join(tmpdir, "out-local.jsonl")
        project = BatchProject(
            [f"{tar_path}::*"], batch_size=16, mesh=None
        )
        project.run(base_out, resume=False)
        project.close()
        with open(base_out, "rb") as f:
            base = f.read()

        # remote tar THROUGH a 503-then-recover fault on the ranged
        # path: the retry budget must absorb it, bit-identically
        host.fail_next("archive.tar", 2, 503)
        rtar_out = os.path.join(tmpdir, "out-rtar.jsonl")
        project = BatchProject(
            [host.url("archive.tar") + "::*"], batch_size=16, mesh=None
        )
        project.run(rtar_out, resume=False)
        project.close()
        with open(rtar_out, "rb") as f:
            if f.read() != base:
                say("FAIL: remote tar output != local tar output")
                return 1
        retries = host.hits.get("archive.tar", 0)
        say(
            "OK: remote tar bit-identical to local through a scripted "
            f"503x2 ({retries} requests served)"
        )

        # remote zip THROUGH one mid-stream truncation (full
        # Content-Length promised, body torn): retried, bit-identical
        host.truncate_next("archive.zip", 64)
        rzip_out = os.path.join(tmpdir, "out-rzip.jsonl")
        project = BatchProject(
            [host.url("archive.zip") + "::*"], batch_size=16, mesh=None
        )
        project.run(rzip_out, resume=False)
        project.close()
        with open(rzip_out, "rb") as f:
            if f.read() != base:
                say("FAIL: remote zip output != local tar output")
                return 1
        say(
            "OK: remote zip bit-identical through one mid-stream "
            "truncation"
        )

        # the merge gate: the remote tarball striped by its EXPANDED
        # blob count across 2 real batch-detect children, each
        # fetching its own span over 127.0.0.1 — merged output must be
        # bit-identical to the local 1-process run, one container row
        manifest = os.path.join(tmpdir, "remote_manifest.txt")
        with open(manifest, "w", encoding="utf-8") as f:
            f.write(host.url("archive.tar") + "::*\n")
        striped_out = os.path.join(tmpdir, "out-striped.jsonl")
        runner = StripeRunner(
            manifest, striped_out, 2,
            forward_args=("--batch-size", "16", "--mesh", "none"),
            base_env=base_env,
            on_event=say,
        )
        summary = runner.run()
        if runner.n_entries != len(members):
            say(
                f"FAIL: expanded remote striping denominator "
                f"{runner.n_entries}, want {len(members)}"
            )
            return 1
        if summary["rows_written"] != len(members):
            say(
                f"FAIL: 2-stripe remote run wrote "
                f"{summary['rows_written']} rows, want {len(members)}"
            )
            return 1
        with open(striped_out, "rb") as f:
            if f.read() != base:
                say("FAIL: 2-stripe remote merge != local output")
                return 1
        with open(
            f"{striped_out}.containers.jsonl", encoding="utf-8"
        ) as f:
            containers = [json.loads(line) for line in f]
        if len(containers) != 1 or containers[0].get("files") != len(
            members
        ):
            say(f"FAIL: remote container sidecar: {containers}")
            return 1
        say(
            "OK: 2-stripe remote merge bit-identical to local "
            f"(container license={containers[0].get('license')!r})"
        )
    return 0


_AUTOSCALE_STUB = '''\
import json
import os
import sys
import time

from licensee_tpu.parallel.distributed import (
    manifest_stripe,
    shard_output_path,
)

output, index, count, n_entries, pfile, delay = sys.argv[1:7]
index, count, n_entries = int(index), int(count), int(n_entries)
delay = float(delay)
resume = "--no-resume" not in sys.argv[7:]
shard = shard_output_path(output, index, count)
lo, hi = manifest_stripe(n_entries, index, count)
data = b""
if resume:
    try:
        with open(shard, "rb") as f:
            data = f.read()
    except OSError:
        data = b""
    data = data[: data.rfind(b"\\n") + 1]  # torn-tail truncation
done = data.count(b"\\n")
epoch = 0
with open(shard, "wb") as f:
    f.write(data)
    f.flush()
    for j in range(lo + done, hi):
        epoch += 1
        try:
            with open(pfile, encoding="utf-8") as pf:
                busy = pf.read().strip() or "0"
        except OSError:
            busy = "0"
        tmp = f"{shard}.prom.tmp"
        with open(tmp, "w", encoding="utf-8") as mf:
            mf.write("# TYPE stripe_scrape_epoch gauge\\n")
            mf.write(f"stripe_scrape_epoch {epoch}\\n")
            mf.write("# TYPE pipeline_featurize_busy gauge\\n")
            mf.write(f"pipeline_featurize_busy {busy}\\n")
        os.replace(tmp, f"{shard}.prom")
        row = json.dumps({"path": f"f{j:05d}", "row": j})
        f.write(row.encode() + b"\\n")
        f.flush()
        time.sleep(delay)
'''


def selftest_autoscale(stream=None) -> int:
    """The ``--selftest-autoscale`` drill for script/cibuild: an
    elastic run over deterministic stub stripes whose ``--prom-file``
    heartbeat reports a featurize-lane pressure the drill controls.
    Pressure starts saturated (1.0) -> the runner must scale up; at the
    first up-rescale the drill flips pressure idle (0.05) -> the runner
    must scale back down; the merged output must be bit-identical to
    what a static single stripe writes, scale events must respect the
    cooldown, and the stripe count must respect the bounds.  Exercises
    the REAL drain/respawn/resume machinery — only the workers are
    stubs.  Returns 0/1."""
    import tempfile

    stream = stream if stream is not None else sys.stderr

    def say(msg: str) -> None:
        stream.write(f"autoscale-selftest: {msg}\n")
        stream.flush()

    from licensee_tpu.parallel.autoscale import AutoscaleConfig

    n = 150
    delay = 0.05
    cooldown_s = 0.6
    repo_root = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    with tempfile.TemporaryDirectory(
        prefix="licensee-autoscale-"
    ) as tmpdir:
        stub = os.path.join(tmpdir, "stub_worker.py")
        with open(stub, "w", encoding="utf-8") as f:
            f.write(_AUTOSCALE_STUB)
        manifest = os.path.join(tmpdir, "manifest.txt")
        with open(manifest, "w", encoding="utf-8") as f:
            f.write("\n".join(f"f{j:05d}" for j in range(n)) + "\n")
        pfile = os.path.join(tmpdir, "pressure.txt")
        with open(pfile, "w", encoding="utf-8") as f:
            f.write("1.0\n")
        out = os.path.join(tmpdir, "out.jsonl")
        pythonpath = os.environ.get("PYTHONPATH", "")
        env = {
            **os.environ,
            "PYTHONPATH": (
                f"{repo_root}{os.pathsep}{pythonpath}"
                if pythonpath else repo_root
            ),
        }

        def argv_for(i, count, resume=True):
            argv = [
                sys.executable, stub, out, str(i), str(count), str(n),
                pfile, str(delay),
            ]
            if not resume:
                argv.append("--no-resume")
            return argv

        def on_progress(kind, info):
            if kind == "rescale" and (
                info["to_stripes"] > info["from_stripes"]
            ):
                # saturation answered: the drill goes idle so the
                # decider must walk capacity back down
                tmp = f"{pfile}.tmp"
                with open(tmp, "w", encoding="utf-8") as f:
                    f.write("0.05\n")
                os.replace(tmp, pfile)

        runner = StripeRunner(
            manifest, out, 1,
            elastic=AutoscaleConfig(
                min_units=1, max_units=2,
                up_at=0.8, down_at=0.3,
                confirm_ticks=2, cooldown_s=cooldown_s,
                payoff_min=0.0,
            ),
            elastic_interval_s=0.25,
            elastic_stale_after_s=5.0,
            poll_interval_s=0.05,
            sigterm_timeout_s=5.0,
            argv_for=argv_for,
            env_for=lambda i, chips: env,
            on_event=say,
            on_progress=on_progress,
        )
        summary = runner.run()
        if summary["rows_written"] != n:
            say(f"FAIL: wrote {summary['rows_written']} rows, want {n}")
            return 1
        expected = b"".join(
            json.dumps({"path": f"f{j:05d}", "row": j}).encode() + b"\n"
            for j in range(n)
        )
        with open(out, "rb") as f:
            got = f.read()
        if got != expected:
            say("FAIL: elastic merged output != static 1-stripe bytes")
            return 1
        auto = summary.get("autoscale") or {}
        events = auto.get("events") or []
        ups = [e for e in events if e["to"] > e["from"]]
        downs = [e for e in events if e["to"] < e["from"]]
        if not ups:
            say(f"FAIL: saturated lane never scaled up: {events}")
            return 1
        if not downs:
            say(f"FAIL: idle lane never scaled down: {events}")
            return 1
        if any(e["to"] > 2 or e["to"] < 1 for e in events):
            say(f"FAIL: bounds violated: {events}")
            return 1
        for a, b in zip(events, events[1:]):
            if b["t"] - a["t"] < cooldown_s:
                say(f"FAIL: cooldown violated: {events}")
                return 1
        say(
            f"OK: scaled up then down ({len(ups)} up / {len(downs)} "
            f"down over {auto.get('scale_events')} rescales), merged "
            f"output bit-identical, cooldown {cooldown_s}s respected"
        )
    return 0
