"""Multi-host (DCN) bootstrap for batch classification.

The reference is single-process Ruby (SURVEY.md §2.7 — no communication
backend of any kind); this module is the TPU-native multi-host design.

Placement rationale: the scoring workload has no cross-blob communication
— the mesh's data axis emits zero collectives (each blob's best-match is
independent), and the only collective in the program is the model-axis
``psum`` of partial popcounts.  The scaling recipe (axes that communicate
stay on the fastest fabric) therefore maps:

* **model axis** → within a host's local chips, riding ICI;
* **data axis**  → across hosts, as *manifest striping*: each process
  classifies a contiguous stripe of the global manifest on its local mesh
  and writes its own JSONL shard.  This is mathematically identical to a
  global-mesh data axis (no collectives to lose) and keeps every host's
  failure/resume domain independent — shard files resume per-host.

DCN carries only the ``jax.distributed`` bootstrap handshake.

Environment contract (all three must be set to opt in):

* ``LICENSEE_TPU_COORDINATOR``   — ``host:port`` of process 0
* ``LICENSEE_TPU_NUM_PROCESSES`` — world size
* ``LICENSEE_TPU_PROCESS_ID``    — this process's rank

On TPU pod slices where the runtime provides cluster metadata,
``jax.distributed.initialize()`` auto-detects instead; call
``maybe_initialize`` with ``auto=True`` env ``LICENSEE_TPU_DISTRIBUTED=auto``.
"""

from __future__ import annotations

import os

_initialized = False


def maybe_initialize(env=None) -> tuple[int, int]:
    """Initialize `jax.distributed` from the environment (idempotent).

    Returns ``(process_index, process_count)`` — ``(0, 1)`` when no
    multi-host environment is configured."""
    global _initialized
    env = os.environ if env is None else env

    coord = env.get("LICENSEE_TPU_COORDINATOR")
    auto = env.get("LICENSEE_TPU_DISTRIBUTED") == "auto"
    if not coord and not auto:
        return 0, 1

    import jax

    if not _initialized:
        if coord:
            jax.distributed.initialize(
                coordinator_address=coord,
                num_processes=int(env["LICENSEE_TPU_NUM_PROCESSES"]),
                process_id=int(env["LICENSEE_TPU_PROCESS_ID"]),
            )
        else:
            jax.distributed.initialize()
        _initialized = True
    return jax.process_index(), jax.process_count()


def manifest_stripe(n: int, process_index: int, process_count: int) -> tuple[int, int]:
    """[lo, hi) bounds of this process's contiguous manifest stripe.

    Contiguous (not strided) so each shard's resume invariant — output
    line count == completed prefix of the stripe — holds independently;
    the remainder spreads one extra item over the first shards."""
    if not 0 <= process_index < process_count:
        raise ValueError(
            f"process_index {process_index} out of range for "
            f"process_count {process_count}"
        )
    base, rem = divmod(n, process_count)
    lo = process_index * base + min(process_index, rem)
    hi = lo + base + (1 if process_index < rem else 0)
    return lo, hi


def shard_output_path(output: str, process_index: int, process_count: int) -> str:
    """Per-host JSONL shard path (process 0 of 1 keeps the plain path)."""
    if process_count <= 1:
        return output
    return f"{output}.shard-{process_index:05d}-of-{process_count:05d}"
