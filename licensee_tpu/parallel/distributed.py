"""Multi-host (DCN) bootstrap for batch classification.

The reference is single-process Ruby (SURVEY.md §2.7 — no communication
backend of any kind); this module is the TPU-native multi-host design.

Placement rationale: the scoring workload has no cross-blob communication
— the mesh's data axis emits zero collectives (each blob's best-match is
independent), and the only collective in the program is the model-axis
``psum`` of partial popcounts.  The scaling recipe (axes that communicate
stay on the fastest fabric) therefore maps:

* **model axis** → within a host's local chips, riding ICI;
* **data axis**  → across hosts, as *manifest striping*: each process
  classifies a contiguous stripe of the global manifest on its local mesh
  and writes its own JSONL shard.  This is mathematically identical to a
  global-mesh data axis (no collectives to lose) and keeps every host's
  failure/resume domain independent — shard files resume per-host.

DCN carries only the ``jax.distributed`` bootstrap handshake.

Environment contract (all three must be set to opt in):

* ``LICENSEE_TPU_COORDINATOR``   — ``host:port`` of process 0
* ``LICENSEE_TPU_NUM_PROCESSES`` — world size
* ``LICENSEE_TPU_PROCESS_ID``    — this process's rank

On TPU pod slices where the runtime provides cluster metadata,
``jax.distributed.initialize()`` auto-detects instead; call
``maybe_initialize`` with ``auto=True`` env ``LICENSEE_TPU_DISTRIBUTED=auto``.

Co-located processes (one host, chips split per process) additionally set

* ``LICENSEE_TPU_VISIBLE_CHIPS`` — comma list of this process's chip ids

which ``apply_visible_chips`` translates, BEFORE the backend initializes,
into the PJRT TPU visibility var (``TPU_VISIBLE_DEVICES``) and — for the
CPU rehearsal of the same launch — a matching virtual host-device count.
This is the v5e-8 north-star shape (the scaling-model ADR in
projects/batch_project.py): >=4 manifest-striped processes sharing the
host, each with its own chip subset and its own local data mesh.
"""

from __future__ import annotations

import os

_initialized = False
_chips_applied: list[str] | None = None


def apply_visible_chips(env=None) -> list[str] | None:
    """Restrict THIS process to its chip subset (idempotent).

    Reads ``LICENSEE_TPU_VISIBLE_CHIPS`` (e.g. ``"4,5"``) and exports the
    visibility the runtime actually honors:

    * ``TPU_VISIBLE_DEVICES`` for the PJRT TPU plugin (real chips);
    * ``--xla_force_host_platform_device_count=<n>`` so a CPU run of the
      same launch line rehearses an n-device local mesh per process.

    Must run before the jax backend initializes — visibility cannot
    change after; raises RuntimeError if a backend is already live.
    Returns the chip list, or None when the env var is unset."""
    global _chips_applied
    env = os.environ if env is None else env
    is_process_env = env is os.environ
    spec = env.get("LICENSEE_TPU_VISIBLE_CHIPS")
    if spec is None:
        return None
    chips = [c.strip() for c in spec.split(",") if c.strip()]
    if not chips:
        raise ValueError(
            f"LICENSEE_TPU_VISIBLE_CHIPS={spec!r}: no chip ids"
        )
    # the applied-state latch tracks the PROCESS environment only: a
    # dict-env dry run must neither consume the latch (a later real
    # apply would silently export nothing) nor be blocked by it
    if is_process_env and _chips_applied is not None:
        if chips != _chips_applied:
            raise RuntimeError(
                f"LICENSEE_TPU_VISIBLE_CHIPS changed after apply: "
                f"{_chips_applied} -> {chips}"
            )
        return chips
    import sys

    # the live-backend guard protects THIS process's visibility; a
    # dict env is a dry run or a CHILD's environment (the fleet
    # supervisor derives worker envs from a process whose own backend
    # is legitimately live) and cannot change this process's devices
    if is_process_env and "jax" in sys.modules:
        try:
            from jax._src import xla_bridge

            live = bool(xla_bridge._backends)
        except Exception:  # noqa: BLE001 — private API may move
            live = False
        if live:
            raise RuntimeError(
                "LICENSEE_TPU_VISIBLE_CHIPS set but the jax backend is "
                "already initialized; set it before the first device use"
            )
    want = ",".join(chips)
    # read AND write through the SAME mapping the chip spec came from: a
    # caller-supplied dict env must be validated against itself and must
    # never leak writes into os.environ (ADVICE r5 — the old code read
    # the spec from `env` but conflict-checked and mutated os.environ,
    # so a dict-env dry run could both miss a real conflict in `env` and
    # corrupt the live process environment)
    have = env.get("TPU_VISIBLE_DEVICES")
    if have is not None and have != want:
        # refuse loudly: a stale/wrapper-set value silently winning over
        # the requested subset would leave co-located ranks contending
        # for the same chips with no diagnostic
        raise RuntimeError(
            f"TPU_VISIBLE_DEVICES={have!r} conflicts with "
            f"LICENSEE_TPU_VISIBLE_CHIPS={spec!r}; unset one"
        )
    env["TPU_VISIBLE_DEVICES"] = want
    # CPU rehearsal: LICENSEE_TPU_VISIBLE_CHIPS is authoritative for the
    # virtual local-device count — rewrite a leaked count (test harnesses
    # commonly export one) instead of silently keeping it
    import re

    flag = f"--xla_force_host_platform_device_count={len(chips)}"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" in flags:
        flags = re.sub(
            r"--xla_force_host_platform_device_count=\d+", flag, flags
        )
        env["XLA_FLAGS"] = flags
    else:
        env["XLA_FLAGS"] = (flags + " " + flag).strip()
    _export_colocated_tpu_vars(env, chips)
    if is_process_env:
        _chips_applied = chips
    return chips


def _export_colocated_tpu_vars(env, chips: list[str]) -> None:
    """Best-effort libtpu co-location vars for N processes sharing one
    REAL TPU host.

    ``TPU_VISIBLE_DEVICES`` alone is not enough for libtpu to split one
    host's chips across processes — it also wants per-process ports, the
    full address list, a task id, and the topology bounds.  When the
    multi-process contract is present alongside the chip split (which
    implies co-location on this host), derive what is derivable and pass
    the topology bounds through from ``LICENSEE_TPU_PROCESS_BOUNDS`` /
    ``LICENSEE_TPU_CHIPS_PER_PROCESS_BOUNDS`` (topology-dependent; the
    v5e-8 4x2-chip split is documented in the README).  setdefault
    everywhere: an operator who exports the TPU_* vars directly wins.
    CI exercises the CPU rehearsal of this launch; the real-host var set
    is exported on the documented contract but this repo's environment
    (one tunneled chip) cannot validate libtpu's acceptance of it."""
    n = env.get("LICENSEE_TPU_NUM_PROCESSES")
    rank = env.get("LICENSEE_TPU_PROCESS_ID")
    if not n or rank is None:
        return
    # chips-split + a REMOTE coordinator = a hybrid multi-host layout
    # this derivation cannot describe (the address list below would name
    # every global rank as localhost); in that layout the operator
    # exports the TPU_* vars per host directly
    coord = env.get("LICENSEE_TPU_COORDINATOR", "")
    coord_host = coord.rsplit(":", 1)[0] if coord else ""
    if coord_host not in ("", "localhost", "127.0.0.1", "::1"):
        return
    n_i, rank_i = int(n), int(rank)
    base = int(env.get("LICENSEE_TPU_PROCESS_PORT_BASE", "8476"))
    # write through the caller's mapping, like apply_visible_chips: in
    # production env IS os.environ; a dict env stays self-contained
    env.setdefault("TPU_PROCESS_PORT", str(base + rank_i))
    env.setdefault(
        "TPU_PROCESS_ADDRESSES",
        ",".join(f"localhost:{base + i}" for i in range(n_i)),
    )
    env.setdefault("CLOUD_TPU_TASK_ID", str(rank))
    for src, dst in (
        ("LICENSEE_TPU_PROCESS_BOUNDS", "TPU_PROCESS_BOUNDS"),
        (
            "LICENSEE_TPU_CHIPS_PER_PROCESS_BOUNDS",
            "TPU_CHIPS_PER_PROCESS_BOUNDS",
        ),
    ):
        if env.get(src):
            env.setdefault(dst, env[src])


def maybe_initialize(env=None) -> tuple[int, int]:
    """Initialize `jax.distributed` from the environment (idempotent).

    Returns ``(process_index, process_count)`` — ``(0, 1)`` when no
    multi-host environment is configured."""
    global _initialized
    env = os.environ if env is None else env

    if not _initialized:
        apply_visible_chips(env)

    coord = env.get("LICENSEE_TPU_COORDINATOR")
    auto = env.get("LICENSEE_TPU_DISTRIBUTED") == "auto"
    if not coord and not auto:
        return 0, 1

    import jax

    if not _initialized:
        if coord:
            jax.distributed.initialize(
                coordinator_address=coord,
                num_processes=int(env["LICENSEE_TPU_NUM_PROCESSES"]),
                process_id=int(env["LICENSEE_TPU_PROCESS_ID"]),
            )
        else:
            jax.distributed.initialize()
        _initialized = True
    return jax.process_index(), jax.process_count()


def chips_for_worker(
    worker_index: int, chips_per_worker: int
) -> list[str]:
    """The chip-id subset for co-located worker ``worker_index`` when
    every worker owns ``chips_per_worker`` chips: the contiguous range
    ``[i*K, (i+1)*K)``, as the string ids
    ``LICENSEE_TPU_VISIBLE_CHIPS`` wants.

    One derivation for both co-located launch shapes: the offline
    manifest-striped ranks (the README launch recipe) and the serving
    fleet's supervisor (fleet/supervisor.py), which exports the result
    into each worker's child environment and translates it with
    ``apply_visible_chips`` over that same dict."""
    if worker_index < 0:
        raise ValueError(f"worker_index must be >= 0, got {worker_index!r}")
    if chips_per_worker < 1:
        raise ValueError(
            f"chips_per_worker must be >= 1, got {chips_per_worker!r}"
        )
    lo = worker_index * chips_per_worker
    return [str(c) for c in range(lo, lo + chips_per_worker)]


def count_manifest_entries(manifest: str) -> int:
    """Non-blank line count — the striping denominator for LOOSE
    manifests.

    Both sides of the shard row-count contract ride this: the stripe
    runner (parallel/stripes.py) sizes stripe spans from it, and
    ``BatchProject.from_manifest_file`` counts with it before
    collecting a span — so what counts as "an entry" can never drift
    between supervisor and worker.  Container manifests ('::' forms)
    stripe by their EXPANDED blob count instead: both sides run the
    same metadata-only enumeration (ingest/sources.py
    ``expanded_layout`` / ``ManifestExpansion.restrict``), so the
    no-drift property holds there by construction too."""
    n = 0
    with open(manifest, encoding="utf-8") as f:
        for line in f:
            if line.strip():
                n += 1
    return n


def manifest_stripe(n: int, process_index: int, process_count: int) -> tuple[int, int]:
    """[lo, hi) bounds of this process's contiguous manifest stripe.

    Contiguous (not strided) so each shard's resume invariant — output
    line count == completed prefix of the stripe — holds independently;
    the remainder spreads one extra item over the first shards."""
    if not 0 <= process_index < process_count:
        raise ValueError(
            f"process_index {process_index} out of range for "
            f"process_count {process_count}"
        )
    base, rem = divmod(n, process_count)
    lo = process_index * base + min(process_index, rem)
    hi = lo + base + (1 if process_index < rem else 0)
    return lo, hi


def shard_output_path(output: str, process_index: int, process_count: int) -> str:
    """Per-host JSONL shard path (process 0 of 1 keeps the plain path)."""
    if process_count <= 1:
        return output
    return f"{output}.shard-{process_index:05d}-of-{process_count:05d}"
