"""Self-serve corpus onboarding: upload -> validate -> roll -> persist.

The edge's ``POST /corpus`` verb lands here (on an ops thread, never
the router's event loop).  The pipeline:

1. **Stage** the uploaded artifact bytes under a content-addressed
   name (sha256 prefix) so a re-upload of the same bytes is idempotent
   and a half-written file can never be rolled.
2. **Validate** through the corpus gate
   (:func:`~licensee_tpu.corpus.artifact.resolve_corpus` by default —
   the same fail-closed fingerprint-checked load the PR 7 blue/green
   reload runs), yielding the artifact's fingerprint.
3. **Journal** a ``roll_start`` record (fsync'd), then roll the
   tenant's pool via the per-pool ``reload_fleet`` — other pools keep
   serving.  A crash between start and done leaves a dangling journal
   record that :meth:`CorpusOnboarder.recover` replays at next boot.
4. **Persist** the tenant's new corpus binding in the registry and
   swap the router's fingerprint routes, so tagged traffic follows
   the roll and response verification expects the new fingerprint.

Failures raise :class:`OnboardError` with a closed set of codes; the
edge owns the HTTP mapping (403/400/409/500) and mints the wire error
bodies — no protocol strings originate here.
"""

from __future__ import annotations

import hashlib
import os

from licensee_tpu.corpus.artifact import ArtifactError, resolve_corpus


class OnboardError(Exception):
    """A typed onboarding failure.  ``code`` is one of
    ``unknown_tenant`` / ``corpus_invalid`` /
    ``fleet_reload_in_progress`` / ``reload_failed``; the edge maps
    codes to HTTP statuses and mints the response body."""

    def __init__(self, code: str, detail: str):
        self.code = code
        self.detail = detail
        super().__init__(f"{code}: {detail}")


def _default_validator(path: str) -> str:
    _corpus, fingerprint, _manifest = resolve_corpus(path)
    return fingerprint


class CorpusOnboarder:
    """The tenant-facing onboarding pipeline over one fleet.

    ``validator(path) -> fingerprint`` and
    ``source_for(path, fingerprint) -> corpus source`` are injectable
    so the stub selftests can drill the full journal/roll/route flow
    without building a real corpus (a stub worker's "corpus" is just
    the fingerprint string its reload op installs).
    """

    def __init__(
        self, registry, pools, router, *, staging_dir: str,
        validator=None, source_for=None, reload_kwargs: dict | None = None,
    ):
        self.registry = registry
        self.pools = pools
        self.router = router
        self.staging_dir = staging_dir
        self._validator = validator or _default_validator
        self._source_for = source_for or (lambda path, fp: path)
        self._reload_kwargs = dict(reload_kwargs or {})
        os.makedirs(staging_dir, exist_ok=True)

    # -- edge auth glue --

    def tenant_for(self, client: str | None):
        """The edge's authenticated client label -> Tenant (the edge
        token map comes from ``registry.tokens()``, so the label IS
        the tenant name); None for unauthenticated or unbound."""
        if not client:
            return None
        return self.registry.get(client)

    def pool_for_client(self, client: str | None) -> str | None:
        tenant = self.tenant_for(client)
        return tenant.pool if tenant is not None else None

    # -- route table sync --

    def sync_routes(self, fingerprints: dict | None = None) -> None:
        """Seed the router's corpus-tag routes from the registry:
        every tenant name and pool name routes to its pool, plus any
        known fingerprint (``fingerprints`` maps pool -> fp for
        topologies where the caller already knows what each pool
        serves, e.g. the selftests and boot-time CLI)."""
        for tenant in self.registry.tenants().values():
            self.router.set_corpus_route(tenant.name, tenant.pool)
            self.router.set_corpus_route(tenant.pool, tenant.pool)
            fp = (fingerprints or {}).get(tenant.pool) or tenant.fingerprint
            if fp:
                self._install_fingerprint(tenant.pool, fp, old=None)

    def _install_fingerprint(
        self, pool: str, fp: str, *, old: str | None
    ) -> None:
        if old and old != fp:
            self.router.drop_corpus_route(old)
            self.router.drop_corpus_route(old[:12])
        self.router.set_corpus_route(fp, pool)
        if len(fp) > 12:
            self.router.set_corpus_route(fp[:12], pool)
        self.router.set_pool_fingerprint(pool, fp)

    # -- the onboarding pipeline --

    def stage(self, data: bytes, name: str | None = None) -> str:
        digest = hashlib.sha256(data).hexdigest()[:16]
        base = os.path.basename(name) if name else "corpus.npz"
        path = os.path.join(self.staging_dir, f"{digest}-{base}")
        tmp = path + ".tmp"
        with open(tmp, "wb") as fh:
            fh.write(data)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
        return path

    def upload(self, tenant_name: str, data: bytes,
               name: str | None = None) -> dict:
        """The whole pipeline for one authenticated upload.  Runs on
        an edge ops thread; the only event-loop interaction is through
        ``reload_fleet``'s own oneshot connections."""
        tenant = self.registry.get(tenant_name)
        if tenant is None:
            raise OnboardError(
                "unknown_tenant", f"no tenant named {tenant_name!r}"
            )
        staged = self.stage(data, name)
        try:
            fingerprint = self._validator(staged)
        except (ArtifactError, OSError, ValueError) as exc:
            raise OnboardError("corpus_invalid", str(exc))
        if not isinstance(fingerprint, str) or not fingerprint:
            raise OnboardError(
                "corpus_invalid", "validator yielded no fingerprint"
            )
        source = self._source_for(staged, fingerprint)
        return self._roll(tenant, source, fingerprint, staged=staged)

    def _roll(self, tenant, source: str, fingerprint: str, *,
              staged: str | None = None) -> dict:
        old_fp = tenant.fingerprint
        self.registry.record_roll(
            "roll_start", tenant.name, corpus=source,
            fingerprint=fingerprint, staged=staged,
        )
        # disarm the router's per-pool fingerprint fence for the roll
        # window: a mid-roll pool serves old AND new fingerprints, and
        # either is the right answer until the swap completes
        self.router.set_pool_fingerprint(tenant.pool, None)
        try:
            result = self.pools.reload_fleet(
                source, pool=tenant.pool, **self._reload_kwargs
            )
        except Exception as exc:
            self.router.set_pool_fingerprint(tenant.pool, old_fp)
            self.registry.record_roll(
                "roll_failed", tenant.name, reason=str(exc)
            )
            raise
        if not result.get("ok"):
            # a refused roll leaves the pool on (or rolled back to)
            # its previous corpus: re-arm the fence where it was
            self.router.set_pool_fingerprint(tenant.pool, old_fp)
            reason = str(result.get("error") or "reload failed")
            self.registry.record_roll(
                "roll_failed", tenant.name, reason=reason
            )
            if reason.startswith("fleet_reload_in_progress"):
                raise OnboardError("fleet_reload_in_progress", reason)
            raise OnboardError("reload_failed", reason)
        self.registry.record_roll(
            "roll_done", tenant.name, fingerprint=fingerprint
        )
        self.registry.update_corpus(tenant.name, source, fingerprint)
        self._install_fingerprint(tenant.pool, fingerprint, old=old_fp)
        return {
            "tenant": tenant.name,
            "pool": tenant.pool,
            "fingerprint": fingerprint,
            "corpus": source,
            "workers": sorted(result.get("workers") or ()),
        }

    def recover(self) -> list[dict]:
        """Replay rolls a crash interrupted: every journaled
        ``roll_start`` without a terminal record is re-validated and
        re-rolled (reload is idempotent — a pool already on the target
        fingerprint rolls to itself)."""
        results = []
        for row in self.registry.pending_rolls():
            tenant = self.registry.get(row.get("tenant") or "")
            source = row.get("corpus")
            fingerprint = row.get("fingerprint")
            if tenant is None or not isinstance(source, str):
                continue
            if not isinstance(fingerprint, str) or not fingerprint:
                continue
            try:
                results.append(
                    self._roll(tenant, source, fingerprint,
                               staged=row.get("staged"))
                )
            except OnboardError as exc:
                results.append({
                    "tenant": tenant.name, "recovered": False,
                    "reason": str(exc),
                })
        return results
