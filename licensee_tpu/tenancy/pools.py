"""Heterogeneous worker pools: one supervisor per pool, one facade.

The fleet tier was built single-corpus: ONE supervisor owns every
worker and ``reload_fleet`` rolls them all.  Multi-tenant serving
needs N workers on corpus A next to M workers on corpus B, each pool
independently health-probed, restarted, and rolled — without teaching
the router a second supervision protocol.  :class:`TenantPools` is
that shim: it owns one :class:`~licensee_tpu.fleet.supervisor.
Supervisor` per pool and re-exports the exact supervisor surface the
router consumes (``dispatchable``/``status``/``host_health``/
``reload_fleet``), routing each call to the pool that owns the named
worker.  Worker names are globally unique across pools (the router's
backend table is flat), so the mapping is a plain dict.
"""

from __future__ import annotations


class TenantPools:
    """A supervisor-of-supervisors: the router sees one ``supervisor``
    object; each pool keeps its own probe thread, restart backoff, and
    blue/green reload lock, so rolling pool A cannot stall or restart
    pool B's workers."""

    def __init__(self, pools: dict, *, default_pool: str | None = None):
        if not pools:
            raise ValueError("TenantPools needs at least one pool")
        self.pools = dict(pools)
        self.default_pool = (
            default_pool if default_pool is not None
            else sorted(self.pools)[0]
        )
        if self.default_pool not in self.pools:
            raise ValueError(
                f"default pool {self.default_pool!r} is not one of "
                f"{sorted(self.pools)}"
            )
        self._owner: dict[str, str] = {}
        for pool_name, sup in self.pools.items():
            for worker in sup.workers:
                other = self._owner.get(worker)
                if other is not None:
                    raise ValueError(
                        f"worker name {worker!r} appears in pools "
                        f"{other!r} and {pool_name!r} (names must be "
                        "fleet-unique: the router's backend table is "
                        "flat)"
                    )
                self._owner[worker] = pool_name
        self._router = None

    # the Router constructor does ``supervisor.router = self``; fan the
    # handle out so each pool's drain path can read per-worker
    # outstanding counts from the shared router
    @property
    def router(self):
        return self._router

    @router.setter
    def router(self, value) -> None:
        self._router = value
        for sup in self.pools.values():
            sup.router = value

    @property
    def workers(self) -> dict[str, str]:
        """Merged worker name -> socket target across every pool (the
        Router's ``backends`` ctor argument)."""
        merged: dict[str, str] = {}
        for sup in self.pools.values():
            for name, handle in sup.workers.items():
                merged[name] = handle.socket_path
        return merged

    def handles(self) -> dict:
        """Merged worker name -> live WorkerHandle across every pool
        (the selftests read pids and restart counts here)."""
        merged: dict = {}
        for sup in self.pools.values():
            merged.update(sup.workers)
        return merged

    def pool_of(self, name: str) -> str | None:
        return self._owner.get(name)

    def worker_pools(self) -> dict[str, str]:
        """worker name -> pool name (the router's routing table seed)."""
        return dict(self._owner)

    # -- lifecycle --

    def start(self) -> None:
        for sup in self.pools.values():
            sup.start()

    def stop(self) -> None:
        for sup in self.pools.values():
            sup.stop()

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    def wait_healthy(self, timeout_s: float = 30.0) -> bool:
        import time

        deadline = time.perf_counter() + timeout_s
        for sup in self.pools.values():
            remaining = deadline - time.perf_counter()
            if remaining <= 0 or not sup.wait_healthy(remaining):
                return False
        return True

    # -- the supervisor surface the router consumes --

    def dispatchable(self, name: str) -> bool:
        pool = self._owner.get(name)
        if pool is None:
            return True
        return self.pools[pool].dispatchable(name)

    def probe(self, name: str):
        pool = self._owner.get(name)
        if pool is None:
            return None
        return self.pools[pool].probe(name)

    def status(self) -> dict:
        merged: dict = {}
        for pool_name, sup in sorted(self.pools.items()):
            for worker, row in sup.status().items():
                if isinstance(row, dict):
                    row = dict(row)
                    row["pool"] = pool_name
                merged[worker] = row
        return merged

    def host_health(self) -> dict:
        totals = {
            "workers": 0, "healthy": 0, "dispatchable": 0,
            "restarts": 0, "serving": True,
        }
        per_pool: dict = {}
        for pool_name, sup in sorted(self.pools.items()):
            health = sup.host_health()
            per_pool[pool_name] = health
            for key in ("workers", "healthy", "dispatchable", "restarts"):
                totals[key] += health.get(key, 0)
            totals["serving"] = totals["serving"] and bool(
                health.get("serving", False)
            )
        totals["pools"] = per_pool
        return totals

    def drain(self, name: str, **kwargs):
        pool = self._owner.get(name)
        if pool is None:
            raise KeyError(name)
        return self.pools[pool].drain(name, **kwargs)

    def reload_fleet(self, corpus: str, *, pool: str | None = None,
                     **kwargs) -> dict:
        """Roll ONE pool onto a new corpus; other pools keep serving
        untouched.  ``pool=None`` rolls the default pool (the
        single-tenant ``{"op": "reload"}`` verb keeps working)."""
        target = pool if pool is not None else self.default_pool
        sup = self.pools.get(target)
        if sup is None:
            return {
                "ok": False,
                "error": f"unknown_pool: no pool named {target!r}",
                "pools": sorted(self.pools),
            }
        result = sup.reload_fleet(corpus, **kwargs)
        if isinstance(result, dict):
            result.setdefault("pool", target)
        return result
